"""localai-tpu: a TPU-native, OpenAI-compatible model serving framework.

A ground-up re-design of the capabilities of LocalAI (reference:
/root/reference, an OpenAI-compatible REST server routing every AI
capability over a gRPC contract to per-model backend processes) for TPU
hardware: the compute path is JAX/XLA/Pallas with continuous batching and
mesh-sharded (tp/dp/sp) inference; the serving shape — HTTP core that never
links an inference engine, per-model backend processes behind a gRPC
contract — is preserved because it is a good shape, but every layer below
the contract is TPU-first rather than a port.

Layer map (mirrors reference SURVEY.md section 1, re-imagined):
  api/        OpenAI-compatible HTTP server (aiohttp)       [ref: core/http]
  config/     app + per-model YAML configuration            [ref: core/config]
  backend/    the gRPC backend contract + client            [ref: backend/backend.proto, pkg/grpc]
  modelmgr/   model lifecycle: spawn/health/watchdog        [ref: pkg/model]
  engine/     TPU serving engine: continuous batching,
              paged KV, sampling, streaming detok           [ref: backend/cpp/llama/grpc-server.cpp]
  models/     JAX model definitions (llama, bert, ...)      [ref: llama.cpp / python backends]
  ops/        pallas kernels + jnp fallbacks
  parallel/   mesh, shardings, ring attention, multi-host   [ref: core/p2p -- replaced by XLA collectives]
  functions/  tools -> grammar-constrained decoding         [ref: pkg/functions]
  templates/  chat prompt templating                        [ref: pkg/templates]
  gallery/    model acquisition                             [ref: core/gallery, pkg/downloader]
  stores/     vector store                                  [ref: backend/go/stores]
  services/   metrics, monitor, job queues                  [ref: core/services]
"""

__version__ = "0.1.0"
