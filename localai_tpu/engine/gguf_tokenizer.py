"""Tokenizer reconstructed from GGUF-embedded vocab metadata.

The reference gets tokenization for free from llama.cpp, which reads the
same ``tokenizer.ggml.*`` keys this module consumes (GGUF spec; reference
ingestion path: backend/cpp/llama/grpc-server.cpp tokenize →
llama_tokenize). Re-implemented TPU-side so a pulled ``ollama://`` GGUF
serves without any sidecar HF tokenizer files:

  * ``llama`` model: SentencePiece unigram — Viterbi segmentation over
    piece scores with byte-fallback (<0xXX>) for uncovered bytes.
  * ``gpt2`` model: byte-level BPE — UTF-8 bytes mapped through the GPT-2
    printable-byte table, then greedy lowest-rank merges.

The surface mirrors the small subset of the HF tokenizer API the serving
stack uses (encode / decode / convert_ids_to_tokens / eos_token_id).
"""

from __future__ import annotations

import functools
import re
from typing import Optional

# GPT-2's pre-tokenization split (contractions / words / numbers /
# punctuation runs / whitespace) — BPE merges never cross these
# boundaries, which also keeps the merge loop O(word), not O(text)
_BPE_SPLIT = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?[^\s\w]+|\s+(?!\S)|\s+")


def _gpt2_byte_table() -> dict[int, str]:
    """GPT-2's bijective byte -> printable-unicode map."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


_BYTE_TO_CHAR = _gpt2_byte_table()
_CHAR_TO_BYTE = {c: b for b, c in _BYTE_TO_CHAR.items()}


class GGUFTokenizer:
    """Built from GGUFFile.metadata (tokenizer.ggml.*)."""

    # token type enum (llama.cpp llama_token_type)
    NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6

    def __init__(self, metadata: dict):
        md = metadata
        self.model = md.get("tokenizer.ggml.model", "llama")
        self.tokens: list[str] = md["tokenizer.ggml.tokens"]
        self.scores: list[float] = md.get("tokenizer.ggml.scores") or []
        self.token_types: list[int] = md.get("tokenizer.ggml.token_type") or []
        self.merges: list[str] = md.get("tokenizer.ggml.merges") or []
        self.bos_token_id: Optional[int] = md.get("tokenizer.ggml.bos_token_id")
        self.eos_token_id: Optional[int] = md.get("tokenizer.ggml.eos_token_id")
        self.unk_token_id: Optional[int] = md.get("tokenizer.ggml.unknown_token_id")
        self.add_bos = bool(md.get("tokenizer.ggml.add_bos_token",
                                   self.model == "llama"))
        self.vocab: dict[str, int] = {t: i for i, t in enumerate(self.tokens)}
        self.vocab_size = len(self.tokens)
        if self.model == "gpt2":
            self.merge_ranks = {tuple(m.split(" ", 1)): r
                                for r, m in enumerate(self.merges)}
        # byte-fallback ids for the llama model: "<0xNN>" pieces
        self.byte_ids: dict[int, int] = {}
        for i, t in enumerate(self.tokens):
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                try:
                    self.byte_ids[int(t[3:5], 16)] = i
                except ValueError:
                    pass
        self._specials = {
            i for i, tt in enumerate(self.token_types)
            if tt in (self.CONTROL, self.UNKNOWN)
        }

    # ---- encode ----

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        if self.model == "gpt2":
            ids = self._encode_bpe(text)
        else:
            ids = self._encode_spm(text)
        if add_special_tokens and self.add_bos and self.bos_token_id is not None:
            ids = [self.bos_token_id] + ids
        return ids

    def _encode_spm(self, text: str) -> list[int]:
        """Unigram Viterbi over piece scores (SentencePiece semantics:
        spaces become ▁; leading space prepended)."""
        s = "▁" + text.replace(" ", "▁")
        n = len(s)
        NEG = -1e30
        best = [NEG] * (n + 1)
        back: list[Optional[tuple]] = [None] * (n + 1)
        best[0] = 0.0
        max_piece = 32
        for i in range(n):
            if best[i] <= NEG:
                continue
            for j in range(i + 1, min(i + max_piece, n) + 1):
                piece = s[i:j]
                tid = self.vocab.get(piece)
                if tid is not None and tid not in self._specials:
                    sc = self.scores[tid] if tid < len(self.scores) else 0.0
                    cand = best[i] + sc
                    if cand > best[j]:
                        best[j] = cand
                        back[j] = (i, tid)
            # byte fallback: always available, heavily penalized
            b = s[i].encode("utf-8")
            j = i + 1
            cand = best[i] + len(b) * -100.0
            if cand > best[j]:
                best[j] = cand
                back[j] = (i, ("bytes", b))
        ids: list[int] = []
        j = n
        segs = []
        while j > 0:
            i, tok = back[j]
            segs.append(tok)
            j = i
        for tok in reversed(segs):
            if isinstance(tok, tuple):
                for byte in tok[1]:
                    ids.append(self.byte_ids.get(byte, self.unk_token_id or 0))
            else:
                ids.append(tok)
        return ids

    def _encode_bpe(self, text: str) -> list[int]:
        out: list[int] = []
        for word in _BPE_SPLIT.findall(text):
            mapped = "".join(_BYTE_TO_CHAR[b] for b in word.encode("utf-8"))
            parts = list(mapped)
            while len(parts) > 1:
                ranks = [(self.merge_ranks.get((parts[i], parts[i + 1]), 1 << 30), i)
                         for i in range(len(parts) - 1)]
                r, i = min(ranks)
                if r == 1 << 30:
                    break
                parts = parts[:i] + [parts[i] + parts[i + 1]] + parts[i + 2:]
            for p in parts:
                tid = self.vocab.get(p)
                if tid is None:
                    out.extend(self.vocab.get(ch, self.unk_token_id or 0)
                               for ch in p)
                else:
                    out.append(tid)
        return out

    # ---- decode ----

    def _piece_bytes(self, tid: int) -> bytes:
        t = self.tokens[tid]
        if tid in self.byte_ids.values() and t.startswith("<0x"):
            return bytes([int(t[3:5], 16)])
        if self.model == "gpt2":
            return bytes(_CHAR_TO_BYTE.get(c, ord("?")) for c in t)
        return t.replace("▁", " ").encode("utf-8")

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        out = bytearray()
        for tid in ids:
            tid = int(tid)
            if tid < 0 or tid >= self.vocab_size:
                continue
            if skip_special_tokens and (tid in self._specials
                                        or tid in (self.bos_token_id,
                                                   self.eos_token_id)):
                continue
            out += self._piece_bytes(tid)
        text = out.decode("utf-8", errors="replace")
        # SentencePiece: the leading ▁-space is an artifact of encoding
        if self.model != "gpt2" and text.startswith(" "):
            text = text[1:]
        return text

    def convert_ids_to_tokens(self, ids) -> list[str]:
        return [self.tokens[int(i)] if 0 <= int(i) < self.vocab_size else ""
                for i in ids]

    def get_vocab_size(self) -> int:
        return self.vocab_size

    def __len__(self) -> int:
        return self.vocab_size


@functools.lru_cache(maxsize=8)
def from_gguf(path: str) -> GGUFTokenizer:
    from localai_tpu.engine.gguf import open_gguf

    return GGUFTokenizer(open_gguf(path).metadata)
