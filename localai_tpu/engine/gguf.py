"""GGUF checkpoint ingestion: header/metadata parse + dequantize to the
stacked llama pytree.

The reference's whole model ecosystem is GGUF — its downloader pulls GGUF
blobs (reference: pkg/downloader/uri.go:21-30, gallery YAMLs) and its
guesser reads the same header this module parses (reference:
core/config/guesser.go:145-246 via gguf-parser). The TPU design
dequantizes GGUF tensors into dense arrays at LOAD time (optionally
re-quantizing to TPU-native weight-only int8): the MXU consumes
bf16/int8 tiles, so llama.cpp's block formats are a storage format here,
not a compute format.

Supported tensor types: F32, F16, BF16, Q8_0, Q4_0, Q4_1, Q5_0, Q5_1,
Q4_K, Q5_K, Q6_K — covering the ollama-default and *_K_M gallery quants.

Everything is numpy (host-side, memory-mapped reads); JAX placement
happens in weights.load_llama_params.
"""

from __future__ import annotations

import functools
import struct
from typing import Any, BinaryIO

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL, _T_STR, \
    _T_ARR, _T_U64, _T_I64, _T_F64 = range(13)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_BOOL: "<?",
    _T_U64: "<Q", _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor types (ggml.h enum ggml_type)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0, GGML_Q8_1 = 8, 9
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K, GGML_Q8_K = 10, 11, 12, 13, 14, 15
GGML_BF16 = 30

# type -> (block_elems, block_bytes)
_BLOCK = {
    GGML_F32: (1, 4), GGML_F16: (1, 2), GGML_BF16: (1, 2),
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24),
    GGML_Q8_0: (32, 34),
    GGML_Q4_K: (256, 144), GGML_Q5_K: (256, 176), GGML_Q6_K: (256, 210),
}

_TYPE_NAMES = {v: k[5:] for k, v in globals().items() if k.startswith("GGML_")}


def _read_str(f: BinaryIO) -> str:
    n = struct.unpack("<Q", f.read(8))[0]
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_STR:
        return _read_str(f)
    if vtype == _T_ARR:
        etype, n = struct.unpack("<IQ", f.read(12))
        if etype in _SCALAR_FMT:
            fmt = _SCALAR_FMT[etype]
            size = struct.calcsize(fmt)
            raw = f.read(size * n)
            return [struct.unpack_from(fmt, raw, i * size)[0] for i in range(n)]
        return [_read_value(f, etype) for _ in range(n)]
    fmt = _SCALAR_FMT[vtype]
    return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]


class GGUFFile:
    """Parsed GGUF header: ``metadata`` dict + ``tensors`` name->info, with
    lazy per-tensor dequantization from a memory map."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, dict] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            self.version = struct.unpack("<I", f.read(4))[0]
            if self.version < 2:
                raise ValueError(f"GGUF v{self.version} unsupported (need >= 2)")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                vtype = struct.unpack("<I", f.read(4))[0]
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_str(f)
                n_dims = struct.unpack("<I", f.read(4))[0]
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ttype, offset = struct.unpack("<IQ", f.read(12))
                self.tensors[name] = {
                    "dims": dims,  # ggml order: dims[0] fastest-varying
                    "type": ttype,
                    "offset": offset,
                }
            align = int(self.metadata.get("general.alignment", 32))
            pos = f.tell()
            self.data_start = (pos + align - 1) // align * align
        self._mmap = np.memmap(path, dtype=np.uint8, mode="r")

    def tensor(self, name: str, dtype=np.float32) -> np.ndarray:
        """Dequantize a tensor, shaped in ROW-MAJOR numpy order (ggml dims
        reversed): a ggml [in, out] matrix comes back [out, in] — the same
        orientation as HF ``*.weight`` tensors. ``dtype=np.float16`` halves
        host peak memory during load (quantized sources carry <= f16
        precision anyway)."""
        info = self.tensors[name]
        dims = info["dims"]
        ttype = info["type"]
        if ttype not in _BLOCK:
            raise ValueError(
                f"{name}: unsupported GGML type {ttype} "
                f"({_TYPE_NAMES.get(ttype, '?')})")
        n_elems = int(np.prod(dims))
        be, bb = _BLOCK[ttype]
        nbytes = n_elems // be * bb
        start = self.data_start + info["offset"]
        raw = np.asarray(self._mmap[start:start + nbytes])
        flat = _dequantize(raw, ttype, n_elems)
        if dtype is not np.float32:
            flat = flat.astype(dtype)
        return flat.reshape(tuple(reversed(dims)))


@functools.lru_cache(maxsize=4)
def open_gguf(path: str) -> GGUFFile:
    """Shared parsed-header cache: config, weights and tokenizer all read
    the same file during one LoadModel — parse the (vocab-sized) metadata
    once, not three times."""
    return GGUFFile(path)


def _f16(raw_u8: np.ndarray) -> np.ndarray:
    return raw_u8.view(np.float16).astype(np.float32)


def _dequantize(raw: np.ndarray, ttype: int, n: int) -> np.ndarray:
    """raw uint8 buffer -> float32 [n]. Layouts follow ggml-quants.c."""
    if ttype == GGML_F32:
        return np.asarray(raw.view(np.float32)[:n])
    if ttype == GGML_F16:
        return _f16(raw)[:n]
    if ttype == GGML_BF16:
        out = np.zeros(n, np.float32)
        out.view(np.uint32)[:] = raw.view(np.uint16)[:n].astype(np.uint32) << 16
        return out
    if ttype == GGML_Q8_0:
        # block: f16 d; int8 qs[32]
        blocks = raw.reshape(-1, 34)
        d = _f16(blocks[:, :2].reshape(-1))[:, None]
        q = blocks[:, 2:].view(np.int8).astype(np.float32)
        return (d * q).reshape(-1)[:n]
    if ttype == GGML_Q4_0:
        # block: f16 d; u8 qs[16] (elem i in low nibble, i+16 in high)
        blocks = raw.reshape(-1, 18)
        d = _f16(blocks[:, :2].reshape(-1))[:, None]
        qs = blocks[:, 2:]
        lo = (qs & 0x0F).astype(np.int8) - 8
        hi = (qs >> 4).astype(np.int8) - 8
        q = np.concatenate([lo, hi], axis=1).astype(np.float32)
        return (d * q).reshape(-1)[:n]
    if ttype == GGML_Q4_1:
        # block: f16 d, m; u8 qs[16]
        blocks = raw.reshape(-1, 20)
        d = _f16(blocks[:, :2].reshape(-1))[:, None]
        m = _f16(blocks[:, 2:4].reshape(-1))[:, None]
        qs = blocks[:, 4:]
        lo = (qs & 0x0F).astype(np.float32)
        hi = (qs >> 4).astype(np.float32)
        q = np.concatenate([lo, hi], axis=1)
        return (d * q + m).reshape(-1)[:n]
    if ttype in (GGML_Q5_0, GGML_Q5_1):
        # block: f16 d (,f16 m); u32 qh; u8 qs[16] — 5th bit from qh
        bb = 22 if ttype == GGML_Q5_0 else 24
        blocks = raw.reshape(-1, bb)
        d = _f16(blocks[:, :2].reshape(-1))[:, None]
        off = 2
        if ttype == GGML_Q5_1:
            m = _f16(blocks[:, 2:4].reshape(-1))[:, None]
            off = 4
        qh = blocks[:, off:off + 4].copy().view(np.uint32).reshape(-1, 1)
        qs = blocks[:, off + 4:]
        shifts = np.arange(32, dtype=np.uint32)
        h = ((qh >> shifts) & 1).astype(np.uint8)          # [B, 32]
        lo = (qs & 0x0F)
        hi = (qs >> 4)
        q4 = np.concatenate([lo, hi], axis=1)              # [B, 32]
        q = (q4 | (h << 4)).astype(np.float32)
        if ttype == GGML_Q5_0:
            return (d * (q - 16.0)).reshape(-1)[:n]
        return (d * q + m).reshape(-1)[:n]
    if ttype == GGML_Q4_K:
        # super-block of 256: f16 d, dmin; u8 scales[12] (6-bit packed,
        # 8 sub-blocks of 32); u8 qs[128]
        blocks = raw.reshape(-1, 144)
        d = _f16(blocks[:, :2].reshape(-1))
        dmin = _f16(blocks[:, 2:4].reshape(-1))
        sc, mn = _unpack_k_scales(blocks[:, 4:16])          # [B, 8] each
        qs = blocks[:, 16:]                                 # [B, 128]
        # pairs of sub-blocks share 32 bytes: low nibbles sb 2j, high 2j+1
        q = np.empty((blocks.shape[0], 256), np.float32)
        for j in range(4):
            chunk = qs[:, j * 32:(j + 1) * 32]
            q[:, (2 * j) * 32:(2 * j + 1) * 32] = (chunk & 0x0F)
            q[:, (2 * j + 1) * 32:(2 * j + 2) * 32] = (chunk >> 4)
        scale = (d[:, None] * sc).repeat(32, axis=1)
        minv = (dmin[:, None] * mn).repeat(32, axis=1)
        return (scale * q - minv).reshape(-1)[:n]
    if ttype == GGML_Q5_K:
        # f16 d, dmin; scales[12]; u8 qh[32]; u8 qs[128]
        blocks = raw.reshape(-1, 176)
        d = _f16(blocks[:, :2].reshape(-1))
        dmin = _f16(blocks[:, 2:4].reshape(-1))
        sc, mn = _unpack_k_scales(blocks[:, 4:16])
        qh = blocks[:, 16:48]                               # [B, 32]
        qs = blocks[:, 48:]                                 # [B, 128]
        q = np.empty((blocks.shape[0], 256), np.float32)
        for j in range(4):
            chunk = qs[:, j * 32:(j + 1) * 32]
            hbit_lo = (qh >> (2 * j)) & 1
            hbit_hi = (qh >> (2 * j + 1)) & 1
            q[:, (2 * j) * 32:(2 * j + 1) * 32] = (chunk & 0x0F) | (hbit_lo << 4)
            q[:, (2 * j + 1) * 32:(2 * j + 2) * 32] = (chunk >> 4) | (hbit_hi << 4)
        scale = (d[:, None] * sc).repeat(32, axis=1)
        minv = (dmin[:, None] * mn).repeat(32, axis=1)
        return (scale * q - minv).reshape(-1)[:n]
    if ttype == GGML_Q6_K:
        # u8 ql[128]; u8 qh[64]; i8 scales[16]; f16 d — 16 sub-blocks of 16
        blocks = raw.reshape(-1, 210)
        ql = blocks[:, :128]
        qh = blocks[:, 128:192]
        scales = blocks[:, 192:208].view(np.int8).astype(np.float32)
        d = _f16(blocks[:, 208:210].reshape(-1))[:, None]
        B = blocks.shape[0]
        q = np.empty((B, 256), np.float32)
        # layout per ggml-quants.c dequantize_row_q6_K: two halves of 128
        for half in range(2):
            lq = ql[:, half * 64:(half + 1) * 64]
            hq = qh[:, half * 32:(half + 1) * 32]
            base = half * 128
            q[:, base + 0:base + 32] = ((lq[:, :32] & 0x0F) | ((hq & 0x03) << 4)).astype(np.int8) - 32
            q[:, base + 32:base + 64] = ((lq[:, 32:] & 0x0F) | (((hq >> 2) & 0x03) << 4)).astype(np.int8) - 32
            q[:, base + 64:base + 96] = ((lq[:, :32] >> 4) | (((hq >> 4) & 0x03) << 4)).astype(np.int8) - 32
            q[:, base + 96:base + 128] = ((lq[:, 32:] >> 4) | (((hq >> 6) & 0x03) << 4)).astype(np.int8) - 32
        scale = (d * scales).repeat(16, axis=1)
        return (scale * q).reshape(-1)[:n]
    raise ValueError(f"unsupported GGML type {ttype}")


def _unpack_k_scales(sc12: np.ndarray):
    """Unpack the 12-byte 6-bit scale/min table of Q4_K/Q5_K.

    Sub-blocks 0-3: scale = q[j] & 63, min = q[j+4] & 63.
    Sub-blocks 4-7: scale = (q[j+4] & 0xF) | ((q[j-4] >> 6) << 4),
                    min   = (q[j+4] >> 4)  | ((q[j]   >> 6) << 4).
    (ggml-quants.c get_scale_min_k4.)
    """
    q = sc12.astype(np.uint8)
    B = q.shape[0]
    sc = np.empty((B, 8), np.float32)
    mn = np.empty((B, 8), np.float32)
    for j in range(4):
        sc[:, j] = (q[:, j] & 63)
        mn[:, j] = (q[:, j + 4] & 63)
    for j in range(4, 8):
        sc[:, j] = (q[:, j + 4] & 0x0F) | ((q[:, j - 4] >> 6) << 4)
        mn[:, j] = (q[:, j + 4] >> 4) | ((q[:, j] >> 6) << 4)
    return sc, mn


# ---------- llama mapping ----------

def config_from_gguf(g: "GGUFFile | str"):
    """Build a LlamaConfig from GGUF metadata (keys per the GGUF spec's
    llama architecture section; same fields the reference's guesser reads,
    core/config/guesser.go:145-246)."""
    from localai_tpu.models.llama import LlamaConfig

    if isinstance(g, str):
        g = GGUFFile(g)
    md = g.metadata
    arch = md.get("general.architecture", "llama")
    pre = arch + "."

    def get(key, default=None):
        return md.get(pre + key, default)

    n_heads = int(get("attention.head_count", 32))
    vocab = g.tensors["token_embd.weight"]["dims"][1]
    hidden = int(get("embedding_length", g.tensors["token_embd.weight"]["dims"][0]))
    rs_type = "none"
    factor = float(get("rope.scaling.factor", 1.0) or 1.0)
    st = get("rope.scaling.type")
    if st in ("linear", "yarn"):
        rs_type = st
    return LlamaConfig(
        vocab_size=int(vocab),
        hidden_size=hidden,
        intermediate_size=int(get("feed_forward_length", 4 * hidden)),
        num_layers=int(get("block_count", 32)),
        num_heads=n_heads,
        num_kv_heads=int(get("attention.head_count_kv", n_heads)),
        head_dim=int(get("rope.dimension_count", hidden // n_heads)),
        rope_theta=float(get("rope.freq_base", 10000.0)),
        rope_scaling_type=rs_type,
        rope_scaling_factor=factor,
        rope_original_max_position=int(
            get("rope.scaling.original_context_length",
                get("context_length", 8192))),
        rms_norm_eps=float(get("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(get("context_length", 4096)),
        tie_word_embeddings="output.weight" not in g.tensors,
    )


def _unpermute(w: np.ndarray, n_heads: int) -> np.ndarray:
    """GGUF stores llama wq/wk rows in the interleaved (Meta) rope layout
    (llama.cpp convert permutes HF weights); our rope is HF rotate_half, so
    apply the inverse permutation. w: [out, in]."""
    out, inn = w.shape
    return (w.reshape(n_heads, out // n_heads // 2, 2, inn)
            .swapaxes(1, 2)
            .reshape(out, inn))


def iter_llama_tensors(g: GGUFFile, cfg, dtype=np.float16):
    """Yield (pytree_path, host array) one leaf at a time so the caller can
    place each leaf on device and free the host copy before the next is
    dequantized — peak host memory stays at ONE stacked leaf, matching the
    safetensors loader's stance (weights.py module doc)."""
    L = cfg.num_layers

    def stack(fmt, permute_heads=0):
        mats = []
        for i in range(L):
            m = g.tensor(fmt.format(i=i), dtype)
            if permute_heads:
                m = _unpermute(m, permute_heads)
            mats.append(np.ascontiguousarray(m.T))
        return np.stack(mats)

    def stack_vec(fmt):
        return np.stack([g.tensor(fmt.format(i=i), dtype) for i in range(L)])

    yield ("embed",), g.tensor("token_embd.weight", dtype)
    yield ("layers", "attn_norm"), stack_vec("blk.{i}.attn_norm.weight")
    yield ("layers", "wq"), stack("blk.{i}.attn_q.weight",
                                  permute_heads=cfg.num_heads)
    yield ("layers", "wk"), stack("blk.{i}.attn_k.weight",
                                  permute_heads=cfg.num_kv_heads)
    yield ("layers", "wv"), stack("blk.{i}.attn_v.weight")
    yield ("layers", "wo"), stack("blk.{i}.attn_output.weight")
    yield ("layers", "mlp_norm"), stack_vec("blk.{i}.ffn_norm.weight")
    yield ("layers", "w_gate"), stack("blk.{i}.ffn_gate.weight")
    yield ("layers", "w_up"), stack("blk.{i}.ffn_up.weight")
    yield ("layers", "w_down"), stack("blk.{i}.ffn_down.weight")
    yield ("final_norm",), g.tensor("output_norm.weight", dtype)
    if "output.weight" in g.tensors:
        yield ("lm_head",), np.ascontiguousarray(
            g.tensor("output.weight", dtype).T)


def load_gguf_tensors(path: str, cfg=None):
    """Read a GGUF file into (cfg, host-numpy pytree matching
    models/llama.py's layout). Convenience wrapper over iter_llama_tensors
    (which streaming callers should prefer)."""
    g = open_gguf(path)
    if cfg is None:
        cfg = config_from_gguf(g)
    params: dict = {"layers": {}}
    for spec_path, arr in iter_llama_tensors(g, cfg):
        node = params
        for k in spec_path[:-1]:
            node = node[k]
        node[spec_path[-1]] = arr
    return cfg, params


# ---------- test/export helper ----------

def write_gguf(path: str, metadata: dict, tensors: dict,
               tensor_types: dict = None):
    """Write a GGUF v3 file (float32/float16/Q8_0/Q4_0 encoders) — the
    tiny-checkpoint path for offline tests and a general exporter."""
    tensor_types = tensor_types or {}
    align = 32

    def enc_str(s: str) -> bytes:
        b = s.encode("utf-8")
        return struct.pack("<Q", len(b)) + b

    def enc_value(v) -> bytes:
        if isinstance(v, bool):
            return struct.pack("<I?", _T_BOOL, v)
        if isinstance(v, int):
            return struct.pack("<Iq", _T_I64, v) if v < 0 else struct.pack("<IQ", _T_U64, v)
        if isinstance(v, float):
            return struct.pack("<If", _T_F32, v)
        if isinstance(v, str):
            return struct.pack("<I", _T_STR) + enc_str(v)
        if isinstance(v, (list, tuple)):
            if all(isinstance(x, str) for x in v):
                body = b"".join(enc_str(x) for x in v)
                return struct.pack("<IIQ", _T_ARR, _T_STR, len(v)) + body
            if all(isinstance(x, int) for x in v):
                body = b"".join(struct.pack("<i", x) for x in v)
                return struct.pack("<IIQ", _T_ARR, _T_I32, len(v)) + body
            body = b"".join(struct.pack("<f", float(x)) for x in v)
            return struct.pack("<IIQ", _T_ARR, _T_F32, len(v)) + body
        raise TypeError(f"unsupported metadata value {type(v)}")

    def encode_tensor(arr: np.ndarray, ttype: int) -> bytes:
        flat = np.asarray(arr, np.float32).reshape(-1)
        if ttype == GGML_F32:
            return flat.tobytes()
        if ttype == GGML_F16:
            return flat.astype(np.float16).tobytes()
        if ttype == GGML_Q8_0:
            blocks = flat.reshape(-1, 32)
            d = np.maximum(np.abs(blocks).max(axis=1), 1e-12) / 127.0
            q = np.clip(np.rint(blocks / d[:, None]), -127, 127).astype(np.int8)
            out = bytearray()
            for i in range(blocks.shape[0]):
                out += np.float16(d[i]).tobytes() + q[i].tobytes()
            return bytes(out)
        if ttype == GGML_Q4_0:
            blocks = flat.reshape(-1, 32)
            amax_idx = np.argmax(np.abs(blocks), axis=1)
            maxv = blocks[np.arange(blocks.shape[0]), amax_idx]
            d = np.where(maxv == 0, 1e-12, maxv / -8.0)
            q = np.clip(np.rint(blocks / d[:, None] + 8.0), 0, 15).astype(np.uint8)
            packed = (q[:, :16] | (q[:, 16:] << 4)).astype(np.uint8)
            out = bytearray()
            for i in range(blocks.shape[0]):
                out += np.float16(d[i]).tobytes() + packed[i].tobytes()
            return bytes(out)
        raise ValueError(f"no encoder for GGML type {ttype}")

    infos = []
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        ttype = tensor_types.get(name, GGML_F32)
        blob = encode_tensor(arr, ttype)
        dims = tuple(reversed(np.asarray(arr).shape))
        infos.append((name, dims, ttype, offset))
        blobs.append(blob)
        offset += len(blob)
        offset = (offset + align - 1) // align * align

    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<QQ", len(tensors), len(metadata)))
        for k, v in metadata.items():
            f.write(enc_str(k))
            f.write(enc_value(v))
        for name, dims, ttype, off in infos:
            f.write(enc_str(name))
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", ttype, off))
        pos = f.tell()
        f.write(b"\x00" * ((pos + align - 1) // align * align - pos))
        for i, blob in enumerate(blobs):
            f.write(blob)
            pos = f.tell()
            if i + 1 < len(blobs):
                f.write(b"\x00" * ((pos + align - 1) // align * align - pos))
