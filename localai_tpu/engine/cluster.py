"""Cluster router over N engine-pool worker hosts (ISSUE 17).

The serving unit grows one more level: a ``ClusterRouter`` fronts N
``ClusterHost``s, each a PR-14 ``EnginePool`` (replicas + one shared
host tier) made NETWORK-ADDRESSABLE by a ``KVWireServer``
(services/kv_wire.py) and peer-aware by a ``FederatedKV``
(engine/kv_stream.py). The PR-2/3 chained block hashes already make KV
location-independent, so everything the pool does across replicas —
prefix-affinity routing, live handoff, crash recovery — lifts across
hosts with the wire as the only new mechanism:

* ROUTING: the router polls each host's chain-key DIGEST (the pool
  prefix index + host-tier membership) over the wire and routes each
  request to the host holding the longest prefix match; peer-held
  chains a probe misses still stream in at admission through the
  federated tier, so a wrong guess costs a fetch, not a re-prefill.

* DISAGGREGATION (DejaVu / Splitwise): hosts carry a ``role`` —
  ``prefill`` hosts run admission + packed prefill only and retire each
  chain to the transport after its first token; the router hands the
  ResumeEntry to a ``decode`` host which pre-fetches the streamed chain
  and splices it. Decode ITL never queues behind a prefill wave.

* CRASH RECOVERY: a host whose engine loops die (accelerator/host loop
  lost; the wire server thread keeps serving the surviving host tier —
  loop death is not store death) is harvested exactly like a dead pool
  replica, one level up: in-flight slots and parked resumes re-adopt on
  sibling hosts whose federated tier streams the warm chains over; the
  client stream never closes (PR-10's resume ≡ fresh-re-admission
  contract makes the continuation byte-identical to re-submitting
  prompt + emitted).

* AUDIT (ISSUE 15, lifted cluster-wide): chain entries in flight on the
  wire are DECLARED EXTRAS, never leaks — ``kv_audit_sweep`` folds
  every host's sweep and checks all transports are quiesced.

* PROCESS MODE (ISSUE 20): hosts may run as real OS processes behind
  the control plane (services/cluster_rpc.py). The router drives a
  ``RemoteHostHandle`` through the exact same facade as an in-process
  ``ClusterHost`` (submit / cancel / chain_keys / metrics_snapshot /
  kv_audit_sweep / load / alive) — it is agnostic to whether a host is
  a thread or a PID. Remote liveness comes from a phi-accrual heartbeat
  detector: SUSPECT hosts (slow, answering late) are DE-PREFERRED in
  routing and skipped as KV-streaming targets but keep their streams;
  DEAD hosts (silent past ``cluster_dead_ms``, or the process exited)
  trigger recovery — each lost stream re-admits (prompt + delivered
  tokens) on a sibling, byte-identical by the PR-10 contract.

``cluster=off`` (the default) never constructs any of this — the
single-host PR-16 path is untouched, bit-for-bit. ``cluster_mode=
inproc`` (the default) builds only in-process hosts: no heartbeats, no
RPC, bit-for-bit PR-17.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Optional

from localai_tpu.engine import engine as eng
from localai_tpu.engine.kv_stream import FederatedKV, KVStreamClient
from localai_tpu.engine.pool import EnginePool
from localai_tpu.engine.scheduler import PRIORITY_RANK, ResumeEntry
from localai_tpu.services.cluster_rpc import FailureDetector
from localai_tpu.services.eventlog import EVENTS
from localai_tpu.services.faults import FAULTS
from localai_tpu.services.kv_wire import KVWireServer, WireError

log = logging.getLogger(__name__)

# how many recovery/disagg chain pins to keep mapped before releasing
# the oldest (same bound and rationale as pool._MAX_PINS)
_MAX_PINS = 16
# digest poll cadence: affinity data may be this stale; staleness costs
# a federated fetch at admission, never correctness
_DIGEST_PERIOD_S = 0.25


class ClusterHost:
    """One worker host: an EnginePool + its shared KV tiers, serving its
    host tier to peers over the wire and consulting peers on misses.

    ``role``: ``both`` (default — a full host), ``prefill`` (admission +
    packed prefill only; finished prefills retire to the transport) or
    ``decode`` (receives disagg handoffs; the router keeps fresh
    arrivals away when a prefill host is alive)."""

    remote = False

    def __init__(self, host_id: int, pool: EnginePool, role: str = "both",
                 bind: str = "127.0.0.1"):
        assert role in ("both", "prefill", "decode"), role
        self.host_id = int(host_id)
        self.pool = pool
        self.role = role
        self._bind = bind
        self.server: Optional[KVWireServer] = None
        self.fed: Optional[FederatedKV] = None
        self.address = ""
        self.killed = False
        # host-scoped chaos identity: in-process hosts share the global
        # FAULTS table, so replica{N}_die would collide across hosts —
        # every engine loop on this host consumes one firing of this
        # name instead (kill() arms count=len(engines))
        self._die_fault = f"cluster{self.host_id}_die"
        for e in self.pool._engines:
            e._die_fault = self._die_fault

    # ---------- construction ----------

    @classmethod
    def build(cls, model_cfg, params, tokenizer, engine_cfg=None,
              host_id: int = 0, engines: int = 1, role: str = "both",
              bind: str = "127.0.0.1", **kw):
        """One host = one EnginePool with a role-annotated config.
        Requires the preemptive scheduler (pause/resume is the handoff
        primitive) and a host tier (the transport serves it)."""
        ecfg = engine_cfg or eng.EngineConfig()
        ecfg = dataclasses.replace(ecfg, disagg=role)
        if not ecfg.preempt:
            raise ValueError("cluster hosts require preempt=1 (pause/"
                             "resume is the handoff primitive)")
        if not ecfg.kv_offload or not ecfg.kv_prefix_cache:
            raise ValueError("cluster hosts require kv_offload=1 + the "
                             "prefix cache (the wire serves the host "
                             "tier)")
        pool = EnginePool.build(model_cfg, params, tokenizer, ecfg,
                                engines=max(1, int(engines)), **kw)
        return cls(host_id, pool, role=role, bind=bind)

    # ---------- lifecycle ----------

    def start(self, precompile: bool = False) -> str:
        self.pool.start(precompile=precompile)
        store = self.pool._shared.store
        if store is None:
            raise RuntimeError("cluster host has no shared host store "
                               "(kv_offload off, or a non-paged layout?)")
        self.server = KVWireServer(store, index=self.pool._shared.index,
                                   host_id=self.host_id, bind=self._bind)
        self.address = self.server.start()
        for e in self.pool._engines:
            # continuous warm-chain checkpointing (DejaVu): active
            # chains stream to the host tier on the watermark cadence
            # so a crash leaves near-current state for siblings to pull
            e.kv_checkpoint = True
        log.info("cluster host %d (%s) serving kv at %s",
                 self.host_id, self.role, self.address)
        return self.address

    def connect_peers(self, addresses: list):
        """Attach the federated tier: this host's store misses consult
        these peers (every other host's wire address)."""
        store = self.pool._shared.store
        ecfg = self.pool._engines[0].ecfg
        peers = [KVStreamClient(
                     a, store.scope, store.page_size,
                     timeout_s=ecfg.kv_stream_connect_timeout_ms / 1e3,
                     cooldown_s=ecfg.kv_stream_cooldown_ms / 1e3)
                 for a in addresses if a and a != self.address]
        self.fed = FederatedKV(store, peers,
                               neg_ttl_s=ecfg.kv_stream_negcache_ms / 1e3
                               ).attach()
        return self.fed

    def shutdown(self):
        if self.fed is not None:
            self.fed.close()
        if self.server is not None:
            self.server.stop()
        self.pool.shutdown()

    # ---------- health / chaos ----------

    @property
    def alive(self) -> bool:
        """False once every engine loop on the host died WITHOUT
        shutdown (the pool's crash asymmetry, host-wide). The wire
        server is deliberately not consulted: loop death with a live
        store is exactly the recoverable state."""
        if self.killed and all(not e.loop_alive for e in self.pool._engines):
            return False
        dead = [e for e in self.pool._engines
                if e._thread is not None
                and not e.loop_alive and not e._stop]
        return len(dead) < len(self.pool._engines)

    def kill(self):
        """Chaos: lose this host's engine loops (accelerator gone), but
        NOT its host tier or wire server — siblings stream the warm
        chains out of the carcass. The pool's own housekeeping stops
        FIRST so it cannot race the router's harvest by failing streams
        when it finds no live sibling replica."""
        self.killed = True
        self.pool._hk_stop.set()
        FAULTS.arm(self._die_fault, count=len(self.pool._engines))
        for e in self.pool._engines:
            e._wake.set()

    # ---------- load ----------

    def load(self, rank: int = 1) -> float:
        return sum(self.pool._load(i, rank)
                   for i in range(len(self.pool._engines))
                   if not self.pool._dead[i])

    # ---------- uniform host facade (ISSUE 20) ----------
    # The router drives every host — in-process or behind the control
    # plane — through exactly these methods, so it is agnostic to
    # whether a host is a thread or a PID.

    @property
    def state(self) -> str:
        return (FailureDetector.DEAD if not self.alive
                else FailureDetector.ALIVE)

    def submit(self, req) -> "queue.Queue":
        return self.pool.submit(req)

    def cancel(self, rid: str):
        self.pool.cancel(rid)

    def chain_keys(self, ids) -> list:
        pc = self.pool._engines[0]._pcache
        return list(pc.chain_keys(ids)) if pc is not None else []

    def metrics_snapshot(self) -> dict:
        return {
            "pool": self.pool.metrics(),
            "kv_stream": (self.fed.stats() if self.fed is not None else {}),
            "kv_stream_served": (self.server.stats()
                                 if self.server is not None else {}),
            "kv_debug": self.pool.kv_debug(),
        }

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        out = dict(self.pool.kv_audit_sweep(drained=drained))
        out["stream_inflight"] = (self.fed.inflight
                                  if self.fed is not None else 0)
        return out


class ClusterRouter:
    """Front door over N ClusterHosts: cross-host prefix-affinity
    routing, disagg handoff brokering, host crash recovery, cluster-wide
    audit. Mirrors the pool surface the servicer drives (submit /
    generate / cancel / metrics / kv_audit_sweep / shutdown)."""

    def __init__(self, hosts: list):
        assert hosts, "ClusterRouter needs at least one host"
        self.hosts = list(hosts)
        self._dead = [False] * len(hosts)
        self._lock = threading.Lock()
        self._where: dict = {}
        self._where_order: list = []
        self._digests: list = [set() for _ in hosts]
        self._clients: list = [None] * len(hosts)
        self._t_digest = 0.0
        self._pins: list = []
        self._disagg_q: "queue.Queue" = queue.Queue()
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.disagg_handoffs = 0
        self.hosts_recovered = 0
        self._routed = 0
        # remote (process-mode) host bookkeeping: streams re-adopted
        # after a crash/drain, idempotence guard per request id
        self.remote_recovered = 0
        self.drains = 0
        self._recovering: set = set()
        self._hk_stop = threading.Event()
        self._hk_thread: Optional[threading.Thread] = None

    # ---------- lifecycle ----------

    def start(self, precompile: bool = False):
        addrs = [h.start(precompile=precompile) for h in self.hosts]
        for h in self.hosts:
            h.connect_peers(addrs)
        # the router's own digest/stats connections ride the same wire
        # the federated tier uses — affinity data is whatever a peer
        # could learn, no in-process shortcuts. Remote hosts answer
        # DIGEST over the control plane instead (the idempotent-retry
        # path); their failover callbacks land here.
        for i, h in enumerate(self.hosts):
            if h.remote:
                h.on_stream_lost = self._remote_stream_lost
                h.on_state_change = self._remote_state_change
            else:
                store = h.pool._shared.store
                e0 = h.pool._engines[0].ecfg
                self._clients[i] = KVStreamClient(
                    addrs[i], store.scope, store.page_size,
                    timeout_s=e0.kv_stream_connect_timeout_ms / 1e3,
                    cooldown_s=e0.kv_stream_cooldown_ms / 1e3)
        # prefill-role engines hand finished chains to the router
        for i, h in enumerate(self.hosts):
            if h.role == "prefill" and not h.remote:
                for e in h.pool._engines:
                    e.disagg_handoff = self._make_handoff(i)
        self._hk_thread = threading.Thread(
            target=self._housekeeping, name="cluster-router", daemon=True)
        self._hk_thread.start()

    def shutdown(self):
        self._hk_stop.set()
        if self._hk_thread is not None:
            self._hk_thread.join(timeout=5)
        self._drain_disagg()        # nothing may strand in the broker
        with self._lock:
            pins, self._pins = self._pins, []
        for host_i, rid, keys in pins:
            self._unpin(host_i, rid, keys)
        for c in self._clients:
            if c is not None:
                c.close()
        for h in self.hosts:
            try:
                h.shutdown()
            except Exception:
                log.exception("cluster host %d shutdown failed", h.host_id)

    # ---------- routing ----------

    def _alive_hosts(self):
        return [i for i in range(len(self.hosts)) if not self._dead[i]]

    def _note_where(self, rid: str, host: int):
        with self._lock:
            if rid not in self._where:
                self._where_order.append(rid)
            self._where[rid] = host
            while len(self._where_order) > 4096:
                old = self._where_order.pop(0)
                self._where.pop(old, None)

    def where(self, rid: str) -> Optional[int]:
        return self._where.get(rid)

    def _poll_digests(self):
        """Refresh the per-host chain-key sets used for affinity. A
        host that fails to answer keeps its last digest — stale beats
        empty, and the federated fetch at admission is the backstop.
        SUSPECT remote hosts are skipped entirely: a slow peer keeps
        its streams but gets no new probe traffic from the router."""
        for i in self._alive_hosts():
            h = self.hosts[i]
            if h.remote:
                if h.state != FailureDetector.ALIVE:
                    continue
                try:
                    d = h.digest()
                except (OSError, WireError):
                    continue
            else:
                c = self._clients[i]
                if c is None or not c.online():
                    continue
                try:
                    d = c.digest()
                except (OSError, WireError):
                    continue
            self._digests[i] = {bytes.fromhex(k)
                                for k in d.get("keys", ())}

    def _penalty(self, i: int) -> int:
        """Routing de-preference: a SUSPECT host (slow but answering)
        sorts behind every healthy host at any load — degraded, not
        excluded; it still serves if it is all that's left."""
        return 0 if self.hosts[i].state == FailureDetector.ALIVE else 1

    def _match_depth(self, keys: list, digest: set) -> int:
        d = 0
        for k in keys:
            if k not in digest:
                break
            d += 1
        return d

    def _route(self, req, host: Optional[int] = None) -> int:
        alive = self._alive_hosts()
        if not alive:
            raise RuntimeError("cluster: no live hosts")
        if host is not None:
            if host not in alive:
                raise RuntimeError(f"cluster: host {host} is not live")
            self._routed += 1
            return host
        # fresh arrivals need a prefill-capable host; pure-decode hosts
        # receive work only through the disagg broker (unless they are
        # all that's left — serving beats failing)
        cands = [i for i in alive if self.hosts[i].role != "decode"]
        if not cands:
            cands = alive
        rank = PRIORITY_RANK.get(getattr(req, "priority", None), 1)
        self._routed += 1
        if len(cands) > 1 and getattr(req, "prompt_ids", None):
            keys = self.hosts[cands[0]].chain_keys(req.prompt_ids)
            best_i, best_d = None, 0
            for i in cands:
                if self._penalty(i):
                    continue            # a SUSPECT host never wins
                d = self._match_depth(keys, self._digests[i])
                if d > best_d or (d == best_d and d > 0
                                  and best_i is not None
                                  and self.hosts[i].load(rank)
                                  < self.hosts[best_i].load(rank)):
                    best_i, best_d = i, d
            if best_i is not None and best_d > 0:
                self.affinity_hits += 1
                return best_i
            self.affinity_misses += 1
        return min(cands, key=lambda i: (self._penalty(i),
                                         self.hosts[i].load(rank), i))

    def submit(self, req, host: Optional[int] = None) -> "queue.Queue":
        i = self._route(req, host=host)
        self._note_where(req.request_id, i)
        return self.hosts[i].submit(req)

    def generate(self, req, host: Optional[int] = None):
        out = self.submit(req, host=host)
        while True:
            ev = out.get()
            if ev is None:
                return
            yield ev

    def cancel(self, request_id: str):
        i = self._where.get(request_id)
        if i is not None and not self._dead[i]:
            self.hosts[i].cancel(request_id)
        else:
            for i in self._alive_hosts():
                self.hosts[i].cancel(request_id)

    # ---------- chain pinning ----------

    def _pin(self, host_i: int, rid: str, keys: list):
        """Map recovered/disagg chain keys in ``host_i``'s store under
        ("cluster", rid) so budget eviction can't beat the adoptive
        replica's restore; bounded, oldest released first."""
        if not keys:
            return
        store = self.hosts[host_i].pool._shared.store
        owner = ("cluster", rid)
        for k in keys:
            store.map_key(k, owner)
        drop = []
        with self._lock:
            self._pins.append((host_i, rid, keys))
            while len(self._pins) > _MAX_PINS:
                drop.append(self._pins.pop(0))
        for old in drop:
            self._unpin(*old)

    def _unpin(self, host_i: int, rid: str, keys: list):
        store = self.hosts[host_i].pool._shared.store
        owner = ("cluster", rid)
        for k in keys:
            store.unmap_key(k, owner)

    # ---------- disaggregation ----------

    def _make_handoff(self, src_host: int):
        """The callback a prefill-role engine fires (on its loop thread)
        with a finished-prefill ResumeEntry: enqueue for the router
        thread — the loop must not block on a peer fetch."""
        def handoff(entry, keys, _src=src_host):
            self._disagg_q.put((_src, entry, keys))
        return handoff

    def _drain_disagg(self):
        while True:
            try:
                src, entry, keys = self._disagg_q.get_nowait()
            except queue.Empty:
                return
            self._place_disagg(src, entry, keys)

    def _place_disagg(self, src: int, entry: ResumeEntry, keys: list):
        # ResumeEntry adoption is an in-process move (live slot state);
        # remote hosts receive work as fresh submissions only, so they
        # are never disagg targets. SUSPECT hosts are de-preferred: the
        # router stops placing KV-streaming work on a slow peer.
        rid = entry.req.request_id
        cands = [i for i in self._alive_hosts()
                 if i != src and not self.hosts[i].remote
                 and self.hosts[i].role != "prefill"]
        if not cands:
            # no decode host: hand the request back — the source engine
            # decodes it to completion (never strand a client stream)
            entry.req._no_disagg = True
            if not self._dead[src] and self._adopt_on(src, rid, entry):
                return
            for i in self._alive_hosts():
                if not self.hosts[i].remote \
                        and self._adopt_on(i, rid, entry):
                    return
            self.hosts[src].pool._fail_stream(
                entry.req, "disagg: no host can adopt")
            return
        rank = PRIORITY_RANK.get(entry.priority, 1)
        tgt = min(cands, key=lambda i: (self._penalty(i),
                                        self.hosts[i].load(rank), i))
        host = self.hosts[tgt]
        # stream the prefilled chain over BEFORE admission so the decode
        # host splices local, verified bytes (prefetch > demand-fetch:
        # one round-trip for the whole chain, off the engine loop)
        self._pin(tgt, rid, keys)
        if host.fed is not None and keys:
            host.fed.prefetch(keys)
        if not self._adopt_on(tgt, rid, entry):
            entry.req._no_disagg = True
            if self._dead[src] or not self._adopt_on(src, rid, entry):
                self.hosts[src].pool._fail_stream(
                    entry.req, "disagg: no host can adopt")
            return
        self.disagg_handoffs += 1
        # the source kept the chain mapped under ("disagg", rid) from
        # its force-offload; the decode host holds its own copy now
        src_store = self.hosts[src].pool._shared.store
        for k in keys:
            src_store.unmap_key(k, ("disagg", rid))
        EVENTS.emit("disagg_handoff", rid=rid, src=src, dst=tgt,
                    n_decoded=entry.n_decoded, keys=len(keys))

    def _adopt_on(self, host_i: int, rid: str, entry: ResumeEntry) -> bool:
        """Adopt a ResumeEntry on the least-loaded live replica of one
        host; the pool's note_where keeps its own cancel path working."""
        pool = self.hosts[host_i].pool
        rank = PRIORITY_RANK.get(entry.priority, 1)
        reps = [i for i in range(len(pool._engines)) if not pool._dead[i]]
        if not reps:
            return False
        r = min(reps, key=lambda i: (pool._load(i, rank), i))
        if not pool._engines[r].adopt_resume(entry):
            return False
        pool._note_where(rid, r)
        self._note_where(rid, host_i)
        aud = pool._engines[r]._kv_audit
        if aud is not None:
            aud.ledger.record("adopt", slot=("cluster", host_i), rid=rid)
        return True

    # ---------- crash recovery ----------

    def _recover_host(self, i: int):
        """A host's engine loops died (its device tiers are gone; its
        host tier and wire server survive). Everything it was serving
        re-adopts on sibling hosts: warm chains stream over the wire
        from the carcass store, cold ones re-prefill the identical
        history. Client streams never close — the StreamEvent queues
        ride the ResumeEntries (pool._recover_replica, one level up)."""
        host = self.hosts[i]
        with self._lock:
            if self._dead[i]:
                return              # another thread already harvesting
            self._dead[i] = True
        host.pool._hk_stop.set()    # no same-host recovery races
        self._digests[i] = set()
        EVENTS.emit("cluster_host_down", host=i, role=host.role)
        log.warning("cluster: host %d loop(s) died; recovering", i)
        recovered = failed = 0
        for e in host.pool._engines:
            r = e.replica_id
            if r < len(host.pool._dead):
                host.pool._dead[r] = True
            if e._emitter is not None:
                try:
                    e._emitter.drain(2.0)
                except Exception:
                    pass
            for slot, s in enumerate(e.slots):
                if s is None:
                    continue
                e.slots[slot] = None
                rid = s.req.request_id
                ok = False
                if e._sched is not None and e._preempt_eligible(slot, s):
                    hist = list(e._cache_tokens[slot])
                    if len(hist) < s.prompt_len:
                        hist = list(s.req.prompt_ids) + list(s.generated)
                    entry = ResumeEntry(
                        req=s.req, ids=hist, priority=s.req.priority,
                        generated=list(s.generated), n_decoded=s.n_decoded,
                        prompt_len=s.prompt_len, detok=s.detok,
                        held_text=s.held_text, t_start=s.t_start,
                        t_first_token=s.t_first_token or None,
                        t_prefill_ms=s.t_prefill_ms, mu=float(e.mu[slot]),
                        preempt_count=s.preempts)
                    ok = self._adopt_on_sibling_host(rid, entry, src=i)
                if ok:
                    recovered += 1
                else:
                    failed += 1
                    host.pool._fail_stream(
                        s.req, f"cluster host {i} died; request not "
                               f"recoverable on a sibling host")
            if e._sched is not None:
                for entry in e._sched.drain_parked():
                    if self._adopt_on_sibling_host(
                            entry.req.request_id, entry, src=i):
                        recovered += 1
                    else:
                        failed += 1
                        host.pool._fail_stream(
                            entry.req, f"cluster host {i} died")
            while True:
                try:
                    r2 = e._queue.get_nowait()
                except queue.Empty:
                    break
                try:
                    tgt = self._route(r2)
                    self._note_where(r2.request_id, tgt)
                    self.hosts[tgt].pool.submit(r2)
                    recovered += 1
                except Exception:
                    failed += 1
                    host.pool._fail_stream(
                        r2, f"cluster host {i} died; no live sibling")
        self.hosts_recovered += 1
        EVENTS.emit("cluster_host_recovered", host=i,
                    recovered=recovered, failed=failed)
        log.warning("cluster: host %d recovery done "
                    "(recovered=%d failed=%d)", i, recovered, failed)

    def _adopt_on_sibling_host(self, rid: str, entry: ResumeEntry,
                               src: int) -> bool:
        cands = [i for i in self._alive_hosts()
                 if i != src and not self.hosts[i].remote
                 and self.hosts[i].role != "prefill"]
        if not cands:
            cands = [i for i in self._alive_hosts()
                     if i != src and not self.hosts[i].remote]
        if not cands:
            return False
        rank = PRIORITY_RANK.get(entry.priority, 1)
        tgt = min(cands, key=lambda i: (self._penalty(i),
                                        self.hosts[i].load(rank), i))
        host = self.hosts[tgt]
        pc = host.pool._engines[0]._pcache
        keys = list(pc.chain_keys(entry.ids)) if pc is not None else []
        if keys:
            self._pin(tgt, rid, keys)
            if host.fed is not None:
                # pull the dead host's checkpointed chain into the
                # target's local tier before admission restores it
                host.fed.prefetch(keys)
        if not self._adopt_on(tgt, rid, entry):
            return False
        EVENTS.emit("migrate", rid=rid, src=("host", src),
                    dst=("host", tgt), reason="host_crash", kind="resume",
                    n_decoded=entry.n_decoded)
        return True

    # ---------- remote (process-mode) failure handling ----------

    def _remote_state_change(self, handle, prev: str, state: str):
        """Heartbeat-thread callback: failure-detector transitions.
        SUSPECT needs no action here — routing reads ``state`` live and
        de-prefers; DEAD marks the host down (its own heartbeat thread
        aborts the streams, which fail over via _remote_stream_lost)."""
        try:
            i = self.hosts.index(handle)
        except ValueError:
            return
        EVENTS.emit("cluster_host_state", host=i, prev=prev, state=state,
                    phi=round(handle.detector.phi(), 3))
        if state == FailureDetector.DEAD:
            self._mark_remote_dead(i)

    def _mark_remote_dead(self, i: int):
        with self._lock:
            if self._dead[i]:
                return
            self._dead[i] = True
        self._digests[i] = set()
        self.hosts_recovered += 1
        EVENTS.emit("cluster_host_down", host=i,
                    role=self.hosts[i].role, remote=True)
        log.warning("cluster: remote host %d declared dead; streams "
                    "fail over as they surface", i)

    def _remote_stream_lost(self, handle, req, emitted: list, reason: str):
        """A remote stream ended without EOF — the host crashed, hung
        past ``cluster_dead_ms``, or drained. Recovery is the PR-10
        contract from the CLIENT side: re-admit (prompt + delivered
        tokens) as a fresh continuation on a sibling and bridge its
        events into the original stream — byte-identical, because
        resume ≡ fresh re-admission. SUBMIT is never auto-retried; this
        path is the one and only re-drive, idempotent per request."""
        rid = req.request_id
        with self._lock:
            if rid in self._recovering:
                return
            self._recovering.add(rid)
        try:
            i = self.hosts.index(handle)
        except ValueError:
            i = -1
        remaining = int(req.max_new_tokens) - len(emitted)
        if remaining <= 0:
            # every token was delivered (and the last one carried the
            # finish reason); only the EOF marker was lost
            req.out.put(None)
            return
        cands = [j for j in self._alive_hosts()
                 if j != i and self.hosts[j].role != "prefill"]
        if not cands:
            cands = [j for j in self._alive_hosts() if j != i]
        if not cands:
            self._fail_remote_stream(req, f"cluster host "
                                     f"{handle.host_id} lost ({reason}); "
                                     f"no live sibling")
            return
        rank = PRIORITY_RANK.get(getattr(req, "priority", None), 1)
        tgt = min(cands, key=lambda j: (self._penalty(j),
                                        self.hosts[j].load(rank), j))
        host = self.hosts[tgt]
        hist = list(req.prompt_ids) + [int(t) for t in emitted]
        cont = eng.GenRequest(
            prompt_ids=hist, params=req.params,
            max_new_tokens=remaining,
            stop_sequences=list(req.stop_sequences or []),
            ignore_eos=req.ignore_eos, grammar=req.grammar,
            priority=req.priority,
            request_id=f"{rid}~r{len(emitted)}")
        # warm-chain pull: the dead host's wire server may survive a
        # drain (and a hang); a kill -9 lost it too — then the fetch
        # fails fast and the continuation re-prefills the identical
        # history. Correct either way, warm when possible.
        if not host.remote:
            keys = host.chain_keys(hist)
            if keys:
                self._pin(tgt, rid, keys)
                if host.fed is not None:
                    host.fed.prefetch(keys)
        self._note_where(rid, tgt)
        try:
            out = host.submit(cont)
        except Exception as e:
            self._fail_remote_stream(req, f"cluster: continuation "
                                     f"submit failed: {e}")
            return
        self.remote_recovered += 1
        EVENTS.emit("migrate", rid=rid, src=("host", i),
                    dst=("host", tgt),
                    reason=("host_drain" if reason == "drain"
                            else "host_crash"),
                    kind="readmit", n_decoded=len(emitted))
        t = threading.Thread(
            target=self._bridge_continuation,
            args=(req, out, len(emitted)),
            name=f"cluster-bridge-{rid[:8]}", daemon=True)
        t.start()

    def _bridge_continuation(self, req, out: "queue.Queue", k: int):
        """Pump continuation events into the ORIGINAL stream, with
        counters shifted so the client sees one uninterrupted request
        (completion tokens continue from the crash point; prompt size
        stays the original prompt, not prompt + delivered)."""
        plen = len(req.prompt_ids)
        while True:
            ev = out.get()
            if ev is None:
                req.out.put(None)
                return
            if ev.completion_tokens:
                ev = dataclasses.replace(
                    ev, completion_tokens=ev.completion_tokens + k,
                    prompt_tokens=plen)
            req.out.put(ev)

    def _fail_remote_stream(self, req, msg: str):
        log.warning("cluster: %s", msg)
        req.out.put(eng.StreamEvent(token_id=-1, text="", logprob=0.0,
                                    error=msg, error_kind="stall"))
        req.out.put(None)

    def drain_host(self, i: int, deadline_s: float = 30.0) -> dict:
        """Graceful drain (the clean half of the crash path): the host
        stops admissions, checkpoints active chains, and hands every
        stream off; continuations re-adopt on siblings through the same
        byte-gated path a crash uses. The host leaves routing."""
        h = self.hosts[i]
        self.drains += 1
        if h.remote:
            out = h.drain(deadline_s=deadline_s)
            with self._lock:
                self._dead[i] = True
            self._digests[i] = set()
            EVENTS.emit("cluster_host_drained", host=i, **{
                k: v for k, v in out.items() if isinstance(v, int)})
            return out
        # in-process: there is no admission surface to refuse through;
        # stop the loops cooperatively and let the loop-death recovery
        # path re-adopt the streams (same ResumeEntry machinery)
        h.kill()
        deadline = time.monotonic() + 5.0
        while h.alive and time.monotonic() < deadline:
            time.sleep(0.02)
        if not self._dead[i]:
            self._recover_host(i)
        return {"streams": 0, "handed_off": 0}

    # ---------- housekeeping ----------

    def _housekeeping(self):
        while not self._hk_stop.wait(0.05):
            try:
                for i, h in enumerate(self.hosts):
                    if self._dead[i]:
                        continue
                    if h.remote:
                        # belt-and-braces: the heartbeat thread owns
                        # DEAD transitions, but a process that exited
                        # between beats is caught here
                        if h.state == FailureDetector.DEAD:
                            self._mark_remote_dead(i)
                            h.abort_streams("crash")
                    elif not h.alive:
                        self._recover_host(i)
                self._drain_disagg()
                t0 = time.monotonic()
                if t0 - self._t_digest > _DIGEST_PERIOD_S:
                    self._t_digest = t0
                    self._poll_digests()
            except Exception:
                log.exception("cluster router housekeeping failed")

    # ---------- audit ----------

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        """Cluster-wide fold of every live host's pool sweep, plus the
        transport conservation check: with the cluster quiesced no
        entry may still be in flight on any wire (a declared extra that
        never lands IS a leak)."""
        out = {"mode": "off", "checks": 0, "violations": 0,
               "leaked_pages": 0, "ledger_events": 0,
               "stream_inflight": 0}
        for i in self._alive_hosts():
            try:
                snap = self.hosts[i].kv_audit_sweep(drained=drained)
            except (OSError, WireError):
                continue            # a dead remote host has no sweep
            if snap.get("mode") != "off":
                out["mode"] = snap["mode"]
                for k in ("checks", "violations", "leaked_pages",
                          "ledger_events"):
                    out[k] += snap.get(k, 0)
            out["stream_inflight"] += snap.get("stream_inflight", 0)
        for i, h in enumerate(self.hosts):
            # dead IN-PROCESS hosts still hold a federated tier whose
            # in-flight fetches count against quiescence (the carcass
            # keeps serving); a dead remote host has no reachable tier
            if self._dead[i] and not h.remote and h.fed is not None:
                out["stream_inflight"] += h.fed.inflight
        if drained:
            if out["stream_inflight"]:
                out["violations"] += 1
                log.warning("cluster audit: %d stream fetches still in "
                            "flight after drain", out["stream_inflight"])
        return out

    # ---------- observability ----------

    def _host_snapshots(self) -> list:
        snaps = []
        for i, h in enumerate(self.hosts):
            if self._dead[i]:
                snaps.append(None)
                continue
            try:
                snaps.append(h.metrics_snapshot())
            except (OSError, WireError):
                snaps.append(None)  # unreachable remote: skip this poll
        return snaps

    def metrics(self) -> dict:
        snaps = self._host_snapshots()
        live = [s["pool"] for s in snaps if s is not None]
        out = dict(live[0]) if live else {}
        for k in ("slots_total", "slots_active", "queued", "queue_limit",
                  "total_tokens_generated", "prompt_tokens_reused"):
            out[k] = sum(m.get(k) or 0 for m in live)
        stream = {"fetches": 0, "hits": 0, "misses": 0, "pages": 0,
                  "bytes": 0, "pushes": 0, "pushed_pages": 0,
                  "corrupt_rejected": 0, "inflight": 0}
        served = {"serves": 0, "pages_out": 0, "bytes_out": 0}
        rpc = {"retries": {}, "timeouts": {}, "reconnects": 0}
        states, heartbeat = {}, {}
        for i, h in enumerate(self.hosts):
            s = snaps[i]
            if not h.remote:
                # dead in-process hosts keep their transport counters
                # (the carcass store served the recovery streams)
                fs = h.fed.stats() if h.fed is not None else {}
                ss = h.server.stats() if h.server is not None else {}
            else:
                fs = (s or {}).get("kv_stream") or {}
                ss = (s or {}).get("kv_stream_served") or {}
            for k in stream:
                stream[k] += fs.get(k, 0)
            for k in served:
                served[k] += ss.get(k, 0)
            states[str(h.host_id)] = (FailureDetector.DEAD
                                      if self._dead[i] else h.state)
            if h.remote:
                heartbeat[str(h.host_id)] = h.heartbeat_telemetry()
                hs = h.rpc_stats()
                for k in ("retries", "timeouts"):
                    for op, n in hs[k].items():
                        rpc[k][op] = rpc[k].get(op, 0) + n
                rpc["reconnects"] += hs["reconnects"]
        out["kv_stream"] = stream
        out["kv_stream_served"] = served
        out["cluster"] = {
            "hosts": len(self.hosts),
            "hosts_alive": len(self._alive_hosts()),
            "hosts_recovered": self.hosts_recovered,
            "remote_recovered": self.remote_recovered,
            "drains": self.drains,
            "routed": self._routed,
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "disagg_handoffs": self.disagg_handoffs
                               + sum(e.disagg_handoffs
                                     for h in self.hosts if not h.remote
                                     for e in h.pool._engines),
            "roles": {str(h.host_id): h.role for h in self.hosts},
            "host_states": states,
            "rpc": rpc,
            "heartbeat": heartbeat,
        }
        out["hosts"] = [{
            "host": h.host_id,
            "role": h.role,
            "alive": not self._dead[i],
            "remote": bool(h.remote),
            "state": states[str(h.host_id)],
            "address": h.address,
            "kv_stream": (h.fed.stats()
                          if not h.remote and h.fed is not None
                          else ((snaps[i] or {}).get("kv_stream") or {})),
        } for i, h in enumerate(self.hosts)]
        return out

    def kv_debug(self) -> dict:
        snaps = self._host_snapshots()
        return {
            "cluster_hosts": len(self.hosts),
            "hosts": [{
                "host": h.host_id, "role": h.role,
                "alive": not self._dead[i], "address": h.address,
                **((snaps[i] or {}).get("kv_debug") or {}),
                "kv_stream": ((snaps[i] or {}).get("kv_stream") or {}),
                "kv_serve": ((snaps[i] or {}).get("kv_stream_served")
                             or {}),
            } for i, h in enumerate(self.hosts)],
        }
