"""Cluster router over N engine-pool worker hosts (ISSUE 17).

The serving unit grows one more level: a ``ClusterRouter`` fronts N
``ClusterHost``s, each a PR-14 ``EnginePool`` (replicas + one shared
host tier) made NETWORK-ADDRESSABLE by a ``KVWireServer``
(services/kv_wire.py) and peer-aware by a ``FederatedKV``
(engine/kv_stream.py). The PR-2/3 chained block hashes already make KV
location-independent, so everything the pool does across replicas —
prefix-affinity routing, live handoff, crash recovery — lifts across
hosts with the wire as the only new mechanism:

* ROUTING: the router polls each host's chain-key DIGEST (the pool
  prefix index + host-tier membership) over the wire and routes each
  request to the host holding the longest prefix match; peer-held
  chains a probe misses still stream in at admission through the
  federated tier, so a wrong guess costs a fetch, not a re-prefill.

* DISAGGREGATION (DejaVu / Splitwise): hosts carry a ``role`` —
  ``prefill`` hosts run admission + packed prefill only and retire each
  chain to the transport after its first token; the router hands the
  ResumeEntry to a ``decode`` host which pre-fetches the streamed chain
  and splices it. Decode ITL never queues behind a prefill wave.

* CRASH RECOVERY: a host whose engine loops die (accelerator/host loop
  lost; the wire server thread keeps serving the surviving host tier —
  loop death is not store death) is harvested exactly like a dead pool
  replica, one level up: in-flight slots and parked resumes re-adopt on
  sibling hosts whose federated tier streams the warm chains over; the
  client stream never closes (PR-10's resume ≡ fresh-re-admission
  contract makes the continuation byte-identical to re-submitting
  prompt + emitted).

* AUDIT (ISSUE 15, lifted cluster-wide): chain entries in flight on the
  wire are DECLARED EXTRAS, never leaks — ``kv_audit_sweep`` folds
  every host's sweep and checks all transports are quiesced.

``cluster=off`` (the default) never constructs any of this — the
single-host PR-16 path is untouched, bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Optional

from localai_tpu.engine import engine as eng
from localai_tpu.engine.kv_stream import FederatedKV, KVStreamClient
from localai_tpu.engine.pool import EnginePool
from localai_tpu.engine.scheduler import PRIORITY_RANK, ResumeEntry
from localai_tpu.services.eventlog import EVENTS
from localai_tpu.services.faults import FAULTS
from localai_tpu.services.kv_wire import KVWireServer, WireError

log = logging.getLogger(__name__)

# how many recovery/disagg chain pins to keep mapped before releasing
# the oldest (same bound and rationale as pool._MAX_PINS)
_MAX_PINS = 16
# digest poll cadence: affinity data may be this stale; staleness costs
# a federated fetch at admission, never correctness
_DIGEST_PERIOD_S = 0.25


class ClusterHost:
    """One worker host: an EnginePool + its shared KV tiers, serving its
    host tier to peers over the wire and consulting peers on misses.

    ``role``: ``both`` (default — a full host), ``prefill`` (admission +
    packed prefill only; finished prefills retire to the transport) or
    ``decode`` (receives disagg handoffs; the router keeps fresh
    arrivals away when a prefill host is alive)."""

    def __init__(self, host_id: int, pool: EnginePool, role: str = "both",
                 bind: str = "127.0.0.1"):
        assert role in ("both", "prefill", "decode"), role
        self.host_id = int(host_id)
        self.pool = pool
        self.role = role
        self._bind = bind
        self.server: Optional[KVWireServer] = None
        self.fed: Optional[FederatedKV] = None
        self.address = ""
        self.killed = False
        # host-scoped chaos identity: in-process hosts share the global
        # FAULTS table, so replica{N}_die would collide across hosts —
        # every engine loop on this host consumes one firing of this
        # name instead (kill() arms count=len(engines))
        self._die_fault = f"cluster{self.host_id}_die"
        for e in self.pool._engines:
            e._die_fault = self._die_fault

    # ---------- construction ----------

    @classmethod
    def build(cls, model_cfg, params, tokenizer, engine_cfg=None,
              host_id: int = 0, engines: int = 1, role: str = "both",
              bind: str = "127.0.0.1", **kw):
        """One host = one EnginePool with a role-annotated config.
        Requires the preemptive scheduler (pause/resume is the handoff
        primitive) and a host tier (the transport serves it)."""
        ecfg = engine_cfg or eng.EngineConfig()
        ecfg = dataclasses.replace(ecfg, disagg=role)
        if not ecfg.preempt:
            raise ValueError("cluster hosts require preempt=1 (pause/"
                             "resume is the handoff primitive)")
        if not ecfg.kv_offload or not ecfg.kv_prefix_cache:
            raise ValueError("cluster hosts require kv_offload=1 + the "
                             "prefix cache (the wire serves the host "
                             "tier)")
        pool = EnginePool.build(model_cfg, params, tokenizer, ecfg,
                                engines=max(1, int(engines)), **kw)
        return cls(host_id, pool, role=role, bind=bind)

    # ---------- lifecycle ----------

    def start(self, precompile: bool = False) -> str:
        self.pool.start(precompile=precompile)
        store = self.pool._shared.store
        if store is None:
            raise RuntimeError("cluster host has no shared host store "
                               "(kv_offload off, or a non-paged layout?)")
        self.server = KVWireServer(store, index=self.pool._shared.index,
                                   host_id=self.host_id, bind=self._bind)
        self.address = self.server.start()
        for e in self.pool._engines:
            # continuous warm-chain checkpointing (DejaVu): active
            # chains stream to the host tier on the watermark cadence
            # so a crash leaves near-current state for siblings to pull
            e.kv_checkpoint = True
        log.info("cluster host %d (%s) serving kv at %s",
                 self.host_id, self.role, self.address)
        return self.address

    def connect_peers(self, addresses: list):
        """Attach the federated tier: this host's store misses consult
        these peers (every other host's wire address)."""
        store = self.pool._shared.store
        peers = [KVStreamClient(a, store.scope, store.page_size)
                 for a in addresses if a and a != self.address]
        self.fed = FederatedKV(store, peers).attach()
        return self.fed

    def shutdown(self):
        if self.fed is not None:
            self.fed.close()
        if self.server is not None:
            self.server.stop()
        self.pool.shutdown()

    # ---------- health / chaos ----------

    @property
    def alive(self) -> bool:
        """False once every engine loop on the host died WITHOUT
        shutdown (the pool's crash asymmetry, host-wide). The wire
        server is deliberately not consulted: loop death with a live
        store is exactly the recoverable state."""
        if self.killed and all(not e.loop_alive for e in self.pool._engines):
            return False
        dead = [e for e in self.pool._engines
                if e._thread is not None
                and not e.loop_alive and not e._stop]
        return len(dead) < len(self.pool._engines)

    def kill(self):
        """Chaos: lose this host's engine loops (accelerator gone), but
        NOT its host tier or wire server — siblings stream the warm
        chains out of the carcass. The pool's own housekeeping stops
        FIRST so it cannot race the router's harvest by failing streams
        when it finds no live sibling replica."""
        self.killed = True
        self.pool._hk_stop.set()
        FAULTS.arm(self._die_fault, count=len(self.pool._engines))
        for e in self.pool._engines:
            e._wake.set()

    # ---------- load ----------

    def load(self, rank: int = 1) -> float:
        return sum(self.pool._load(i, rank)
                   for i in range(len(self.pool._engines))
                   if not self.pool._dead[i])


class ClusterRouter:
    """Front door over N ClusterHosts: cross-host prefix-affinity
    routing, disagg handoff brokering, host crash recovery, cluster-wide
    audit. Mirrors the pool surface the servicer drives (submit /
    generate / cancel / metrics / kv_audit_sweep / shutdown)."""

    def __init__(self, hosts: list):
        assert hosts, "ClusterRouter needs at least one host"
        self.hosts = list(hosts)
        self._dead = [False] * len(hosts)
        self._lock = threading.Lock()
        self._where: dict = {}
        self._where_order: list = []
        self._digests: list = [set() for _ in hosts]
        self._clients: list = [None] * len(hosts)
        self._t_digest = 0.0
        self._pins: list = []
        self._disagg_q: "queue.Queue" = queue.Queue()
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.disagg_handoffs = 0
        self.hosts_recovered = 0
        self._routed = 0
        self._hk_stop = threading.Event()
        self._hk_thread: Optional[threading.Thread] = None

    # ---------- lifecycle ----------

    def start(self, precompile: bool = False):
        addrs = [h.start(precompile=precompile) for h in self.hosts]
        for h in self.hosts:
            h.connect_peers(addrs)
        store = self.hosts[0].pool._shared.store
        # the router's own digest/stats connections ride the same wire
        # the federated tier uses — affinity data is whatever a peer
        # could learn, no in-process shortcuts
        self._clients = [KVStreamClient(a, store.scope, store.page_size)
                         for a in addrs]
        # prefill-role engines hand finished chains to the router
        for i, h in enumerate(self.hosts):
            if h.role == "prefill":
                for e in h.pool._engines:
                    e.disagg_handoff = self._make_handoff(i)
        self._hk_thread = threading.Thread(
            target=self._housekeeping, name="cluster-router", daemon=True)
        self._hk_thread.start()

    def shutdown(self):
        self._hk_stop.set()
        if self._hk_thread is not None:
            self._hk_thread.join(timeout=5)
        self._drain_disagg()        # nothing may strand in the broker
        with self._lock:
            pins, self._pins = self._pins, []
        for host_i, rid, keys in pins:
            self._unpin(host_i, rid, keys)
        for c in self._clients:
            if c is not None:
                c.close()
        for h in self.hosts:
            try:
                h.shutdown()
            except Exception:
                log.exception("cluster host %d shutdown failed", h.host_id)

    # ---------- routing ----------

    def _alive_hosts(self):
        return [i for i in range(len(self.hosts)) if not self._dead[i]]

    def _note_where(self, rid: str, host: int):
        with self._lock:
            if rid not in self._where:
                self._where_order.append(rid)
            self._where[rid] = host
            while len(self._where_order) > 4096:
                old = self._where_order.pop(0)
                self._where.pop(old, None)

    def where(self, rid: str) -> Optional[int]:
        return self._where.get(rid)

    def _poll_digests(self):
        """Refresh the per-host chain-key sets used for affinity. A
        host that fails to answer keeps its last digest — stale beats
        empty, and the federated fetch at admission is the backstop."""
        for i in self._alive_hosts():
            c = self._clients[i]
            if c is None or not c.online():
                continue
            try:
                d = c.digest()
            except (OSError, WireError):
                continue
            self._digests[i] = {bytes.fromhex(k)
                                for k in d.get("keys", ())}

    def _match_depth(self, keys: list, digest: set) -> int:
        d = 0
        for k in keys:
            if k not in digest:
                break
            d += 1
        return d

    def _route(self, req, host: Optional[int] = None) -> int:
        alive = self._alive_hosts()
        if not alive:
            raise RuntimeError("cluster: no live hosts")
        if host is not None:
            if host not in alive:
                raise RuntimeError(f"cluster: host {host} is not live")
            self._routed += 1
            return host
        # fresh arrivals need a prefill-capable host; pure-decode hosts
        # receive work only through the disagg broker (unless they are
        # all that's left — serving beats failing)
        cands = [i for i in alive if self.hosts[i].role != "decode"]
        if not cands:
            cands = alive
        rank = PRIORITY_RANK.get(getattr(req, "priority", None), 1)
        self._routed += 1
        if len(cands) > 1 and getattr(req, "prompt_ids", None):
            pc = self.hosts[cands[0]].pool._engines[0]._pcache
            if pc is not None:
                keys = list(pc.chain_keys(req.prompt_ids))
                best_i, best_d = None, 0
                for i in cands:
                    d = self._match_depth(keys, self._digests[i])
                    if d > best_d or (d == best_d and d > 0
                                      and best_i is not None
                                      and self.hosts[i].load(rank)
                                      < self.hosts[best_i].load(rank)):
                        best_i, best_d = i, d
                if best_i is not None and best_d > 0:
                    self.affinity_hits += 1
                    return best_i
                self.affinity_misses += 1
        return min(cands, key=lambda i: (self.hosts[i].load(rank), i))

    def submit(self, req, host: Optional[int] = None) -> "queue.Queue":
        i = self._route(req, host=host)
        self._note_where(req.request_id, i)
        return self.hosts[i].pool.submit(req)

    def generate(self, req, host: Optional[int] = None):
        out = self.submit(req, host=host)
        while True:
            ev = out.get()
            if ev is None:
                return
            yield ev

    def cancel(self, request_id: str):
        i = self._where.get(request_id)
        if i is not None and not self._dead[i]:
            self.hosts[i].pool.cancel(request_id)
        else:
            for i in self._alive_hosts():
                self.hosts[i].pool.cancel(request_id)

    # ---------- chain pinning ----------

    def _pin(self, host_i: int, rid: str, keys: list):
        """Map recovered/disagg chain keys in ``host_i``'s store under
        ("cluster", rid) so budget eviction can't beat the adoptive
        replica's restore; bounded, oldest released first."""
        if not keys:
            return
        store = self.hosts[host_i].pool._shared.store
        owner = ("cluster", rid)
        for k in keys:
            store.map_key(k, owner)
        drop = []
        with self._lock:
            self._pins.append((host_i, rid, keys))
            while len(self._pins) > _MAX_PINS:
                drop.append(self._pins.pop(0))
        for old in drop:
            self._unpin(*old)

    def _unpin(self, host_i: int, rid: str, keys: list):
        store = self.hosts[host_i].pool._shared.store
        owner = ("cluster", rid)
        for k in keys:
            store.unmap_key(k, owner)

    # ---------- disaggregation ----------

    def _make_handoff(self, src_host: int):
        """The callback a prefill-role engine fires (on its loop thread)
        with a finished-prefill ResumeEntry: enqueue for the router
        thread — the loop must not block on a peer fetch."""
        def handoff(entry, keys, _src=src_host):
            self._disagg_q.put((_src, entry, keys))
        return handoff

    def _drain_disagg(self):
        while True:
            try:
                src, entry, keys = self._disagg_q.get_nowait()
            except queue.Empty:
                return
            self._place_disagg(src, entry, keys)

    def _place_disagg(self, src: int, entry: ResumeEntry, keys: list):
        rid = entry.req.request_id
        cands = [i for i in self._alive_hosts()
                 if i != src and self.hosts[i].role != "prefill"]
        if not cands:
            # no decode host: hand the request back — the source engine
            # decodes it to completion (never strand a client stream)
            entry.req._no_disagg = True
            if not self._dead[src] and self._adopt_on(src, rid, entry):
                return
            for i in self._alive_hosts():
                if self._adopt_on(i, rid, entry):
                    return
            self.hosts[src].pool._fail_stream(
                entry.req, "disagg: no host can adopt")
            return
        rank = PRIORITY_RANK.get(entry.priority, 1)
        tgt = min(cands, key=lambda i: (self.hosts[i].load(rank), i))
        host = self.hosts[tgt]
        # stream the prefilled chain over BEFORE admission so the decode
        # host splices local, verified bytes (prefetch > demand-fetch:
        # one round-trip for the whole chain, off the engine loop)
        self._pin(tgt, rid, keys)
        if host.fed is not None and keys:
            host.fed.prefetch(keys)
        if not self._adopt_on(tgt, rid, entry):
            entry.req._no_disagg = True
            if self._dead[src] or not self._adopt_on(src, rid, entry):
                self.hosts[src].pool._fail_stream(
                    entry.req, "disagg: no host can adopt")
            return
        self.disagg_handoffs += 1
        # the source kept the chain mapped under ("disagg", rid) from
        # its force-offload; the decode host holds its own copy now
        src_store = self.hosts[src].pool._shared.store
        for k in keys:
            src_store.unmap_key(k, ("disagg", rid))
        EVENTS.emit("disagg_handoff", rid=rid, src=src, dst=tgt,
                    n_decoded=entry.n_decoded, keys=len(keys))

    def _adopt_on(self, host_i: int, rid: str, entry: ResumeEntry) -> bool:
        """Adopt a ResumeEntry on the least-loaded live replica of one
        host; the pool's note_where keeps its own cancel path working."""
        pool = self.hosts[host_i].pool
        rank = PRIORITY_RANK.get(entry.priority, 1)
        reps = [i for i in range(len(pool._engines)) if not pool._dead[i]]
        if not reps:
            return False
        r = min(reps, key=lambda i: (pool._load(i, rank), i))
        if not pool._engines[r].adopt_resume(entry):
            return False
        pool._note_where(rid, r)
        self._note_where(rid, host_i)
        aud = pool._engines[r]._kv_audit
        if aud is not None:
            aud.ledger.record("adopt", slot=("cluster", host_i), rid=rid)
        return True

    # ---------- crash recovery ----------

    def _recover_host(self, i: int):
        """A host's engine loops died (its device tiers are gone; its
        host tier and wire server survive). Everything it was serving
        re-adopts on sibling hosts: warm chains stream over the wire
        from the carcass store, cold ones re-prefill the identical
        history. Client streams never close — the StreamEvent queues
        ride the ResumeEntries (pool._recover_replica, one level up)."""
        host = self.hosts[i]
        self._dead[i] = True
        host.pool._hk_stop.set()    # no same-host recovery races
        self._digests[i] = set()
        EVENTS.emit("cluster_host_down", host=i, role=host.role)
        log.warning("cluster: host %d loop(s) died; recovering", i)
        recovered = failed = 0
        for e in host.pool._engines:
            r = e.replica_id
            if r < len(host.pool._dead):
                host.pool._dead[r] = True
            if e._emitter is not None:
                try:
                    e._emitter.drain(2.0)
                except Exception:
                    pass
            for slot, s in enumerate(e.slots):
                if s is None:
                    continue
                e.slots[slot] = None
                rid = s.req.request_id
                ok = False
                if e._sched is not None and e._preempt_eligible(slot, s):
                    hist = list(e._cache_tokens[slot])
                    if len(hist) < s.prompt_len:
                        hist = list(s.req.prompt_ids) + list(s.generated)
                    entry = ResumeEntry(
                        req=s.req, ids=hist, priority=s.req.priority,
                        generated=list(s.generated), n_decoded=s.n_decoded,
                        prompt_len=s.prompt_len, detok=s.detok,
                        held_text=s.held_text, t_start=s.t_start,
                        t_first_token=s.t_first_token or None,
                        t_prefill_ms=s.t_prefill_ms, mu=float(e.mu[slot]),
                        preempt_count=s.preempts)
                    ok = self._adopt_on_sibling_host(rid, entry, src=i)
                if ok:
                    recovered += 1
                else:
                    failed += 1
                    host.pool._fail_stream(
                        s.req, f"cluster host {i} died; request not "
                               f"recoverable on a sibling host")
            if e._sched is not None:
                for entry in e._sched.drain_parked():
                    if self._adopt_on_sibling_host(
                            entry.req.request_id, entry, src=i):
                        recovered += 1
                    else:
                        failed += 1
                        host.pool._fail_stream(
                            entry.req, f"cluster host {i} died")
            while True:
                try:
                    r2 = e._queue.get_nowait()
                except queue.Empty:
                    break
                try:
                    tgt = self._route(r2)
                    self._note_where(r2.request_id, tgt)
                    self.hosts[tgt].pool.submit(r2)
                    recovered += 1
                except Exception:
                    failed += 1
                    host.pool._fail_stream(
                        r2, f"cluster host {i} died; no live sibling")
        self.hosts_recovered += 1
        EVENTS.emit("cluster_host_recovered", host=i,
                    recovered=recovered, failed=failed)
        log.warning("cluster: host %d recovery done "
                    "(recovered=%d failed=%d)", i, recovered, failed)

    def _adopt_on_sibling_host(self, rid: str, entry: ResumeEntry,
                               src: int) -> bool:
        cands = [i for i in self._alive_hosts()
                 if i != src and self.hosts[i].role != "prefill"]
        if not cands:
            cands = [i for i in self._alive_hosts() if i != src]
        if not cands:
            return False
        rank = PRIORITY_RANK.get(entry.priority, 1)
        tgt = min(cands, key=lambda i: (self.hosts[i].load(rank), i))
        host = self.hosts[tgt]
        pc = host.pool._engines[0]._pcache
        keys = list(pc.chain_keys(entry.ids)) if pc is not None else []
        if keys:
            self._pin(tgt, rid, keys)
            if host.fed is not None:
                # pull the dead host's checkpointed chain into the
                # target's local tier before admission restores it
                host.fed.prefetch(keys)
        if not self._adopt_on(tgt, rid, entry):
            return False
        EVENTS.emit("migrate", rid=rid, src=("host", src),
                    dst=("host", tgt), reason="host_crash", kind="resume",
                    n_decoded=entry.n_decoded)
        return True

    # ---------- housekeeping ----------

    def _housekeeping(self):
        while not self._hk_stop.wait(0.05):
            try:
                for i, h in enumerate(self.hosts):
                    if not self._dead[i] and not h.alive:
                        self._recover_host(i)
                self._drain_disagg()
                t0 = time.monotonic()
                if t0 - self._t_digest > _DIGEST_PERIOD_S:
                    self._t_digest = t0
                    self._poll_digests()
            except Exception:
                log.exception("cluster router housekeeping failed")

    # ---------- audit ----------

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        """Cluster-wide fold of every live host's pool sweep, plus the
        transport conservation check: with the cluster quiesced no
        entry may still be in flight on any wire (a declared extra that
        never lands IS a leak)."""
        out = {"mode": "off", "checks": 0, "violations": 0,
               "leaked_pages": 0, "ledger_events": 0,
               "stream_inflight": 0}
        for i in self._alive_hosts():
            snap = self.hosts[i].pool.kv_audit_sweep(drained=drained)
            if snap.get("mode") != "off":
                out["mode"] = snap["mode"]
                for k in ("checks", "violations", "leaked_pages",
                          "ledger_events"):
                    out[k] += snap.get(k, 0)
        for h in self.hosts:
            if h.fed is not None:
                out["stream_inflight"] += h.fed.inflight
        if drained:
            if out["stream_inflight"]:
                out["violations"] += 1
                log.warning("cluster audit: %d stream fetches still in "
                            "flight after drain", out["stream_inflight"])
        return out

    # ---------- observability ----------

    def metrics(self) -> dict:
        ms = [h.pool.metrics() if not self._dead[i] else None
              for i, h in enumerate(self.hosts)]
        live = [m for m in ms if m is not None]
        out = dict(live[0]) if live else {}
        for k in ("slots_total", "slots_active", "queued",
                  "total_tokens_generated", "prompt_tokens_reused"):
            out[k] = sum(m.get(k) or 0 for m in live)
        stream = {"fetches": 0, "hits": 0, "misses": 0, "pages": 0,
                  "bytes": 0, "pushes": 0, "pushed_pages": 0,
                  "corrupt_rejected": 0, "inflight": 0}
        served = {"serves": 0, "pages_out": 0, "bytes_out": 0}
        for h in self.hosts:
            if h.fed is not None:
                fs = h.fed.stats()
                for k in stream:
                    stream[k] += fs.get(k, 0)
            if h.server is not None:
                ss = h.server.stats()
                for k in served:
                    served[k] += ss.get(k, 0)
        out["kv_stream"] = stream
        out["kv_stream_served"] = served
        out["cluster"] = {
            "hosts": len(self.hosts),
            "hosts_alive": len(self._alive_hosts()),
            "hosts_recovered": self.hosts_recovered,
            "routed": self._routed,
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "disagg_handoffs": self.disagg_handoffs
                               + sum(e.disagg_handoffs
                                     for h in self.hosts
                                     for e in h.pool._engines),
            "roles": {str(h.host_id): h.role for h in self.hosts},
        }
        out["hosts"] = [{
            "host": h.host_id,
            "role": h.role,
            "alive": not self._dead[i],
            "address": h.address,
            "kv_stream": (h.fed.stats() if h.fed is not None else {}),
        } for i, h in enumerate(self.hosts)]
        return out

    def kv_debug(self) -> dict:
        return {
            "cluster_hosts": len(self.hosts),
            "hosts": [{
                "host": h.host_id, "role": h.role,
                "alive": not self._dead[i], "address": h.address,
                **h.pool.kv_debug(),
                "kv_stream": (h.fed.stats() if h.fed is not None else {}),
                "kv_serve": (h.server.stats()
                             if h.server is not None else {}),
            } for i, h in enumerate(self.hosts)],
        }
