"""LoRA adapter merging at weight-load time.

Reference plumbs LoraAdapter/LoraBase/LoraScale end-to-end
(backend/backend.proto:146-148,207-208) and applies adapters inside
llama.cpp (grpc-server.cpp:2295-2309). The TPU-native equivalent is
simpler and free at serve time: JAX params are a plain pytree, so the
low-rank update ``W += scale * (B @ A)`` is merged into each stacked
leaf as the checkpoint streams onto the device — no extra HBM, no extra
matmuls per step.

Adapter layout: HF PEFT — ``adapter_config.json`` (r, lora_alpha,
target_modules) + ``adapter_model.safetensors`` with tensors named
``...layers.{i}.self_attn.q_proj.lora_A.weight`` ([r, in]) and
``....lora_B.weight`` ([out, r]). Effective scale is
``lora_scale * lora_alpha / r`` (PEFT semantics; lora_scale is the
user knob, default 1.0).
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

import numpy as np

# our stacked-leaf name -> HF module suffix
_LEAF_TO_MODULE = {
    "wq": "self_attn.q_proj",
    "wk": "self_attn.k_proj",
    "wv": "self_attn.v_proj",
    "wo": "self_attn.o_proj",
    "w_gate": "mlp.gate_proj",
    "w_up": "mlp.up_proj",
    "w_down": "mlp.down_proj",
}

_NAME_RE = re.compile(
    r"layers\.(\d+)\.(self_attn\.[qkvo]_proj|mlp\.(?:gate|up|down)_proj)"
    r"\.lora_(A|B)\.weight$")


class LoraAdapter:
    """Parsed adapter: per-(layer, module) A/B matrices + effective scale."""

    def __init__(self, path: str, scale: float = 1.0):
        from safetensors import safe_open

        cfg_path = os.path.join(path, "adapter_config.json")
        cfg = {}
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
        r = float(cfg.get("r", 8))
        alpha = float(cfg.get("lora_alpha", r))
        self.scale = (scale or 1.0) * alpha / max(r, 1.0)

        st = None
        for name in ("adapter_model.safetensors", "adapter.safetensors"):
            p = os.path.join(path, name)
            if os.path.exists(p):
                st = p
                break
        if st is None:
            raise FileNotFoundError(
                f"{path}: no adapter_model.safetensors found")
        # (module, layer) -> {"A": [r, in], "B": [out, r]}
        self.mats: dict = {}
        with safe_open(st, framework="np") as f:
            for name in f.keys():
                m = _NAME_RE.search(name)
                if not m:
                    continue
                li, module, ab = int(m.group(1)), m.group(2), m.group(3)
                self.mats.setdefault((module, li), {})[ab] = f.get_tensor(name)

    def targets_leaf(self, leaf_name: str, num_layers: int) -> bool:
        module = _LEAF_TO_MODULE.get(leaf_name)
        if module is None:
            return False
        return any((module, i) in self.mats for i in range(num_layers))

    def apply_to_leaf(self, leaf_name: str, num_layers: int,
                      arr32: np.ndarray) -> None:
        """Add scale*(B@A).T IN PLACE into the float32 stacked leaf
        [L, in, out] — per-layer, no full-size delta buffer (a 70B leaf's
        extra float32 copy is tens of GB; see r3 review)."""
        module = _LEAF_TO_MODULE[leaf_name]
        for i in range(num_layers):
            ab = self.mats.get((module, i))
            if not ab or "A" not in ab or "B" not in ab:
                continue
            A = np.asarray(ab["A"], np.float32)     # [r, in]
            B = np.asarray(ab["B"], np.float32)     # [out, r]
            # leaf is [in, out] (transposed HF weight): delta = (B@A).T
            arr32[i] += (B @ A).T * self.scale


def maybe_adapter(path: str, scale: float = 1.0) -> Optional[LoraAdapter]:
    if not path:
        return None
    return LoraAdapter(path, scale)
