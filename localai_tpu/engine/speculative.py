"""Speculative decoding: draft-model propose, target-model verify.

Capability parity: the reference plumbs draft-model fields end-to-end
(reference: backend.proto DraftModel, backend_config.go DraftModel) into
llama.cpp's speculative sampling. TPU re-design: one ROUND is a single
compiled program — the draft model autoregressively proposes D tokens
(lax.scan of decode steps over its own KV cache), then the target model
scores all D+1 positions in ONE batched forward (prefill with
return_all_logits) and greedy acceptance keeps the matched prefix plus
the target's correction/bonus token. Greedy speculation is LOSSLESS: the
emitted stream is bit-identical to plain greedy decoding of the target
model, whatever the draft proposes — rejected drafts only waste the
round's spare compute.

Cache invariant (both models): rows [0, length) hold the accepted
context, and the CURRENT token (last emitted) is not yet ingested; the
round ingests it in both models as its first input. Rows written for
rejected proposals sit above the new length and are masked/overwritten.

The engine uses speculation only when every active slot is greedy and
ungrammared (stochastic speculative sampling needs rejection-sampling
acceptance; a documented follow-up) and falls back to normal bursts
otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from localai_tpu.models import llama


def spec_round(params, dparams, cfg: llama.LlamaConfig, dcfg: llama.LlamaConfig,
               tokens, lengths, ck, cv, dck, dcv, active, n_draft: int):
    """One speculative round for all slots.

    tokens [S]: current (not yet ingested) token per slot; lengths [S];
    ck/cv target cache; dck/dcv draft cache; active [S] bool.
    Returns (out [S, D+1] emitted tokens, n_out [S] valid counts,
    ck, cv, dck, dcv, lengths_new).
    """
    from localai_tpu.ops import kvcache

    S = tokens.shape[0]
    D = n_draft
    C = kvcache.shape(ck)[2]
    dC = kvcache.shape(dck)[2]

    # 1. draft proposes D tokens (its cache ingests current + ALL proposals:
    # D+1 steps so the last proposal's KV row exists when fully accepted —
    # otherwise the draft cache carries a permanent hole inside the
    # accepted context and acceptance quality decays)
    def dstep(carry, _):
        tok, dl, dck, dcv = carry
        wl = jnp.where(active, dl, dC)
        logits, dck, dcv = llama.decode_step(dparams, dcfg, tok, wl, dck, dcv)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, dl + active.astype(jnp.int32), dck, dcv), nxt

    (_, _, dck, dcv), proposals = jax.lax.scan(
        dstep, (tokens, lengths, dck, dcv), None, length=D + 1)
    drafts = proposals[:D].T                            # [S, D]

    # 2. target scores current + proposals in one forward
    tin = jnp.concatenate([tokens[:, None], drafts], axis=1)   # [S, D+1]
    seq = jnp.full((S,), D + 1, jnp.int32)
    start = jnp.where(active, lengths, C)  # inactive rows -> OOB, dropped
    all_logits, ck, cv = llama.prefill(
        params, cfg, tin, seq, ck, cv, jnp.arange(S, dtype=jnp.int32), start,
        continued=True, return_all_logits=True)
    greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)  # [S, D+1]

    # 3. greedy acceptance: longest prefix where draft matches target
    match = (drafts == greedy[:, :D]).astype(jnp.int32)         # [S, D]
    acc_prefix = jnp.cumprod(match, axis=1)
    k = jnp.sum(acc_prefix, axis=1)                             # [S] accepted
    bonus = jnp.take_along_axis(greedy, k[:, None], axis=1)[:, 0]
    pos = jnp.arange(D + 1, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1)
    out = jnp.where(pos < k[:, None], drafts_pad,
                    jnp.where(pos == k[:, None], bonus[:, None], 0))
    # matching logprobs for the emitted tokens (target distribution)
    logp_all = jax.nn.log_softmax(all_logits, axis=-1)
    out_lp = jnp.take_along_axis(logp_all, out[:, :, None], axis=2)[:, :, 0]

    n_out = (k + 1) * active.astype(jnp.int32)
    lengths_new = lengths + n_out
    return out, out_lp, n_out, ck, cv, dck, dcv, lengths_new
