"""Speculative decoding: propose D tokens cheaply, verify with the target.

Capability parity: the reference plumbs draft-model fields end-to-end
(reference: backend.proto DraftModel, backend_config.go DraftModel) into
llama.cpp's speculative sampling. TPU re-design: one ROUND is a single
compiled program — a DRAFTER proposes D tokens, then the target model
scores all D+1 positions in ONE batched forward (prefill with
return_all_logits) and greedy acceptance keeps the matched prefix plus
the target's correction/bonus token. Greedy speculation is LOSSLESS: the
emitted stream is bit-identical to plain greedy decoding of the target
model, whatever the drafter proposes — rejected drafts only waste the
round's spare compute.

Two drafters (engine knob ``draft``):

  * ``model``: a second, smaller llama-family model autoregressively
    proposes via a lax.scan of decode steps over its own KV cache
    (draft_propose).
  * ``ngram``: prompt-lookup / n-gram SELF-speculation (ngram_propose) —
    the slot's trailing n-gram is matched against its own prompt+emitted
    history (the device-side penalty ring), and the continuation after
    the most recent match is proposed. No second model, no draft KV, so
    every llama-family greedy request can speculate by default. A miss
    proposes a repeat of the current token — verification rejects it,
    so the fallback costs nothing but the round's spare compute.

Cache invariant (target and draft models alike): rows [0, length) hold
the accepted context, and the CURRENT token (last emitted) is not yet
ingested; a round ingests it as its first input. Rows written for
rejected proposals sit above the new length and are masked/overwritten.

Since ISSUE 13 speculation is a packed citizen of the engine's fused
decode tick (engine.py _spec_tick_body): spec-eligible slots take a
propose+verify round while non-spec neighbors take a plain decode step
through position 0 of the very same ragged verify forward — one chained
dispatch, no whole-engine spec/burst alternation.

Since ISSUE 18 sampled (temperature>0) slots speculate too, via
rejection-sampling acceptance (accept_sampled, leviathan-style): draft
token x_j is accepted with probability min(1, p(x_j)/q(x_j)) against
the FILTERED target distribution p (sampling.verify_dist — the exact
law plain `sample` draws from), and the first rejection resamples from
the residual norm(max(0, p - q)). Our drafters are deterministic (n-gram
lookup / greedy draft model), so q is a one-hot: acceptance degenerates
to u < p(x_j) and the residual is p with the draft token zeroed. Sampled
speculation is lossless IN DISTRIBUTION (chi-square-tested), not
byte-identical — the spec tick consumes the slot's RNG key on a
different schedule (one acceptance+resample draw per round vs one
categorical per token), so a given seed yields a different, equally
distributed stream than spec-off — and since every executed round
advances the key, the bytes also depend on how rounds partition into
dispatches under load. Greedy slots keep accept_greedy and remain
bit-identical to plain greedy decoding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from localai_tpu.models import llama


def ngram_propose(tokens, ring, ring_pos, n_draft: int, ngram: int):
    """Prompt-lookup proposals from the slot's own token history.

    tokens [S]: current (not yet ingested) token per slot; ring
    [S, RING_N] / ring_pos [S]: the penalty ring (engine/sampling.py) —
    prompt-seeded at admission and updated with every emitted token, so
    it IS the trailing prompt+generation history, already device-side.
    Returns proposals [S, D] int32.

    The trailing ``ngram``-gram (current token last) is compared against
    every aligned window of the chronological history; the continuation
    after the MOST RECENT match is proposed, clipped at the history end
    (self-overlap is deliberate — repetitive continuations are exactly
    what prompt-lookup exploits). No valid match (including short
    histories still holding -1 seed entries) proposes a repeat of the
    current token, which the verify round rejects — lossless either way.
    """
    S, N = ring.shape
    D, G = n_draft, ngram
    ar = jnp.arange(N, dtype=jnp.int32)
    # chronological view, oldest -> newest: the ring writes at
    # pos % N then advances, so entry (pos + j) % N ages left-to-right
    # and (pos - 1) % N — chronological index N-1 — is the current token
    idx = (ring_pos[:, None] + ar[None, :]) % N
    hist = jnp.take_along_axis(jnp.asarray(ring), idx, axis=1)   # [S, N]
    trail = hist[:, N - G:]                                      # [S, G]
    starts = jnp.arange(N - G, dtype=jnp.int32)                  # [P]
    win = starts[:, None] + jnp.arange(G, dtype=jnp.int32)[None, :]
    wins = hist[:, win]                                          # [S, P, G]
    ok = jnp.all(wins == trail[:, None, :], axis=-1)
    ok &= jnp.all(wins >= 0, axis=-1)              # unwritten seed entries
    ok &= jnp.all(trail >= 0, axis=-1)[:, None]    # short history: no match
    p_best = jnp.max(jnp.where(ok, starts[None, :], -1), axis=1)  # [S]
    has = p_best >= 0
    cont = jnp.minimum(
        p_best[:, None] + G + jnp.arange(D, dtype=jnp.int32)[None, :], N - 1)
    props = jnp.take_along_axis(hist, cont, axis=1)              # [S, D]
    return jnp.where(has[:, None], props,
                     jnp.asarray(tokens)[:, None]).astype(jnp.int32)


def draft_propose(dparams, dcfg: llama.LlamaConfig, tokens, lengths,
                  dck, dcv, active, n_draft: int):
    """Draft-model proposals: D+1 autoregressive greedy decode steps.

    The draft cache ingests current + ALL proposals (D+1 steps, so the
    last proposal's KV row exists when fully accepted — otherwise the
    draft cache carries a permanent hole inside the accepted context and
    acceptance quality decays). Inactive slots write at the OOB row so
    the scatter drops (contiguous and paged layouts alike).
    Returns (drafts [S, D], dck, dcv).
    """
    from localai_tpu.ops import kvcache

    dC = kvcache.shape(dck)[2]

    def dstep(carry, _):
        tok, dl, dck, dcv = carry
        wl = jnp.where(active, dl, dC)
        logits, dck, dcv = llama.decode_step(dparams, dcfg, tok, wl, dck, dcv)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, dl + active.astype(jnp.int32), dck, dcv), nxt

    (_, _, dck, dcv), proposals = jax.lax.scan(
        dstep, (tokens, lengths, dck, dcv), None, length=n_draft + 1)
    return proposals[:n_draft].T, dck, dcv


def accept_greedy(drafts, greedy, active):
    """Greedy acceptance: longest matched prefix + the target's bonus.

    drafts [S, D] proposals; greedy [S, D+1] the target's greedy picks at
    every position; active [S] bool. Returns (out [S, D+1] emitted
    tokens, n_out [S] valid counts = matched prefix + 1 bonus, k [S]
    accepted-draft counts).
    """
    S, D = drafts.shape
    match = (drafts == greedy[:, :D]).astype(jnp.int32)
    acc_prefix = jnp.cumprod(match, axis=1)
    k = jnp.sum(acc_prefix, axis=1)                             # [S]
    bonus = jnp.take_along_axis(greedy, k[:, None], axis=1)[:, 0]
    pos = jnp.arange(D + 1, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1)
    out = jnp.where(pos < k[:, None], drafts_pad,
                    jnp.where(pos == k[:, None], bonus[:, None], 0))
    n_out = (k + 1) * active.astype(jnp.int32)
    return out, n_out, k


def accept_sampled(drafts, target_probs, draft_probs, rng_keys, active):
    """Stochastic (rejection-sampling) acceptance for sampled slots.

    drafts [S, D] proposals; target_probs [S, D+1, V] the FILTERED target
    distribution at every verify position (each row sums to 1 over the
    candidate support — sampling.verify_dist scattered to vocab);
    draft_probs [S, D, V] the drafter's proposal distribution, or None
    for deterministic drafters (n-gram / greedy draft model: q is a
    one-hot at the draft token, so acceptance is u < p(x_j) and the
    residual is p with the draft token zeroed); rng_keys [S, 2] uint32;
    active [S] bool.

    Accept draft x_j with probability min(1, p(x_j)/q(x_j)); the first
    rejection at position j emits one token resampled from
    norm(max(0, p_j - q_j)); full acceptance draws the bonus from
    p_D. Exactly ONE categorical draw and D uniforms are consumed per
    slot per round, unconditionally — the RNG schedule is data-
    independent, so a fixed seed ladder replays bit-identically.
    Inactive slots keep their keys untouched.

    Returns (out [S, D+1] emitted tokens, n_out [S] valid counts =
    accepted prefix + 1, k [S] accepted-draft counts, new_keys [S, 2]).
    """
    S, D = drafts.shape
    pos = jnp.arange(D + 1, dtype=jnp.int32)[None, :]

    def one(key_data, dr, tp, qp):
        key = jax.random.wrap_key_data(key_data)
        key, sub_u, sub_c = jax.random.split(key, 3)
        u = jax.random.uniform(sub_u, (D,))
        p_dr = jnp.take_along_axis(tp[:D], dr[:, None], axis=1)[:, 0]  # [D]
        if qp is None:
            ratio = p_dr
            resid = tp[:D].at[jnp.arange(D, dtype=jnp.int32), dr].set(0.0)
        else:
            q_dr = jnp.take_along_axis(qp, dr[:, None], axis=1)[:, 0]
            ratio = jnp.minimum(1.0, p_dr / jnp.clip(q_dr, 1e-20))
            resid = jnp.clip(tp[:D] - qp, 0.0)
        accept = (u < ratio).astype(jnp.int32)
        k = jnp.sum(jnp.cumprod(accept))
        # final token: residual row k on rejection, bonus row D otherwise
        fin = jnp.where(k < D, resid[jnp.minimum(k, D - 1)], tp[D])
        # numerically-empty residual (p==q up to rounding): fall back to
        # the target row so the categorical stays well-defined
        fin = jnp.where(jnp.any(fin > 0), fin, tp[jnp.minimum(k, D)])
        fin_logits = jnp.where(fin > 0, jnp.log(fin), -jnp.inf)
        choice = jax.random.categorical(sub_c, fin_logits).astype(jnp.int32)
        return jax.random.key_data(key), choice, k

    if draft_probs is None:
        new_keys, final_tok, k = jax.vmap(
            lambda kd, dr, tp: one(kd, dr, tp, None))(
                rng_keys, drafts, target_probs)
    else:
        new_keys, final_tok, k = jax.vmap(one)(
            rng_keys, drafts, target_probs, draft_probs)

    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1)
    out = jnp.where(pos < k[:, None], drafts_pad,
                    jnp.where(pos == k[:, None], final_tok[:, None], 0))
    n_out = (k + 1) * active.astype(jnp.int32)
    new_keys = jnp.where(active[:, None], new_keys, rng_keys)
    return out.astype(jnp.int32), n_out, k, new_keys


def two_sample_chi2(counts_a, counts_b, min_expected: float = 5.0):
    """Two-sample chi-square homogeneity test (host-side numpy).

    counts_a/counts_b: per-category observation counts of the two
    samples (e.g. token-id frequencies of a spec-sampled vs a
    plain-sampled run). Categories whose combined count is below
    ``min_expected`` are pooled into one bin so the asymptotic
    approximation holds. Returns (stat, dof, p_value); p ~ U[0,1] when
    both samples draw from the same law — the distribution-preservation
    gate asserts p above a small alpha. Uses the unequal-N form
    chi2 = sum (K1*a_i - K2*b_i)^2 / (a_i + b_i) with K1 = sqrt(NB/NA),
    K2 = sqrt(NA/NB).
    """
    import numpy as np

    a = np.asarray(counts_a, np.float64).ravel()
    b = np.asarray(counts_b, np.float64).ravel()
    tot = a + b
    big = tot >= min_expected
    a = np.concatenate([a[big], [a[~big].sum()]])
    b = np.concatenate([b[big], [b[~big].sum()]])
    keep = (a + b) > 0
    a, b = a[keep], b[keep]
    if len(a) < 2 or a.sum() == 0 or b.sum() == 0:
        return 0.0, 0, 1.0
    k1 = np.sqrt(b.sum() / a.sum())
    k2 = np.sqrt(a.sum() / b.sum())
    stat = float(np.sum((k1 * a - k2 * b) ** 2 / (a + b)))
    dof = int(len(a) - 1)
    try:
        from scipy.stats import chi2 as _chi2
        p = float(_chi2.sf(stat, dof))
    except Exception:   # pragma: no cover — scipy ships with jax
        from jax.scipy.special import gammaincc
        p = float(gammaincc(dof / 2.0, stat / 2.0))
    return stat, dof, p


def spec_round(params, dparams, cfg: llama.LlamaConfig, dcfg: llama.LlamaConfig,
               tokens, lengths, ck, cv, dck, dcv, active, n_draft: int):
    """One standalone draft-model speculative round for all slots.

    tokens [S]: current (not yet ingested) token per slot; lengths [S];
    ck/cv target cache; dck/dcv draft cache; active [S] bool.
    Returns (out [S, D+1] emitted tokens, out_lp, n_out [S] valid counts,
    ck, cv, dck, dcv, lengths_new). Kept as the minimal reference round
    (unit-tested directly); the engine's serving path runs the fused
    multi-round tick instead (engine.py _spec_tick_body), which composes
    these same propose/verify/accept pieces per round.
    """
    from localai_tpu.ops import kvcache

    S = tokens.shape[0]
    D = n_draft
    C = kvcache.shape(ck)[2]

    # 1. drafter proposes D tokens
    drafts, dck, dcv = draft_propose(dparams, dcfg, tokens, lengths,
                                     dck, dcv, active, D)

    # 2. target scores current + proposals in one forward
    tin = jnp.concatenate([tokens[:, None], drafts], axis=1)   # [S, D+1]
    seq = jnp.full((S,), D + 1, jnp.int32)
    start = jnp.where(active, lengths, C)  # inactive rows -> OOB, dropped
    all_logits, ck, cv = llama.prefill(
        params, cfg, tin, seq, ck, cv, jnp.arange(S, dtype=jnp.int32), start,
        continued=True, return_all_logits=True)
    greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)  # [S, D+1]

    # 3. greedy acceptance: longest prefix where draft matches target
    out, n_out, _k = accept_greedy(drafts, greedy, active)
    # matching logprobs for the emitted tokens (target distribution)
    logp_all = jax.nn.log_softmax(all_logits, axis=-1)
    out_lp = jnp.take_along_axis(logp_all, out[:, :, None], axis=2)[:, :, 0]

    lengths_new = lengths + n_out
    return out, out_lp, n_out, ck, cv, dck, dcv, lengths_new
