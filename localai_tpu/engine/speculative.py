"""Speculative decoding: propose D tokens cheaply, verify with the target.

Capability parity: the reference plumbs draft-model fields end-to-end
(reference: backend.proto DraftModel, backend_config.go DraftModel) into
llama.cpp's speculative sampling. TPU re-design: one ROUND is a single
compiled program — a DRAFTER proposes D tokens, then the target model
scores all D+1 positions in ONE batched forward (prefill with
return_all_logits) and greedy acceptance keeps the matched prefix plus
the target's correction/bonus token. Greedy speculation is LOSSLESS: the
emitted stream is bit-identical to plain greedy decoding of the target
model, whatever the drafter proposes — rejected drafts only waste the
round's spare compute.

Two drafters (engine knob ``draft``):

  * ``model``: a second, smaller llama-family model autoregressively
    proposes via a lax.scan of decode steps over its own KV cache
    (draft_propose).
  * ``ngram``: prompt-lookup / n-gram SELF-speculation (ngram_propose) —
    the slot's trailing n-gram is matched against its own prompt+emitted
    history (the device-side penalty ring), and the continuation after
    the most recent match is proposed. No second model, no draft KV, so
    every llama-family greedy request can speculate by default. A miss
    proposes a repeat of the current token — verification rejects it,
    so the fallback costs nothing but the round's spare compute.

Cache invariant (target and draft models alike): rows [0, length) hold
the accepted context, and the CURRENT token (last emitted) is not yet
ingested; a round ingests it as its first input. Rows written for
rejected proposals sit above the new length and are masked/overwritten.

Since ISSUE 13 speculation is a packed citizen of the engine's fused
decode tick (engine.py _spec_tick_body): spec-eligible slots take a
propose+verify round while non-spec neighbors take a plain decode step
through position 0 of the very same ragged verify forward — one chained
dispatch, no whole-engine spec/burst alternation. Stochastic speculative
sampling (rejection-sampling acceptance) remains a documented follow-up;
sampled slots simply ride the tick as plain-decode rows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from localai_tpu.models import llama


def ngram_propose(tokens, ring, ring_pos, n_draft: int, ngram: int):
    """Prompt-lookup proposals from the slot's own token history.

    tokens [S]: current (not yet ingested) token per slot; ring
    [S, RING_N] / ring_pos [S]: the penalty ring (engine/sampling.py) —
    prompt-seeded at admission and updated with every emitted token, so
    it IS the trailing prompt+generation history, already device-side.
    Returns proposals [S, D] int32.

    The trailing ``ngram``-gram (current token last) is compared against
    every aligned window of the chronological history; the continuation
    after the MOST RECENT match is proposed, clipped at the history end
    (self-overlap is deliberate — repetitive continuations are exactly
    what prompt-lookup exploits). No valid match (including short
    histories still holding -1 seed entries) proposes a repeat of the
    current token, which the verify round rejects — lossless either way.
    """
    S, N = ring.shape
    D, G = n_draft, ngram
    ar = jnp.arange(N, dtype=jnp.int32)
    # chronological view, oldest -> newest: the ring writes at
    # pos % N then advances, so entry (pos + j) % N ages left-to-right
    # and (pos - 1) % N — chronological index N-1 — is the current token
    idx = (ring_pos[:, None] + ar[None, :]) % N
    hist = jnp.take_along_axis(jnp.asarray(ring), idx, axis=1)   # [S, N]
    trail = hist[:, N - G:]                                      # [S, G]
    starts = jnp.arange(N - G, dtype=jnp.int32)                  # [P]
    win = starts[:, None] + jnp.arange(G, dtype=jnp.int32)[None, :]
    wins = hist[:, win]                                          # [S, P, G]
    ok = jnp.all(wins == trail[:, None, :], axis=-1)
    ok &= jnp.all(wins >= 0, axis=-1)              # unwritten seed entries
    ok &= jnp.all(trail >= 0, axis=-1)[:, None]    # short history: no match
    p_best = jnp.max(jnp.where(ok, starts[None, :], -1), axis=1)  # [S]
    has = p_best >= 0
    cont = jnp.minimum(
        p_best[:, None] + G + jnp.arange(D, dtype=jnp.int32)[None, :], N - 1)
    props = jnp.take_along_axis(hist, cont, axis=1)              # [S, D]
    return jnp.where(has[:, None], props,
                     jnp.asarray(tokens)[:, None]).astype(jnp.int32)


def draft_propose(dparams, dcfg: llama.LlamaConfig, tokens, lengths,
                  dck, dcv, active, n_draft: int):
    """Draft-model proposals: D+1 autoregressive greedy decode steps.

    The draft cache ingests current + ALL proposals (D+1 steps, so the
    last proposal's KV row exists when fully accepted — otherwise the
    draft cache carries a permanent hole inside the accepted context and
    acceptance quality decays). Inactive slots write at the OOB row so
    the scatter drops (contiguous and paged layouts alike).
    Returns (drafts [S, D], dck, dcv).
    """
    from localai_tpu.ops import kvcache

    dC = kvcache.shape(dck)[2]

    def dstep(carry, _):
        tok, dl, dck, dcv = carry
        wl = jnp.where(active, dl, dC)
        logits, dck, dcv = llama.decode_step(dparams, dcfg, tok, wl, dck, dcv)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, dl + active.astype(jnp.int32), dck, dcv), nxt

    (_, _, dck, dcv), proposals = jax.lax.scan(
        dstep, (tokens, lengths, dck, dcv), None, length=n_draft + 1)
    return proposals[:n_draft].T, dck, dcv


def accept_greedy(drafts, greedy, active):
    """Greedy acceptance: longest matched prefix + the target's bonus.

    drafts [S, D] proposals; greedy [S, D+1] the target's greedy picks at
    every position; active [S] bool. Returns (out [S, D+1] emitted
    tokens, n_out [S] valid counts = matched prefix + 1 bonus, k [S]
    accepted-draft counts).
    """
    S, D = drafts.shape
    match = (drafts == greedy[:, :D]).astype(jnp.int32)
    acc_prefix = jnp.cumprod(match, axis=1)
    k = jnp.sum(acc_prefix, axis=1)                             # [S]
    bonus = jnp.take_along_axis(greedy, k[:, None], axis=1)[:, 0]
    pos = jnp.arange(D + 1, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1)
    out = jnp.where(pos < k[:, None], drafts_pad,
                    jnp.where(pos == k[:, None], bonus[:, None], 0))
    n_out = (k + 1) * active.astype(jnp.int32)
    return out, n_out, k


def spec_round(params, dparams, cfg: llama.LlamaConfig, dcfg: llama.LlamaConfig,
               tokens, lengths, ck, cv, dck, dcv, active, n_draft: int):
    """One standalone draft-model speculative round for all slots.

    tokens [S]: current (not yet ingested) token per slot; lengths [S];
    ck/cv target cache; dck/dcv draft cache; active [S] bool.
    Returns (out [S, D+1] emitted tokens, out_lp, n_out [S] valid counts,
    ck, cv, dck, dcv, lengths_new). Kept as the minimal reference round
    (unit-tested directly); the engine's serving path runs the fused
    multi-round tick instead (engine.py _spec_tick_body), which composes
    these same propose/verify/accept pieces per round.
    """
    from localai_tpu.ops import kvcache

    S = tokens.shape[0]
    D = n_draft
    C = kvcache.shape(ck)[2]

    # 1. drafter proposes D tokens
    drafts, dck, dcv = draft_propose(dparams, dcfg, tokens, lengths,
                                     dck, dcv, active, D)

    # 2. target scores current + proposals in one forward
    tin = jnp.concatenate([tokens[:, None], drafts], axis=1)   # [S, D+1]
    seq = jnp.full((S,), D + 1, jnp.int32)
    start = jnp.where(active, lengths, C)  # inactive rows -> OOB, dropped
    all_logits, ck, cv = llama.prefill(
        params, cfg, tin, seq, ck, cv, jnp.arange(S, dtype=jnp.int32), start,
        continued=True, return_all_logits=True)
    greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)  # [S, D+1]

    # 3. greedy acceptance: longest prefix where draft matches target
    out, n_out, _k = accept_greedy(drafts, greedy, active)
    # matching logprobs for the emitted tokens (target distribution)
    logp_all = jax.nn.log_softmax(all_logits, axis=-1)
    out_lp = jnp.take_along_axis(logp_all, out[:, :, None], axis=2)[:, :, 0]

    lengths_new = lengths + n_out
    return out, out_lp, n_out, ck, cv, dck, dcv, lengths_new
