"""Batched, jittable sampling for the decode step.

Capability parity with the reference's sampling surface (proto fields
TopK/TopP/MinP/Temperature/TypicalP/Seed/RepeatPenalty/Repeat(last_n)/
PresencePenalty/FrequencyPenalty/Mirostat/NKeep/LogitBias — reference
backend.proto:93-131 and llama.cpp's common_sampler driven at
grpc-server.cpp:1977), re-designed as ONE vectorized jnp function over all
slots so sampling lives inside the compiled decode step instead of a
per-token host roundtrip.

TPU-first design (round 2 rework, measured on the serving chip):
  * Full-vocab [S, V] passes are the dominant sampling cost on the target
    device (each costs ~2-6 ms regardless of FLOPs). The sampler therefore
    touches the full vocab exactly ONCE — an ``approx_max_k`` that reduces
    [S, V] to a [S, SORT_K] candidate window — and does all other work
    (penalties, temperature, top-k/p/min-p/typical-p, categorical, logprobs)
    on the window. approx_max_k's bin-max algorithm always retains the
    global argmax, so greedy decoding stays exact.
  * Repetition penalties use a per-slot RING BUFFER of the last
    ``RING_N`` context tokens instead of a [S, V] histogram. This matches
    llama.cpp's semantics (penalty_last_n window, default 64 — the r1
    full-context histogram was actually *less* faithful) and removes two
    full-vocab passes plus a 4 MB device matrix per slot batch.
  * Every parameter is a per-slot vector -> one compilation serves any mix
    of per-request settings (no recompiles when users change temperature).

Exactness notes:
  * Candidates: the window is the approx-top-SORT_K of (logits + bias);
    penalties are applied inside the window. A token that only enters the
    true top-SORT_K because *other* tokens got penalized down may be
    missed. With the default repeat_last_n=64 at most 64 candidates are
    penalized, so the post-penalty argmax is always in the window; in the
    degenerate case where the penalty window covers ALL SORT_K candidates
    (repeat_last_n=256 and 256 distinct recent tokens filling the entire
    top-256), greedy can pick a penalized token over an unpenalized
    rank-257 one.
  * Logprobs are normalized over the candidate window (tail mass beyond
    SORT_K is dropped); for real model logits the tail holds <~2% mass.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

SORT_K = 256  # candidate window (cap for TopK)
RING_N = 256  # penalty ring capacity (cap for repeat_last_n)


@dataclasses.dataclass
class SamplingParamsHost:
    """Host-side per-request sampling config (maps to proto PredictOptions)."""
    temperature: float = 0.8
    top_k: int = 40          # 0 => disabled (use all of SORT_K)
    top_p: float = 0.95      # 1.0 => disabled
    min_p: float = 0.0
    typical_p: float = 1.0
    repeat_penalty: float = 1.0       # multiplicative (llama.cpp style)
    repeat_last_n: int = 64           # penalty window (llama.cpp default)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    mirostat: int = 0                 # 0=off; 1/2 run the v2 sampler
    mirostat_tau: float = 5.0         # target surprise (bits)
    mirostat_eta: float = 0.1         # mu learning rate
    seed: int = -1
    logit_bias: dict = dataclasses.field(default_factory=dict)  # token_id -> bias


def make_slot_params(num_slots: int):
    """Initial per-slot parameter vectors (pytree of [S] HOST numpy arrays).

    Host-resident on purpose: per-request installs are in-place numpy writes
    (free) instead of device `.at[].set` dispatches (~3 ms each on the
    serving chip, x10 fields per admission); the vectors ride to the device
    as ordinary jit arguments on the next step.
    """
    import numpy as np

    S = num_slots
    return {
        "temperature": np.ones((S,), np.float32),
        "top_k": np.zeros((S,), np.int32),
        "top_p": np.ones((S,), np.float32),
        "min_p": np.zeros((S,), np.float32),
        "typical_p": np.ones((S,), np.float32),
        "repeat_penalty": np.ones((S,), np.float32),
        "repeat_last_n": np.full((S,), 64, np.int32),
        "presence_penalty": np.zeros((S,), np.float32),
        "frequency_penalty": np.zeros((S,), np.float32),
        "mirostat": np.zeros((S,), np.int32),
        "mirostat_tau": np.full((S,), 5.0, np.float32),
        "mirostat_eta": np.full((S,), 0.1, np.float32),
        "greedy": np.ones((S,), np.bool_),
    }


SLOT_PARAM_FIELDS = (
    "temperature", "top_k", "top_p", "min_p", "typical_p",
    "repeat_penalty", "repeat_last_n", "presence_penalty",
    "frequency_penalty", "mirostat", "mirostat_tau", "mirostat_eta",
    "greedy",
)
_INT_FIELDS = {"top_k", "repeat_last_n", "mirostat"}


def pack_slot_params(slot_params):
    """Stack the per-slot vectors into ONE [NF, S] float32 host array.

    The serving tunnel charges per-transfer latency, so upload COUNT
    dominates upload bytes: one packed upload per dispatch replaces 13
    small ones. All fields are exactly representable in float32."""
    import numpy as np

    return np.stack([slot_params[k].astype(np.float32)
                     for k in SLOT_PARAM_FIELDS])


def unpack_slot_params(packed):
    """Rebuild the slot-params pytree from a packed [NF, S] array (jittable)."""
    out = {}
    for i, k in enumerate(SLOT_PARAM_FIELDS):
        row = packed[i]
        if k == "greedy":
            out[k] = row > 0
        elif k in _INT_FIELDS:
            out[k] = row.astype(jnp.int32)
        else:
            out[k] = row
    return out


def set_slot(slot_params, slot: int, p: SamplingParamsHost):
    """Write one request's params into the per-slot vectors (host side,
    in-place; also returns the pytree for chaining)."""
    sp = slot_params
    sp["temperature"][slot] = max(p.temperature, 1e-6)
    sp["top_k"][slot] = p.top_k if 0 < p.top_k <= SORT_K else 0
    sp["top_p"][slot] = p.top_p if 0 < p.top_p <= 1.0 else 1.0
    sp["min_p"][slot] = min(max(p.min_p, 0.0), 1.0)
    sp["typical_p"][slot] = p.typical_p if 0 < p.typical_p <= 1.0 else 1.0
    sp["repeat_penalty"][slot] = p.repeat_penalty or 1.0
    # -1 = whole context (llama.cpp), capped at the ring capacity
    n = p.repeat_last_n if p.repeat_last_n is not None else 64
    sp["repeat_last_n"][slot] = RING_N if n < 0 else min(n, RING_N)
    sp["presence_penalty"][slot] = p.presence_penalty
    sp["frequency_penalty"][slot] = p.frequency_penalty
    sp["mirostat"][slot] = p.mirostat or 0
    sp["mirostat_tau"][slot] = p.mirostat_tau if p.mirostat_tau > 0 else 5.0
    sp["mirostat_eta"][slot] = p.mirostat_eta if p.mirostat_eta > 0 else 0.1
    sp["greedy"][slot] = p.temperature <= 0
    return sp


def make_mu(num_slots: int):
    """Per-slot mirostat mu state (init 2*tau at admission; host numpy)."""
    import numpy as np

    return np.full((num_slots,), 10.0, np.float32)


def seed_slot_key(rng_keys, slot: int, p: SamplingParamsHost, fallback_seed: int):
    """Install the request's RNG state (honors p.seed; -1 => fallback)."""
    seed = p.seed if p.seed is not None and p.seed >= 0 else fallback_seed
    key_data = jax.random.key_data(jax.random.PRNGKey(seed & 0xFFFFFFFF))
    return rng_keys.at[slot].set(key_data)


def set_slot_logit_bias(bias, slot: int, p: SamplingParamsHost):
    """Install the request's logit_bias map into the [S, V] bias matrix."""
    row = bias[slot] * 0
    for tok, b in (p.logit_bias or {}).items():
        t = int(tok)
        if 0 <= t < bias.shape[1]:
            row = row.at[t].set(float(b))
    return bias.at[slot].set(row)


# ---------- penalty ring buffer ----------

def make_ring(num_slots: int):
    """Penalty state: (ring [S, RING_N] int32, pos [S] int32), HOST numpy.

    ring holds the last RING_N context tokens per slot (-1 = empty);
    pos is the monotone write cursor (next write at pos % RING_N).
    The engine keeps the authoritative copy host-side (it knows every
    emitted token) and ships it to the device as a jit argument; multi-step
    decode bursts evolve a device copy via update_ring and the host mirrors
    the same updates with host_update_ring.
    """
    import numpy as np

    return (np.full((num_slots, RING_N), -1, np.int32),
            np.zeros((num_slots,), np.int32))


def set_slot_ring(ring, pos, slot: int, token_ids):
    """Host-side: seed a slot's ring with the tail of its prompt
    (llama.cpp's penalty window covers prompt tokens too). In-place."""
    import numpy as np

    tail = list(token_ids)[-RING_N:]
    row = np.full((RING_N,), -1, np.int32)
    row[: len(tail)] = tail
    ring[slot] = row
    pos[slot] = len(tail)
    return ring, pos


def update_ring(ring, pos, ids, active):
    """Record sampled tokens into the ring (jit-side)."""
    ring, pos = jnp.asarray(ring), jnp.asarray(pos)
    active = jnp.asarray(active)
    S = ring.shape[0]
    idx = pos % RING_N
    new = jnp.where(active, ids, ring[jnp.arange(S), idx])
    ring = ring.at[jnp.arange(S), idx].set(new)
    pos = pos + active.astype(jnp.int32)
    return ring, pos


def host_update_ring(ring, pos, ids_seq, slots):
    """Host mirror of update_ring for a decode burst.

    ring/pos: numpy (in-place); ids_seq: [K, S] numpy of sampled ids;
    slots: iterable of slot indices that were active for the burst.
    """
    K = ids_seq.shape[0]
    for s in slots:
        for j in range(K):
            ring[s, pos[s] % RING_N] = ids_seq[j, s]
            pos[s] += 1
    return ring, pos


def _window_counts(ring, pos, idx, repeat_last_n):
    """Occurrences of each candidate token within each slot's last-n window.

    ring [S, RING_N]; pos [S]; idx [S, K]; repeat_last_n [S] -> [S, K] int32.
    """
    RN = ring.shape[1]
    slot_off = jnp.arange(RN, dtype=jnp.int32)[None, :]                    # [1, RN]
    age = (pos[:, None] - 1 - slot_off) % RN                               # [S, RN]
    # entry j is in-window iff it was written (j < pos when pos < RN — the
    # -1 fill handles that) and its age < repeat_last_n
    in_window = (age < repeat_last_n[:, None]) & (ring >= 0)               # [S, RN]
    match = ring[:, None, :] == idx[:, :, None]                            # [S, K, RN]
    return jnp.sum(match & in_window[:, None, :], axis=-1).astype(jnp.int32)


def feature_flags(slot_params, active=None) -> dict:
    """Host-side: which sampler features any (active) slot actually uses.

    Per-op launch overhead dominates small ops on the serving chip, so the
    engine compiles burst variants with unused feature blocks traced OUT
    (static flags below) — a temperature/top-k workload skips the penalty
    window counts, the typical-p double argsort, and the mirostat math.
    """
    sel = slice(None) if active is None else active
    pen = (np.any(slot_params["repeat_penalty"][sel] != 1.0)
           or np.any(slot_params["presence_penalty"][sel] != 0.0)
           or np.any(slot_params["frequency_penalty"][sel] != 0.0))
    return {
        "use_penalties": bool(pen),
        "use_typical": bool(np.any(slot_params["typical_p"][sel] < 1.0)),
        "use_mirostat": bool(np.any(slot_params["mirostat"][sel] > 0)),
    }


def filter_window(logits, slot_params, ring, ring_pos, logit_bias, mu=None,
                  use_penalties: bool = True, use_typical: bool = True,
                  use_mirostat: bool = True):
    """Reduce full-vocab logits to the FILTERED candidate-window distribution.

    This is the shared front half of `sample`: the single full-vocab
    approx_max_k, window penalties, temperature scaling, and the
    top-k/top-p/min-p/typical-p (or mirostat) keep-mask chain. Returns
    (idx [S, K] candidate token ids, masked [S, K] unnormalized filtered
    log-probs — exp/normalize = the exact distribution `sample`'s
    categorical draws from, kept rank-0 guaranteed — and vals [S, K], the
    post-penalty pre-temperature window logits used for logprob
    reporting). Speculative verify (verify_dist) calls this with the same
    per-slot params as the decode path, so spec-sampled acceptance and
    plain sampling draw from the identical law by construction.
    """
    S, V = logits.shape
    k = min(SORT_K, V)
    use_mirostat = use_mirostat and mu is not None
    # the ONLY full-vocab op: bias add fuses into the producing matmul's
    # epilogue; approx_max_k reduces to the candidate window
    top_vals, top_idx = jax.lax.approx_max_k(logits + logit_bias, k)
    top_idx = top_idx.astype(jnp.int32)

    if use_penalties:
        # penalties within the window (llama.cpp last-n semantics)
        cnt = _window_counts(ring, ring_pos, top_idx, slot_params["repeat_last_n"])
        seen = cnt > 0
        rp = slot_params["repeat_penalty"][:, None]
        penalized = jnp.where(top_vals > 0, top_vals / rp, top_vals * rp)
        vals = jnp.where(seen, penalized, top_vals)
        vals = vals - seen * slot_params["presence_penalty"][:, None]
        vals = vals - cnt.astype(jnp.float32) * slot_params["frequency_penalty"][:, None]
        # penalties can reorder the window: re-sort descending ([S, k])
        order = jnp.argsort(-vals, axis=-1)
        vals = jnp.take_along_axis(vals, order, axis=-1)
        idx = jnp.take_along_axis(top_idx, order, axis=-1)
    else:
        vals, idx = top_vals, top_idx

    scaled = vals / slot_params["temperature"][:, None]
    rank = jnp.arange(k, dtype=jnp.int32)[None, :]
    # top-k: keep rank < k_s (0 = disabled -> keep all)
    k_s = jnp.where(slot_params["top_k"] > 0, slot_params["top_k"], k)[:, None]
    keep = rank < k_s
    # softmax over the kept top-k window
    probs = jax.nn.softmax(jnp.where(keep, scaled, -jnp.inf), axis=-1)
    # top-p: smallest prefix with cumulative mass >= p (always keep rank 0)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < slot_params["top_p"][:, None]
    # min-p: prob >= min_p * max_prob
    keep &= probs >= slot_params["min_p"][:, None] * probs[:, :1]
    logp = jnp.log(jnp.clip(probs, 1e-20))
    if use_typical:
        # typical-p: keep tokens whose -log p is closest to entropy until
        # mass >= tp
        entropy = -jnp.sum(jnp.where(probs > 0, probs * logp, 0.0), axis=-1,
                           keepdims=True)
        deviation = jnp.abs(-logp - entropy)
        tp_enabled = slot_params["typical_p"][:, None] < 1.0
        dev_order = jnp.argsort(deviation, axis=-1)
        probs_by_dev = jnp.take_along_axis(probs, dev_order, axis=-1)
        cum_dev = jnp.cumsum(probs_by_dev, axis=-1)
        keep_dev_sorted = (cum_dev - probs_by_dev) < slot_params["typical_p"][:, None]
        inv = jnp.argsort(dev_order, axis=-1)
        keep_typical = jnp.take_along_axis(keep_dev_sorted, inv, axis=-1)
        keep = jnp.where(tp_enabled, keep & keep_typical, keep)
    # the independent keep-masks can have an empty intersection (typical-p's
    # lowest-deviation tokens need not lie in the top-p prefix); llama.cpp
    # applies samplers sequentially so this cannot happen there — guarantee
    # progress by always keeping the highest-probability candidate
    keep = keep | (rank == 0)

    # mirostat v2: replace the keep-chain with the surprise-<=-mu cut over
    # the full-window distribution (softmax of scaled, no top-k mask)
    if use_mirostat:
        miro_on = slot_params["mirostat"][:, None] > 0
        full_logp = jax.nn.log_softmax(scaled, axis=-1)
        surprise = -full_logp / jnp.float32(np.log(2.0))          # bits
        keep_miro = (surprise <= jnp.asarray(mu)[:, None]) | (rank == 0)
        keep = jnp.where(miro_on, keep_miro, keep)
        masked = jnp.where(keep, jnp.where(miro_on, full_logp, logp), -jnp.inf)
    else:
        masked = jnp.where(keep, logp, -jnp.inf)
    return idx, masked, vals


def sample(logits, slot_params, ring, ring_pos, logit_bias, rng_keys, mu=None,
           use_penalties: bool = True, use_typical: bool = True,
           use_mirostat: bool = True):
    """Sample one token per slot.

    logits: [S, V] fp32; ring/ring_pos: penalty state from make_ring;
    logit_bias: [S, V] fp32; rng_keys: [S, 2] uint32 (per-slot PRNG data);
    mu: [S] fp32 mirostat state (None = mirostat disabled everywhere).
    use_*: STATIC feature gates (see feature_flags) — False traces the
    block out entirely; semantics are unchanged when the corresponding
    per-slot parameters are at their neutral values.
    Returns (token_ids [S] int32, logprobs [S] fp32, new_rng_keys, new_mu).

    Mirostat (llama.cpp mirostat v2 semantics, sample_token_mirostat_v2:
    truncate candidates whose surprise exceeds mu, sample, then
    mu -= eta * (observed_surprise - tau)) replaces the top-k/p/min-p
    chain for slots with slot_params["mirostat"] > 0.
    """
    use_mirostat = use_mirostat and mu is not None
    idx, masked, vals = filter_window(
        logits, slot_params, ring, ring_pos, logit_bias, mu=mu,
        use_penalties=use_penalties, use_typical=use_typical,
        use_mirostat=use_mirostat)
    greedy_ids = idx[:, 0]

    def sample_one(key_data, logits_row):
        key = jax.random.wrap_key_data(key_data)
        key, sub = jax.random.split(key)
        choice = jax.random.categorical(sub, logits_row)
        return jax.random.key_data(key), choice

    new_keys, choices = jax.vmap(sample_one)(rng_keys, masked)
    sampled_ids = jnp.take_along_axis(idx, choices[:, None], axis=-1)[:, 0]

    ids = jnp.where(slot_params["greedy"], greedy_ids, sampled_ids).astype(jnp.int32)

    if use_mirostat:
        # observed surprise under the truncated+renormalized distribution
        miro_on = slot_params["mirostat"][:, None] > 0
        lse = jax.nn.logsumexp(masked, axis=-1, keepdims=True)
        chosen_lp = jnp.take_along_axis(masked - lse, choices[:, None], axis=-1)[:, 0]
        obs = -chosen_lp / jnp.float32(np.log(2.0))
        new_mu = jnp.asarray(mu) - slot_params["mirostat_eta"] * (
            obs - slot_params["mirostat_tau"])
        new_mu = jnp.where(miro_on[:, 0] & ~jnp.asarray(slot_params["greedy"]),
                           new_mu, jnp.asarray(mu))
    else:
        new_mu = None if mu is None else jnp.asarray(mu)

    # logprob of the chosen token under the post-penalty, pre-temperature
    # window distribution (window-normalized; see module docstring)
    win_logp = jax.nn.log_softmax(vals, axis=-1)
    chosen_rank = jnp.where(slot_params["greedy"][:, None],
                            jnp.zeros_like(choices[:, None]), choices[:, None])
    logprobs = jnp.take_along_axis(win_logp, chosen_rank, axis=-1)[:, 0]
    return ids, logprobs, new_keys, new_mu


def verify_dist(all_logits, slot_params, use_typical: bool = True):
    """Filtered target distribution at EVERY speculative-verify position.

    all_logits [S, W, V] (W = n_draft+1 positions from the ragged verify
    forward); slot_params: the per-slot vectors, broadcast across a
    slot's W positions. Returns (idx [S, W, K] candidate ids, probs
    [S, W, K] — the normalized post-temperature top-k/top-p/min-p window
    distribution each position's plain `sample` call would draw from).

    Runs the SAME filter_window code path as `sample` (position-major
    flatten, params repeated per position), so rejection-sampling
    acceptance against these probs preserves the plain-sampling law
    exactly. Penalties / mirostat / logit_bias are traced out: spec
    eligibility (engine spec_ok) excludes slots using them, because their
    state evolves per emitted token and a verify round scores W positions
    against one frozen state. Greedy picks stay exact: idx[:, :, 0] is
    approx_max_k's retained global argmax over logits + 0.0.
    """
    S, W, V = all_logits.shape
    rep = {k: jnp.repeat(jnp.asarray(v), W, axis=0)
           for k, v in slot_params.items()}
    flat = all_logits.reshape(S * W, V)
    zero_bias = jnp.zeros((1, 1), flat.dtype)
    idx, masked, _vals = filter_window(
        flat, rep, None, None, zero_bias, mu=None,
        use_penalties=False, use_typical=use_typical, use_mirostat=False)
    kk = idx.shape[-1]
    probs = jax.nn.softmax(masked, axis=-1)
    return idx.reshape(S, W, kk), probs.reshape(S, W, kk)
