"""Batched, jittable sampling for the decode step.

Capability parity with the reference's sampling surface (proto fields
TopK/TopP/MinP/Temperature/TypicalP/Seed/RepeatPenalty/PresencePenalty/
FrequencyPenalty/Mirostat/NKeep/LogitBias — reference backend.proto:93-131
and llama.cpp's common_sampler driven at grpc-server.cpp:1977), re-designed
as ONE vectorized jnp function over all slots so sampling lives inside the
compiled decode step instead of a per-token host roundtrip.

Design:
  * Every parameter is a per-slot vector -> one compilation serves any mix
    of per-request settings (no recompiles when users change temperature).
  * top-k/top-p/min-p/typical-p run on the top-``SORT_K`` logits only
    (exact for k <= SORT_K; nucleus mass beyond SORT_K is negligible),
    keeping the op O(V) scan + O(SORT_K log SORT_K) instead of a full sort.
  * Penalties use a per-slot token-count matrix [S, V] updated on-device.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

SORT_K = 256  # logits considered for top-k/p/min-p/typical-p (cap for TopK)


@dataclasses.dataclass
class SamplingParamsHost:
    """Host-side per-request sampling config (maps to proto PredictOptions)."""
    temperature: float = 0.8
    top_k: int = 40          # 0 => disabled (use all of SORT_K)
    top_p: float = 0.95      # 1.0 => disabled
    min_p: float = 0.0
    typical_p: float = 1.0
    repeat_penalty: float = 1.0       # multiplicative (llama.cpp style)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    seed: int = -1
    logit_bias: dict = dataclasses.field(default_factory=dict)  # token_id -> bias


def make_slot_params(num_slots: int):
    """Initial per-slot parameter vectors (pytree of [S] arrays)."""
    S = num_slots
    return {
        "temperature": jnp.ones((S,), jnp.float32),
        "top_k": jnp.zeros((S,), jnp.int32),
        "top_p": jnp.ones((S,), jnp.float32),
        "min_p": jnp.zeros((S,), jnp.float32),
        "typical_p": jnp.ones((S,), jnp.float32),
        "repeat_penalty": jnp.ones((S,), jnp.float32),
        "presence_penalty": jnp.zeros((S,), jnp.float32),
        "frequency_penalty": jnp.zeros((S,), jnp.float32),
        "greedy": jnp.ones((S,), jnp.bool_),
    }


def set_slot(slot_params, slot: int, p: SamplingParamsHost):
    """Write one request's params into the per-slot vectors (host side)."""
    sp = dict(slot_params)
    sp["temperature"] = sp["temperature"].at[slot].set(max(p.temperature, 1e-6))
    sp["top_k"] = sp["top_k"].at[slot].set(p.top_k if 0 < p.top_k <= SORT_K else 0)
    sp["top_p"] = sp["top_p"].at[slot].set(p.top_p if 0 < p.top_p <= 1.0 else 1.0)
    sp["min_p"] = sp["min_p"].at[slot].set(min(max(p.min_p, 0.0), 1.0))
    sp["typical_p"] = sp["typical_p"].at[slot].set(p.typical_p if 0 < p.typical_p <= 1.0 else 1.0)
    sp["repeat_penalty"] = sp["repeat_penalty"].at[slot].set(p.repeat_penalty or 1.0)
    sp["presence_penalty"] = sp["presence_penalty"].at[slot].set(p.presence_penalty)
    sp["frequency_penalty"] = sp["frequency_penalty"].at[slot].set(p.frequency_penalty)
    sp["greedy"] = sp["greedy"].at[slot].set(p.temperature <= 0)
    return sp


def seed_slot_key(rng_keys, slot: int, p: SamplingParamsHost, fallback_seed: int):
    """Install the request's RNG state (honors p.seed; -1 => fallback)."""
    seed = p.seed if p.seed is not None and p.seed >= 0 else fallback_seed
    key_data = jax.random.key_data(jax.random.PRNGKey(seed & 0xFFFFFFFF))
    return rng_keys.at[slot].set(key_data)


def set_slot_logit_bias(bias, slot: int, p: SamplingParamsHost):
    """Install the request's logit_bias map into the [S, V] bias matrix."""
    row = bias[slot] * 0
    for tok, b in (p.logit_bias or {}).items():
        t = int(tok)
        if 0 <= t < bias.shape[1]:
            row = row.at[t].set(float(b))
    return bias.at[slot].set(row)


def apply_penalties(logits, token_counts, sp):
    """logits [S, V] fp32; token_counts [S, V] int32 (tokens seen in context)."""
    seen = token_counts > 0
    # multiplicative repeat penalty (llama.cpp semantics: divide positive
    # logits, multiply negative ones)
    rp = sp["repeat_penalty"][:, None]
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalized, logits)
    logits = logits - seen * sp["presence_penalty"][:, None]
    logits = logits - token_counts.astype(jnp.float32) * sp["frequency_penalty"][:, None]
    return logits


def sample(logits, slot_params, token_counts, logit_bias, rng_keys):
    """Sample one token per slot.

    logits: [S, V] fp32; token_counts: [S, V] int32; logit_bias: [S, V] fp32;
    rng_keys: [S, 2] uint32 (jax PRNG key data per slot).
    Returns (token_ids [S] int32, logprobs [S] fp32, new_rng_keys).
    """
    S, V = logits.shape
    logits = logits + logit_bias
    logits = apply_penalties(logits, token_counts, slot_params)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / slot_params["temperature"][:, None]
    k = min(SORT_K, V)
    top_vals, top_idx = jax.lax.top_k(scaled, k)  # [S, k] descending

    rank = jnp.arange(k, dtype=jnp.int32)[None, :]
    # top-k: keep rank < k_s (0 = disabled -> keep all)
    k_s = jnp.where(slot_params["top_k"] > 0, slot_params["top_k"], k)[:, None]
    keep = rank < k_s
    # softmax over the kept top-k window
    probs = jax.nn.softmax(jnp.where(keep, top_vals, -jnp.inf), axis=-1)
    # top-p: smallest prefix with cumulative mass >= p (always keep rank 0)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < slot_params["top_p"][:, None]
    # min-p: prob >= min_p * max_prob
    keep &= probs >= slot_params["min_p"][:, None] * probs[:, :1]
    # typical-p: keep tokens whose -log p is closest to entropy until mass >= tp
    logp = jnp.log(jnp.clip(probs, 1e-20))
    entropy = -jnp.sum(jnp.where(probs > 0, probs * logp, 0.0), axis=-1, keepdims=True)
    deviation = jnp.abs(-logp - entropy)
    tp_enabled = slot_params["typical_p"][:, None] < 1.0
    order = jnp.argsort(deviation, axis=-1)
    probs_by_dev = jnp.take_along_axis(probs, order, axis=-1)
    cum_dev = jnp.cumsum(probs_by_dev, axis=-1)
    keep_dev_sorted = (cum_dev - probs_by_dev) < slot_params["typical_p"][:, None]
    inv = jnp.argsort(order, axis=-1)
    keep_typical = jnp.take_along_axis(keep_dev_sorted, inv, axis=-1)
    keep = jnp.where(tp_enabled, keep & keep_typical, keep)
    # the independent keep-masks can have an empty intersection (typical-p's
    # lowest-deviation tokens need not lie in the top-p prefix); llama.cpp
    # applies samplers sequentially so this cannot happen there — guarantee
    # progress by always keeping the highest-probability candidate
    keep = keep | (rank == 0)

    masked = jnp.where(keep, logp, -jnp.inf)

    def sample_one(key_data, logits_row):
        key = jax.random.wrap_key_data(key_data)
        key, sub = jax.random.split(key)
        choice = jax.random.categorical(sub, logits_row)
        return jax.random.key_data(key), choice

    new_keys, choices = jax.vmap(sample_one)(rng_keys, masked)
    sampled_ids = jnp.take_along_axis(top_idx, choices[:, None], axis=-1)[:, 0]

    ids = jnp.where(slot_params["greedy"], greedy_ids, sampled_ids).astype(jnp.int32)
    all_logprobs = jax.nn.log_softmax(logits, axis=-1)
    logprobs = jnp.take_along_axis(all_logprobs, ids[:, None], axis=-1)[:, 0]
    return ids, logprobs, new_keys


def update_token_counts(token_counts, ids, active):
    """Record sampled tokens into the per-slot histogram (jit-side)."""
    S, V = token_counts.shape
    onehot = jax.nn.one_hot(ids, V, dtype=token_counts.dtype)
    return token_counts + onehot * active[:, None].astype(token_counts.dtype)
