"""Incremental, UTF-8-safe detokenization for streaming.

The reference reassembles UTF-8 runes across stream chunks on the Go side
(reference: core/backend/llm.go:133-149). Here the same concern is solved at
the source: tokens decode incrementally with a two-offset scheme and text is
only released at UTF-8-complete boundaries, so every SSE chunk is valid text.
"""

from __future__ import annotations


class IncrementalDetokenizer:
    """Decode a growing token-id sequence, emitting only finalized deltas.

    Two-offset algorithm: ``prefix_offset`` marks the start of the decode
    window (kept a few tokens behind so byte-merging tokenizers see their
    context), ``read_offset`` marks how far text has been emitted. Text
    ending in U+FFFD (incomplete multibyte) is withheld until completed.
    """

    def __init__(self, tokenizer, skip_special_tokens: bool = True):
        self.tokenizer = tokenizer
        self.skip_special = skip_special_tokens
        self.ids: list[int] = []
        self.prefix_offset = 0
        self.read_offset = 0
        self._text = ""  # total emitted text

    def _decode(self, ids) -> str:
        if not ids:
            return ""
        return self.tokenizer.decode(ids, skip_special_tokens=self.skip_special)

    def push(self, token_id: int) -> str:
        """Add one token; return the newly finalized text delta (maybe "")."""
        self.ids.append(token_id)
        prefix_text = self._decode(self.ids[self.prefix_offset : self.read_offset])
        full_text = self._decode(self.ids[self.prefix_offset :])
        if full_text.endswith("�"):
            return ""
        delta = full_text[len(prefix_text) :]
        self.prefix_offset = self.read_offset
        self.read_offset = len(self.ids)
        self._text += delta
        return delta

    def flush(self) -> str:
        """Emit any withheld tail (drops a trailing incomplete sequence)."""
        prefix_text = self._decode(self.ids[self.prefix_offset : self.read_offset])
        full_text = self._decode(self.ids[self.prefix_offset :])
        delta = full_text[len(prefix_text) :].rstrip("�")
        self.read_offset = len(self.ids)
        self._text += delta
        return delta

    @property
    def text(self) -> str:
        return self._text
