"""Engine replica pool: shared KV tiers, prefix-affinity routing, live
request migration (ISSUE 14).

The serving unit used to be ONE Engine per model, so one Python host
loop bounded every model's throughput no matter how much chip was left.
This module is the ROADMAP's multi-engine scale-out step 1+2: an
``EnginePool`` owns N Engine replicas of the same model (``engines=N``
on the options wire), all sharing

  * ONE ``HostPageStore`` (``SharedKV.host_store``) — per-replica
    device tiers, one host tier. The store's shared-mode mapping
    refcounts (kv_offload.py) guarantee an entry some replica's device
    tier still maps — or an in-flight migration is about to splice —
    is never budget-evicted from under a sibling.
  * ONE ``PoolPrefixIndex`` (prefix_cache.py) fed by each replica's
    PrefixPageCache membership callbacks: chain key -> {replica: depth}.

Routing (admission): a request goes to the replica holding the LONGEST
live/retained chain match for its prompt (prefix-affinity — the PR-2/3
chained block hashes make KV location-independent, so the match is
computed host-side from token ids alone); with no usable match it goes
to the least-loaded replica, where load = active slots + parked resumes
+ DRR-class-weighted queue pressure (a queue full of high-class work
presses harder on a normal-class arrival than a queue of low).

Live migration composes existing primitives, no new KV machinery:
pause on replica A (PR-10 preemption, ``park=False``), force-offload
the retained chain to the shared host tier (PR-3), adopt + resume as a
re-admission on replica B whose chain lookup splices the same pages
back. PR-10's resume ≡ fresh-re-admission contract makes the byte gate
well-defined: the migrated continuation equals a FRESH submission of
(prompt + tokens emitted so far) — the same contract the priority
bench gates, NOT bit-parity with an uninterrupted run (prefill-vs-
decode kernel numerics differ). Used for drain-free rebalancing when
one replica saturates, and for CRASH RECOVERY: when a replica's loop
dies (DejaVu's failure model), its queued, parked and in-flight
requests re-route to siblings and restore from the shared tier instead
of the client seeing an error (extends PR-7 in-engine recovery).

``engines=1`` never constructs a pool at all (backend/runner.py builds
a plain Engine), so single-engine behavior stays bit-for-bit.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from localai_tpu.engine import engine as eng
from localai_tpu.engine.prefix_cache import PoolPrefixIndex
from localai_tpu.engine.scheduler import (PRIORITY_RANK, ResumeEntry,
                                          parse_priority_weights)
from localai_tpu.services.eventlog import EVENTS

log = logging.getLogger(__name__)

# how many migration pin-sets to keep mapped before releasing the
# oldest (a pin protects a migrated chain from budget eviction until
# the target's restore has long since happened)
_MAX_PINS = 8


class _DrainAbort(Exception):
    """Internal: a scale-in drain couldn't place everything on siblings
    — the step is abandoned and the replica returns to service."""


class SharedKV:
    """The pool-scoped KV state every replica plugs into: one host-tier
    page store (created lazily by the first replica that wants one, so
    scope/page-size come from the real engine config) and one
    cross-replica prefix index. ``prefix_hooks(replica)`` returns the
    PrefixPageCache callbacks that keep both in sync with that
    replica's device tier."""

    def __init__(self):
        self._lock = threading.Lock()
        self.store = None            # kv_offload.HostPageStore | None
        self.store_path = ""
        self.index = PoolPrefixIndex()

    def host_store(self, scope: bytes, page_size: int, budget_mb: int,
                   store_path: str = ""):
        """The ONE shared HostPageStore (created on first call; loaded
        from ``store_path`` once — replicas never load or save it
        themselves)."""
        with self._lock:
            if self.store is None:
                from localai_tpu.engine.kv_offload import HostPageStore

                self.store = HostPageStore(scope, page_size, budget_mb)
                self.store_path = store_path
                if store_path:
                    n = self.store.load(store_path)
                    if n:
                        log.info("shared kv host store: reloaded %d pages"
                                 " from %s", n, store_path)
            else:
                assert self.store.scope == scope, \
                    "pool replicas must share one model scope"
                assert self.store.page_size == page_size
            return self.store

    def prefix_hooks(self, replica: int) -> dict:
        """Membership callbacks for replica's PrefixPageCache: keep the
        pool index AND the shared store's device-mapping refcounts in
        lockstep with the device tier. Called on that replica's engine
        loop thread; index/store methods lock internally."""

        def on_insert(key, depth, _r=replica):
            self.index.note_insert(_r, key, depth)
            if self.store is not None:
                self.store.map_key(key, _r)

        def on_remove(key, _r=replica):
            self.index.note_remove(_r, key)
            if self.store is not None:
                self.store.unmap_key(key, _r)

        def on_clear(_r=replica):
            self.index.clear_replica(_r)
            if self.store is not None:
                self.store.unmap_owner(_r)

        return {"on_insert": on_insert, "on_remove": on_remove,
                "on_clear": on_clear}

    def save(self) -> bool:
        """Persist the shared store ONCE (pool shutdown) — pool-scoped
        entries round-trip a single file, not one per replica."""
        if self.store is not None and self.store_path:
            return self.store.save(self.store_path)
        return False


class EnginePool:
    """N Engine replicas of one model behind prefix-affinity routing.

    Mirrors the Engine surface the gRPC servicer drives (submit /
    cancel / generate / generate_text / num_active / metrics /
    state_snapshot / trace_events / start / shutdown / tracer);
    anything else falls through to replica 0.
    """

    def __init__(self, engines: list, shared: SharedKV):
        assert engines, "EnginePool needs at least one replica"
        self._engines = list(engines)
        self._shared = shared
        self._lock = threading.Lock()
        self._dead = [False] * len(engines)
        # request routing memory: rid -> replica (bounded FIFO trim)
        self._where: dict = {}
        self._where_order: list = []
        # migration pins: (rid, [chain keys]) mapped under
        # ("migrate", rid) in the shared store; oldest released first
        self._pins: list = []
        self._migrations = {"rebalance": 0, "crash": 0}
        self.affinity_hits = 0
        self.affinity_misses = 0
        self._routed = 0
        w = self._engines[0].ecfg.priority_weights
        try:
            self._weights = parse_priority_weights(w)
        except ValueError:
            self._weights = (4, 2, 1)
        self._hk_stop = threading.Event()
        self._hk_thread: Optional[threading.Thread] = None
        # shared-store KV audit fold (ISSUE 15): the POOL scans the one
        # shared host tier on the housekeeping cadence — replicas only
        # scan stores they own, so shared violations count once
        self._t_kv_audit = time.monotonic()
        # --- dynamic resize / autoscaling (ISSUE 19) ---
        # build() stashes its ctor args so resize() can construct fresh
        # replicas; a pool assembled directly can't scale out.
        self._build_args: Optional[dict] = None
        self._precompile = False
        self._draining: set = set()   # replicas emptying toward retire
        self._retired: set = set()    # cleanly shut down (≠ crashed)
        self._resize_lock = threading.Lock()
        self._resize_thread: Optional[threading.Thread] = None
        self.target_replicas = len(engines)
        self._policy = None           # autoscale.AutoscalePolicy | None
        # admission-limit co-scaling (ISSUE 20): max_queued_requests is
        # a PER-REPLICA knob, so the pool's effective admission budget
        # is width-proportional — resize() rescales each live replica's
        # maxq_effective against the CONFIGURED width, so a scaled-in
        # pool sheds at the narrower width's limit instead of promising
        # the full fleet's queue depth
        self._configured_width = len(engines)
        self._maxq_base = self._engines[0].ecfg.max_queued_requests

    # ---------- construction ----------

    @classmethod
    def build(cls, model_cfg, params, tokenizer, engine_cfg=None,
              engines: int = 2, eos_token_ids=None, mesh=None,
              param_shardings=None, draft=None, family=None):
        """Construct N replicas around one SharedKV. Weights (params)
        are shared device buffers — replicas add slots and host loops,
        not model memory. Requires the preemptive scheduler: pause/
        resume IS the migration and crash-recovery primitive."""
        ecfg = engine_cfg or eng.EngineConfig()
        if (engines > 1 or ecfg.autoscale) and not ecfg.preempt:
            raise ValueError("engines>1/autoscale=1 requires preempt=1 "
                             "(pause/resume is the migration primitive)")
        shared = SharedKV()
        replicas = [
            eng.Engine(model_cfg, params, tokenizer, ecfg,
                       eos_token_ids=eos_token_ids, mesh=mesh,
                       param_shardings=param_shardings, draft=draft,
                       family=family, replica_id=i, shared_kv=shared)
            for i in range(max(1, int(engines)))]
        pool = cls(replicas, shared)
        # resize() rebuilds replicas from these; params are the SAME
        # shared device buffers, so a scale-out costs slots + a host
        # loop, never a weight load (the weight win lives in
        # weights.stream_llama_params on the gallery-swap path)
        pool._build_args = dict(
            model_cfg=model_cfg, params=params, tokenizer=tokenizer,
            ecfg=ecfg, eos_token_ids=eos_token_ids, mesh=mesh,
            param_shardings=param_shardings, draft=draft, family=family)
        return pool

    # ---------- lifecycle ----------

    def start(self, precompile: bool = False):
        self._precompile = precompile
        for e in self._engines:
            e.start(precompile=precompile)
        ecfg = self._engines[0].ecfg
        if ecfg.autoscale:
            # autoscale=0 (default) constructs NOTHING here: no policy
            # object, no extra thread — bit-for-bit the static pool
            from localai_tpu.engine.autoscale import AutoscalePolicy

            dwell = max(0.05, ecfg.autoscale_dwell_ms / 1000.0)
            self._policy = AutoscalePolicy(
                min_replicas=ecfg.autoscale_min,
                max_replicas=(ecfg.autoscale_max
                              or 2 * len(self._engines)),
                burn_out=ecfg.autoscale_burn_out,
                burn_in=ecfg.autoscale_burn_in,
                dwell_s=dwell,
                cooldown_s=max(dwell, ecfg.autoscale_cooldown_ms / 1000.0),
                idle_in_s=max(0.2, dwell * 0.75),
                flight=self._engines[0]._flight)
        self._hk_thread = threading.Thread(
            target=self._housekeeping, name="engine-pool", daemon=True)
        self._hk_thread.start()

    def shutdown(self):
        self._hk_stop.set()
        if self._hk_thread is not None:
            self._hk_thread.join(timeout=5)
        if self._resize_thread is not None:
            self._resize_thread.join(timeout=15)
        for i, e in enumerate(self._engines):
            if i in self._retired:
                continue    # scale-in already shut it down cleanly
            try:
                e.shutdown()
            except Exception:
                log.exception("replica %d shutdown failed", e.replica_id)
        # release any leftover migration pins, then persist ONCE
        with self._lock:
            pins, self._pins = self._pins, []
        for rid, keys in pins:
            self._unpin(rid, keys)
        self._shared.save()

    # ---------- passthroughs the servicer touches ----------

    @property
    def tracer(self):
        return self._engines[0].tracer

    @property
    def num_active(self) -> int:
        return sum(e.num_active for e in self._engines)

    def __getattr__(self, name):
        # anything not pool-aware (cfg, ecfg, tokenizer, eos_ids, ...)
        # answers from replica 0; private names never delegate
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._engines[0], name)

    def generate(self, req):
        out = self.submit(req)
        while True:
            ev = out.get()
            if ev is None:
                return
            yield ev

    def generate_text(self, req):
        events = list(self.generate(req))
        return "".join(e.text for e in events), events

    def cancel(self, request_id: str):
        i = self._where.get(request_id)
        if i is not None:
            self._engines[i].cancel(request_id)
        else:
            for e in self._alive_engines():
                e.cancel(request_id)

    # ---------- routing ----------

    def _alive(self, i: int) -> bool:
        return not self._dead[i]

    def _alive_engines(self):
        return [e for i, e in enumerate(self._engines) if not self._dead[i]]

    def _routable(self, i: int) -> bool:
        """Eligible for NEW work: alive and not draining toward a
        scale-in retire (a draining replica still finishes/migrates what
        it has — it just stops being a routing target)."""
        return not self._dead[i] and i not in self._draining

    def _routable_idx(self) -> list:
        return [i for i in range(len(self._engines)) if self._routable(i)]

    def _load(self, i: int, rank: int) -> float:
        """Replica load as seen by a class-``rank`` arrival: active
        slots + parked resumes + queue depth weighted by DRR class
        pressure (queued work of heavier classes presses harder)."""
        e = self._engines[i]
        w = self._weights
        with e._queue.mutex:
            qranks = [PRIORITY_RANK.get(r.priority, 1)
                      for r in e._queue.queue]
        wn = w[rank] if 0 <= rank < len(w) else 1
        pressure = sum(w[q] if 0 <= q < len(w) else 1
                       for q in qranks) / max(1, wn)
        parked = e._sched.resume_depth if e._sched is not None else 0
        return e.num_active + parked + pressure

    def _route(self, req) -> int:
        """Prefix-affinity first, least-loaded otherwise."""
        alive = self._routable_idx()
        if not alive:
            raise RuntimeError("engine pool: no live replicas")
        rank = PRIORITY_RANK.get(getattr(req, "priority", None), 1)
        if len(alive) == 1:
            self._routed += 1
            return alive[0]
        # longest live/retained chain match among live replicas
        pc = self._engines[alive[0]]._pcache
        best_i, best_depth = None, 0
        if pc is not None and getattr(req, "prompt_ids", None):
            keys = list(pc.chain_keys(req.prompt_ids))
            if keys:
                depths = self._shared.index.match_depths(keys)
                for i in alive:
                    d = depths.get(i, 0)
                    if d > best_depth or (d == best_depth and d > 0
                                          and best_i is not None
                                          and self._load(i, rank)
                                          < self._load(best_i, rank)):
                        best_i, best_depth = i, d
        self._routed += 1
        if best_i is not None and best_depth > 0:
            self.affinity_hits += 1
            return best_i
        self.affinity_misses += 1
        return min(alive, key=lambda i: (self._load(i, rank), i))

    def _note_where(self, rid: str, replica: int):
        with self._lock:
            if rid not in self._where:
                self._where_order.append(rid)
            self._where[rid] = replica
            while len(self._where_order) > 4096:
                old = self._where_order.pop(0)
                self._where.pop(old, None)

    def where(self, rid: str) -> Optional[int]:
        return self._where.get(rid)

    def submit(self, req) -> "queue.Queue":
        r = self._route(req)
        self._note_where(req.request_id, r)
        return self._engines[r].submit(req)

    # ---------- live migration ----------

    def _pin(self, rid: str, keys: list):
        """Hold migrated chain keys mapped in the shared store (owner
        ("migrate", rid)) so budget eviction can't race the target's
        restore; bounded — the oldest pin-set releases past _MAX_PINS."""
        if not keys:
            return
        drop = []
        with self._lock:
            self._pins.append((rid, keys))
            while len(self._pins) > _MAX_PINS:
                drop.append(self._pins.pop(0))
        for old_rid, old_keys in drop:
            self._unpin(old_rid, old_keys)

    def _unpin(self, rid: str, keys: list):
        store = self._shared.store
        if store is None:
            return
        owner = ("migrate", rid)
        for k in keys:
            store.unmap_key(k, owner)

    def _await_offload(self, keys: list, timeout_s: float = 2.5) -> bool:
        """Bounded wait for the chain tail to land in the shared store
        (offload puts are async through the source's sync worker). A
        timeout is not an error — the target re-prefills the identical
        history, still byte-exact, just slower."""
        store = self._shared.store
        if store is None or not keys:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if store.contains(keys[-1]):
                return True
            time.sleep(0.005)
        return store.contains(keys[-1])

    def migrate(self, request_id: str, target: Optional[int] = None,
                reason: str = "rebalance", timeout_s: float = 10.0) -> bool:
        """Live-migrate one request to ``target`` (default: least-loaded
        other live replica). Pause on the source at its next tick top,
        force-offload the retained chain to the shared host tier, adopt
        on the target whose chain lookup splices the pages back. The
        client stream never closes — tokens continue from the target,
        byte-identical to a fresh re-admission of (prompt + emitted)."""
        src = self._where.get(request_id)
        if src is None or self._dead[src]:
            return False
        cands = [i for i in self._routable_idx() if i != src]
        if not cands:
            return False
        done = threading.Event()
        box: dict = {}

        def handoff(payload):
            box["p"] = payload
            done.set()

        self._engines[src].request_migration(request_id, handoff)
        if not done.wait(timeout_s):
            return False
        payload = box.get("p")
        if payload is None:
            return False
        kind = payload[0]
        rank = 1
        if target is None:
            target = min(cands, key=lambda i: (self._load(i, rank), i))
        if kind == "fresh":
            req = payload[1]
            self._note_where(request_id, target)
            self._engines[target].submit(req)
        else:
            entry, keys = payload[1], payload[2]
            self._pin(request_id, keys)
            self._await_offload(keys)
            if not self._engines[target].adopt_resume(entry):
                # target can't adopt (no scheduler): re-park at home
                self._engines[src].adopt_resume(entry)
                return False
            self._note_where(request_id, target)
        aud = self._engines[target]._kv_audit
        if aud is not None:
            aud.ledger.record("migrate", slot=(src, target), rid=request_id)
        self._migrations[reason] = self._migrations.get(reason, 0) + 1
        EVENTS.emit("migrate", rid=request_id, src=src, dst=target,
                    reason=reason, kind=kind,
                    n_decoded=(payload[1].n_decoded
                               if kind == "resume" else 0))
        return True

    # ---------- crash recovery ----------

    def _fail_stream(self, req, why: str):
        req.out.put(eng.StreamEvent(
            token_id=-1, text="", logprob=0.0, finish_reason="stop",
            error=why, error_kind="replica_down"))
        req.out.put(None)

    def _adopt_on_sibling(self, rid: str, entry: ResumeEntry, src: int,
                          reason: str = "crash") -> bool:
        cands = [i for i in self._routable_idx() if i != src]
        if not cands:
            return False
        rank = PRIORITY_RANK.get(entry.priority, 1)
        target = min(cands, key=lambda i: (self._load(i, rank), i))
        tgt = self._engines[target]
        if tgt._pcache is not None:
            keys = list(tgt._pcache.chain_keys(entry.ids))
            self._pin(rid, keys)
        if not tgt.adopt_resume(entry):
            return False
        if tgt._kv_audit is not None:
            tgt._kv_audit.ledger.record("adopt", slot=(src, target),
                                        rid=rid)
        self._note_where(rid, target)
        self._migrations[reason] = self._migrations.get(reason, 0) + 1
        EVENTS.emit("migrate", rid=rid, src=src, dst=target,
                    reason=reason, kind="resume",
                    n_decoded=entry.n_decoded)
        return True

    def _recover_replica(self, i: int):
        """A replica's loop thread died without shutdown (crashed host
        analogue). Its device tier is lost; everything it was serving
        re-routes to siblings and restores from the shared host tier —
        warm chains splice back, cold ones re-prefill the identical
        history (DejaVu: crash recovery from streamed cache)."""
        e = self._engines[i]
        self._dead[i] = True
        EVENTS.emit("replica_down", replica=i,
                    slots_in_flight=e.num_active,
                    queued=e._queue.qsize(),
                    parked=(e._sched.resume_depth
                            if e._sched is not None else 0))
        log.warning("engine pool: replica %d loop died; recovering", i)
        # settle client streams + detok state: the emitter owns both
        if e._emitter is not None:
            e._emitter.drain(2.0)
        # its device pages are gone: forget them pool-wide
        self._shared.index.clear_replica(i)
        if self._shared.store is not None:
            self._shared.store.unmap_owner(i)
        recovered = failed = 0
        # in-flight slots -> ResumeEntries adopted by siblings
        for slot, s in enumerate(e.slots):
            if s is None:
                continue
            e.slots[slot] = None
            rid = s.req.request_id
            ok = False
            if e._sched is not None and e._preempt_eligible(slot, s):
                hist = list(e._cache_tokens[slot])
                if len(hist) < s.prompt_len:
                    hist = list(s.req.prompt_ids) + list(s.generated)
                entry = ResumeEntry(
                    req=s.req, ids=hist, priority=s.req.priority,
                    generated=list(s.generated), n_decoded=s.n_decoded,
                    prompt_len=s.prompt_len, detok=s.detok,
                    held_text=s.held_text, t_start=s.t_start,
                    t_first_token=s.t_first_token or None,
                    t_prefill_ms=s.t_prefill_ms, mu=float(e.mu[slot]),
                    preempt_count=s.preempts)
                ok = self._adopt_on_sibling(rid, entry, src=i)
            if ok:
                recovered += 1
            else:
                failed += 1
                self._fail_stream(s.req, f"replica {i} died; request not "
                                         f"recoverable on a sibling")
        # parked resumes migrate wholesale
        if e._sched is not None:
            for entry in e._sched.drain_parked():
                if self._adopt_on_sibling(entry.req.request_id, entry,
                                          src=i):
                    recovered += 1
                else:
                    failed += 1
                    self._fail_stream(entry.req,
                                      f"replica {i} died; request not "
                                      f"recoverable on a sibling")
        # queued requests re-route (nothing computed: plain resubmit)
        while True:
            try:
                r = e._queue.get_nowait()
            except queue.Empty:
                break
            try:
                tgt = self._route(r)
                self._note_where(r.request_id, tgt)
                self._engines[tgt].submit(r)
                recovered += 1
            except Exception:
                failed += 1
                self._fail_stream(r, f"replica {i} died; no live sibling")
        EVENTS.emit("replica_recovered", replica=i, recovered=recovered,
                    failed=failed)
        log.warning("engine pool: replica %d recovery done "
                    "(recovered=%d failed=%d)", i, recovered, failed)

    # ---------- housekeeping ----------

    def _housekeeping(self):
        """Health checks + drain-free queue rebalancing + the autoscale
        policy tick, ~10 Hz."""
        while not self._hk_stop.wait(0.1):
            try:
                for i, e in enumerate(self._engines):
                    if self._dead[i] or e._thread is None:
                        continue
                    if not e.loop_alive and not e._stop:
                        self._recover_replica(i)
                self._rebalance_queued()
                self._autoscale_tick()
                t0 = time.monotonic()
                if t0 - self._t_kv_audit > 0.5:
                    self._t_kv_audit = t0
                    self._audit_shared()
            except Exception:
                log.exception("engine pool housekeeping failed")

    # ---------- autoscaling (ISSUE 19) ----------

    def autoscale_signals(self):
        """Policy-input snapshot over ROUTABLE replicas. Gathered on the
        housekeeping thread from plain attribute reads — no engine locks
        beyond what qsize()/SLO snapshots already take."""
        from localai_tpu.services.sysobs import AutoscaleSignals

        engines = [self._engines[i] for i in self._routable_idx()]
        queued = sum(e._queue.qsize() for e in engines)
        slots = sum(len(e.slots) for e in engines)
        active = sum(e.num_active for e in engines)
        burn = 0.0
        free = 1.0
        pre = 0.0
        for e in engines:
            if e._slo is not None and e._slo.enabled:
                burn = max(burn, e._slo.max_burn())
            if e._paged:
                free = min(free, e._pool.free_pages
                           / max(1, e._pool.num_pages))
            pre += getattr(e, "_preempt_rate_ewma", 0.0)
        # effective (co-scaled) admission budget, not the static knob:
        # a scaled-in pool's queue reads proportionally fuller, so the
        # scale-out trigger fires at the same relative pressure
        cap = sum(e.maxq_effective for e in engines)
        return AutoscaleSignals(
            replicas=len(engines), queued=queued,
            queue_frac=(queued / cap) if cap > 0 else 0.0,
            busy_frac=(active / slots) if slots else 0.0,
            burn_5m=burn, free_page_frac=free,
            preempt_rate_per_min=pre)

    def _autoscale_tick(self):
        """Feed the policy; execute a returned target on a worker thread
        so a multi-second spin-up/drain never blocks health checks. At
        most one resize in flight — the policy is not sampled while one
        runs (its signals would be mid-transition noise)."""
        if self._policy is None:
            return
        if self._resize_thread is not None and \
                self._resize_thread.is_alive():
            return
        tgt = self._policy.sample(self.autoscale_signals())
        if tgt is None or tgt == len(self._routable_idx()):
            return
        self.target_replicas = tgt
        self._resize_thread = threading.Thread(
            target=self._resize_safely, args=(tgt,),
            name="pool-resize", daemon=True)
        self._resize_thread.start()

    def _resize_safely(self, n: int):
        try:
            self.resize(n, reason="autoscale")
        except Exception:
            log.exception("engine pool: autoscale resize to %d failed", n)

    def resize(self, n: int, reason: str = "manual") -> int:
        """Bring the ROUTABLE replica count to ``n`` one step at a time;
        returns the resulting count. Scale-out appends a freshly started
        replica (shared device weights — no load; shared host KV tier —
        it splices warm chains from the first affinity hit). Scale-in
        drains the highest-index replica through the existing migrate
        path and retires it; a drain that cannot complete aborts the
        step and the replica returns to service (never strands work)."""
        with self._resize_lock:
            n = max(1, int(n))
            n0 = len(self._routable_idx())
            while True:
                cur = len(self._routable_idx())
                if cur == n:
                    break
                if cur < n:
                    self._scale_out(reason)
                else:
                    if not self._scale_in(reason):
                        break
            self.target_replicas = n
            got = len(self._routable_idx())
            if got != n0:
                for i in self._routable_idx():
                    # re-anchor the preemption-EWMA reserve to the new
                    # replica count (ISSUE 19 satellite)
                    self._engines[i].note_pool_resize(n0, got)
                self._rescale_admission(got)
            return got

    def _rescale_admission(self, width: int):
        """Admission-limit co-scaling (ISSUE 20): each live replica's
        effective max_queued_requests scales with live width over
        CONFIGURED width, so a scaled-in pool sheds at the narrower
        width's limit (half the replicas -> half the queue promise per
        survivor) instead of buffering the full fleet's depth behind
        fewer engines. At the configured width this is exactly the
        configured knob — bit-for-bit the static-pool behavior."""
        if self._maxq_base <= 0:
            return                  # unbounded stays unbounded
        eff = max(1, round(self._maxq_base * width
                           / max(1, self._configured_width)))
        for i in self._routable_idx():
            self._engines[i].maxq_effective = eff
        EVENTS.emit("queue_limit_rescaled", width=width,
                    configured=self._configured_width,
                    per_replica=eff, pool=eff * max(1, width))

    def _scale_out(self, reason: str):
        if self._build_args is None:
            raise RuntimeError("pool not built via EnginePool.build(); "
                               "resize unavailable")
        a = self._build_args
        rid = len(self._engines)
        t0 = time.monotonic()
        e = eng.Engine(a["model_cfg"], a["params"], a["tokenizer"],
                       a["ecfg"], eos_token_ids=a["eos_token_ids"],
                       mesh=a["mesh"],
                       param_shardings=a["param_shardings"],
                       draft=a["draft"], family=a["family"],
                       replica_id=rid, shared_kv=self._shared)
        # fully started BEFORE it becomes visible to routing: _dead grows
        # first so len(_engines) never outruns it for lock-free readers
        e.start(precompile=self._precompile)
        with self._lock:
            self._dead.append(False)
            self._engines.append(e)
        ms = (time.monotonic() - t0) * 1000.0
        EVENTS.emit("scale_out", replica=rid, reason=reason,
                    spinup_ms=round(ms, 1),
                    replicas=len(self._routable_idx()))
        log.info("engine pool: scale-out -> replica %d (%s, %.0f ms)",
                 rid, reason, ms)

    def _scale_in(self, reason: str, timeout_s: float = 10.0) -> bool:
        routable = self._routable_idx()
        if len(routable) <= 1:
            return False
        i = routable[-1]
        e = self._engines[i]
        self._draining.add(i)
        try:
            # 1) queued work: nothing computed — plain re-route
            while True:
                try:
                    r = e._queue.get_nowait()
                except queue.Empty:
                    break
                tgt = self._route(r)
                self._note_where(r.request_id, tgt)
                self._engines[tgt].submit(r)
            # 2) parked resumes: adopt on siblings (splice from shared)
            if e._sched is not None:
                parked = e._sched.drain_parked()
                for k, entry in enumerate(parked):
                    if not self._adopt_on_sibling(
                            entry.req.request_id, entry, src=i,
                            reason="scale_in"):
                        for rest in parked[k:]:
                            e._sched.adopt(rest)   # re-park, undrained
                        raise _DrainAbort()
            # 3) in-flight slots: live migration, byte-gate preserved
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                rids = [s.req.request_id for s in e.slots if s is not None]
                if not rids:
                    break
                for r_id in rids:
                    self.migrate(r_id, reason="scale_in")
                time.sleep(0.02)
            if any(s is not None for s in e.slots):
                raise _DrainAbort()
        except _DrainAbort:
            self._draining.discard(i)
            log.warning("engine pool: scale-in of replica %d aborted "
                        "(drain incomplete); replica stays in service", i)
            return False
        # empty: retire cleanly. Its device tier goes away with it.
        e.shutdown()
        self._shared.index.clear_replica(i)
        if self._shared.store is not None:
            self._shared.store.unmap_owner(i)
        with self._lock:
            self._dead[i] = True
            self._retired.add(i)
        self._draining.discard(i)
        EVENTS.emit("scale_in", replica=i, reason=reason,
                    replicas=len(self._routable_idx()))
        log.info("engine pool: scale-in retired replica %d (%s)",
                 i, reason)
        return True

    def _audit_shared(self):
        """Invariant scan of the SHARED host tier (ISSUE 15): byte
        accounting vs summed entry sizes, parent/child map consistency,
        sampled CRC of retained entries. Reports through the auditor the
        first replica attached to the store (its ledger already records
        the store-level transitions), so counters, events and flight
        dumps ride the same path as device-tier violations."""
        store = self._shared.store
        aud = store.audit if store is not None else None
        if aud is not None:
            aud.scan_shared(store)

    def _rebalance_queued(self):
        """When one replica has work QUEUED behind full slots while a
        sibling sits with a free slot and an empty queue, re-route one
        queued request (nothing computed yet — this is the zero-risk
        half of drain-free rebalancing; active-slot migration stays
        explicit via migrate())."""
        alive = self._routable_idx()
        if len(alive) < 2:
            return
        for i in alive:
            src = self._engines[i]
            if src._queue.qsize() == 0 or src._free_count() > 0:
                continue
            idle = [j for j in alive
                    if j != i and self._engines[j]._free_count() > 0
                    and self._engines[j]._queue.qsize() == 0]
            if not idle:
                continue
            with src._queue.mutex:
                r = src._queue.queue[0] if src._queue.queue else None
                if r is not None:
                    src._queue.queue.remove(r)
            if r is None:
                continue
            rank = PRIORITY_RANK.get(r.priority, 1)
            j = min(idle, key=lambda x: (self._load(x, rank), x))
            self._note_where(r.request_id, j)
            self._engines[j].submit(r)
            self._migrations["rebalance"] += 1
            EVENTS.emit("migrate", rid=r.request_id, src=i, dst=j,
                        reason="rebalance", kind="fresh")

    # ---------- observability ----------

    def metrics(self) -> dict:
        ms = [e.metrics() for e in self._engines]
        out = dict(ms[0])
        for k in ("slots_total", "slots_active", "queued",
                  "total_tokens_generated", "tokens_per_second_active",
                  "prompt_tokens_reused"):
            out[k] = sum(m.get(k) or 0 for m in ms)
        out["uptime_s"] = max(m.get("uptime_s", 0) for m in ms)
        out["engine_replicas"] = len(self._engines)
        out["engine_replicas_target"] = self.target_replicas
        # effective (co-scaled) pool admission budget ->
        # localai_engine_queue_limit (ISSUE 20)
        out["queue_limit"] = sum(self._engines[i].maxq_effective
                                 for i in self._routable_idx())
        out["replicas"] = [{
            "replica": i,
            "alive": not self._dead[i],
            "draining": i in self._draining,
            "queued": m.get("queued", 0) if not self._dead[i] else 0,
            "slots_in_flight": (m.get("slots_active", 0)
                                if not self._dead[i] else 0),
            "slots_total": m.get("slots_total", 0),
            "resume_depth": (m.get("scheduler") or {}).get(
                "resume_depth", 0),
            "resume_reserve_pages": (m.get("scheduler") or {}).get(
                "resume_reserve_pages", 0),
            "tokens": m.get("total_tokens_generated", 0),
        } for i, m in enumerate(ms)]
        out["pool"] = {
            "replicas_alive": sum(1 for d in self._dead if not d),
            "replicas_target": self.target_replicas,
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "routed": self._routed,
            "migrations": dict(self._migrations),
            "index_keys": len(self._shared.index),
        }
        if self._policy is not None:
            out["pool"]["autoscale"] = self._policy.snapshot()
        # lifecycle auditor (ISSUE 15): counters summed pool-wide (the
        # shared-store scans report through the attached auditor, so
        # they're inside one replica's snapshot already)
        kas = [m.get("kv_audit") for m in ms if m.get("kv_audit")]
        if kas:
            out["kv_audit"] = {
                "mode": kas[0].get("mode", "on"),
                "checks": sum(k.get("checks", 0) for k in kas),
                "violations": sum(k.get("violations", 0) for k in kas),
                "leaked_pages": sum(k.get("leaked_pages", 0) for k in kas),
                "ledger_events": sum(k.get("ledger_events", 0)
                                     for k in kas),
                "last_violations": [v for k in kas
                                    for v in k.get("last_violations",
                                                   [])][-16:],
            }
        return out

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        """Pool-wide on-demand audit: shared host tier first (counters
        land on the attached replica's auditor), then every LIVE
        replica's full pass. Dead replicas are skipped — their device
        mirrors froze wherever the crash left them and their pages were
        recovered onto siblings, which the siblings' scans cover."""
        store = self._shared.store
        aud = store.audit if store is not None else None
        if aud is not None:
            aud.scan_shared(store)
        out = {"mode": "off", "checks": 0, "violations": 0,
               "leaked_pages": 0, "ledger_events": 0}
        for i, e in enumerate(self._engines):
            if self._dead[i]:
                continue
            snap = e.kv_audit_sweep(drained=drained)
            if snap.get("mode") != "off":
                out["mode"] = snap["mode"]
                for k in ("checks", "violations", "leaked_pages",
                          "ledger_events"):
                    out[k] += snap.get(k, 0)
        return out

    def kv_debug(self) -> dict:
        """/debug/kv merged view across replicas + the shared host tier
        (ISSUE 15)."""
        out = {
            "engine_replicas": len(self._engines),
            "engine_replicas_target": self.target_replicas,
            "replicas": [e.kv_debug() for e in self._engines],
            "pool_index_keys": len(self._shared.index),
        }
        store = self._shared.store
        if store is not None:
            out["shared_host"] = store.stats()
        return out

    def state_snapshot(self) -> dict:
        out = {
            "engine_replicas": len(self._engines),
            "pool": {
                "replicas_alive": sum(1 for d in self._dead if not d),
                "affinity_hits": self.affinity_hits,
                "migrations": dict(self._migrations),
            },
            "replicas": [e.state_snapshot() for e in self._engines],
        }
        # target-vs-actual + last decision for /debug/state and /readyz
        # (ISSUE 19) — present whenever pooled so operators see the loop
        # (or that it's off)
        out["autoscale"] = {
            "enabled": self._policy is not None,
            "target": self.target_replicas,
            "replicas_alive": sum(1 for d in self._dead if not d),
            "replicas_routable": len(self._routable_idx()),
            "last_decision": (self._policy.last_decision
                              if self._policy is not None else None),
        }
        return out

    def trace_events(self) -> dict:
        out = self._engines[0].trace_events()
        evs = out.get("traceEvents")
        if isinstance(evs, list):
            for e in self._engines[1:]:
                more = e.trace_events().get("traceEvents")
                if isinstance(more, list):
                    evs.extend(more)
        return out
