"""Cross-release prefix cache: a token-hash-keyed store of retained pages.

PR 1's paged pool only reuses a prompt prefix while it is resident in
some live slot's page table — once a slot is reclaimed for an unrelated
prompt its pages go back to the free list and the next turn of the same
conversation pays a full prefill. This module is the fix the ROADMAP
names as the top paged-KV follow-up: page-level prefix reuse ACROSS
releases, the paged analogue of KV retention/prefetch schemes like
PRESERVE (arXiv:2501.08192) and DejaVu (arXiv:2403.01876), applied at
page granularity on the existing copy-on-write pool.

Design:
  * Identity is a CHAINED BLOCK HASH over token ids, never page content:
    key_i = hash(scope, key_{i-1}, tokens[i*pg:(i+1)*pg])  (kvcache.
    page_chain_hash). The scope folds model geometry + page size into
    every link, so different tokenizations or layouts can never alias;
    the parent chain makes "same page tokens, different history" two
    distinct keys — a hash-chain divergence at page j hides every page
    past j, which is exactly the false-reuse guard the paged layout
    needs (a page's rows encode its absolute position via RoPE).
  * On slot release / context shift the engine calls insert(): each
    committed FULL page gets a pool.hold() reference and a store entry.
    The page is then RETAINED — alive after every slot table lets go.
  * At admission the engine calls match(): the chain is walked from the
    root; contiguous present links yield the physical pages to splice
    into the new slot's table (PagePool.splice — ref-counted, zero KV
    row copies). The boundary write is protected by the engine's
    existing COW guard: a retained page always has refs >= 2 once it is
    in a table again, so the first divergent write clones it.
  * Under pool pressure the engine calls evict(): entries die LRU-first
    (ties: deepest chain link first — children are never more recent
    than their parents, since every touch walks root-down), each drop()
    returning its page to the free list once nothing else references
    it. Eviction never blocks and never touches live slots, so the
    reclaim path stays deadlock-free under oversubscription.

Entries are one page each, so the store is bounded by the pool size;
there is no separate capacity knob — pool pressure IS the bound.

ISSUE 14 (replica pools): the cache stays single-threaded (engine-loop
only), but it can now REPORT its membership changes through optional
``on_insert`` / ``on_remove`` / ``on_clear`` callbacks so an EnginePool
can maintain one cross-replica PoolPrefixIndex (chain key -> which
replicas hold it at what depth) and the shared HostPageStore's mapping
refcounts. With no callbacks installed (the default, engines=1) every
code path is byte-identical to before.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

from localai_tpu.ops import kvcache
from localai_tpu.services.faults import FAULTS


class _Entry:
    __slots__ = ("key", "parent", "page", "depth", "tick")

    def __init__(self, key: bytes, parent: bytes, page: int, depth: int,
                 tick: int):
        self.key = key
        self.parent = parent
        self.page = page
        self.depth = depth      # chain position (0 = first page)
        self.tick = tick        # LRU clock at last touch


class PrefixPageCache:
    """Host-side index of retained pages; the PagePool owns the pages."""

    def __init__(self, scope: bytes, page_size: int,
                 on_insert=None, on_remove=None, on_clear=None):
        self.scope = scope
        self.page_size = page_size
        self._entries: dict[bytes, _Entry] = {}
        self._children: dict[bytes, set] = {}
        self._tick = 0
        # pool-mode membership hooks (ISSUE 14); None = standalone
        self._on_insert = on_insert    # (key, depth) -> None
        self._on_remove = on_remove    # (key,) -> None
        self._on_clear = on_clear      # () -> None
        # telemetry (absolute, monotonic — exported as counters)
        self.hits = 0            # admissions served from the store
        self.misses = 0          # store consulted, no usable chain
        self.hit_rows = 0        # prompt rows reused via the store
        self.inserted_pages = 0
        self.evicted_pages = 0
        # lifecycle ledger/auditor (ISSUE 15): attached by the engine
        # when kv_audit != off; None = zero-cost no-op
        self.audit = None

    # ---------- introspection ----------

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def pages_held(self) -> int:
        return len(self._entries)   # one page per entry, deduped by key

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rows": self.hit_rows,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }

    def pages(self) -> list:
        """Physical pages currently held (one per entry) — the
        auditor's leak-freedom scan counts these as accounted-for."""
        return [e.page for e in self._entries.values()]

    def contains(self, key: bytes) -> bool:
        """Device-tier membership probe WITHOUT an LRU touch — the
        prefetch planner (ISSUE 16) uses it to skip pages that are
        already resident without promoting them over genuinely hot
        chains."""
        return key in self._entries

    def genealogy(self, limit: int = 64) -> list:
        """Per-chain genealogy for /debug/kv (ISSUE 15): the newest
        ``limit`` entries as {key, parent, page, depth, tick}, keys
        abbreviated to 8 bytes hex."""
        items = sorted(self._entries.values(),
                       key=lambda e: (e.tick, e.depth))[-int(limit):]
        return [{"key": e.key[:8].hex(), "parent": e.parent[:8].hex(),
                 "page": e.page, "depth": e.depth, "tick": e.tick}
                for e in items]

    # ---------- the hash chain ----------

    def chain_keys(self, ids) -> Iterable[bytes]:
        """Chain keys for every FULL page of ids, root-down."""
        pg = self.page_size
        parent = kvcache.PAGE_HASH_ROOT
        for i in range(len(ids) // pg):
            parent = kvcache.page_chain_hash(
                parent, ids[i * pg:(i + 1) * pg], self.scope)
            yield parent

    # ---------- store operations ----------

    def insert(self, pool, slot: int, toks) -> int:
        """Index the slot's committed full pages under their chain keys
        (called BEFORE the pool release drops the slot's references).
        Existing keys are touched, not replaced — two slots that
        independently prefilled the same prefix dedup to one retained
        copy; the newcomer's pages simply free with its table. Returns
        the number of newly retained pages."""
        self._tick += 1
        added = 0
        n_full = min(len(toks) // self.page_size, int(pool.owned[slot]))
        parent = kvcache.PAGE_HASH_ROOT
        for i, key in enumerate(self.chain_keys(toks)):
            if i >= n_full:
                break
            e = self._entries.get(key)
            if e is not None:
                e.tick = self._tick
                parent = key
                continue
            page = int(pool.ptab[slot, i])
            if page >= pool.num_pages or pool.refs[page] <= 0:
                break   # unallocated tail; nothing past it is committed
            pool.hold(page)
            self._entries[key] = _Entry(key, parent, page, i, self._tick)
            self._children.setdefault(parent, set()).add(key)
            if self._on_insert is not None:
                self._on_insert(key, i)
            if self.audit is not None:
                self.audit.ledger.record("retain", page=page, slot=slot,
                                         key=key)
            added += 1
            parent = key
        self.inserted_pages += added
        return added

    def match(self, ids, max_pages: int) -> list:
        """Longest contiguous chain match over ids' full pages. Returns
        the physical pages root-down (possibly empty); every matched
        entry (and thus its whole ancestor path) is LRU-touched."""
        self._tick += 1
        pages: list = []
        for key in self.chain_keys(ids):
            if len(pages) >= max_pages:
                break
            e = self._entries.get(key)
            if e is None:
                break
            e.tick = self._tick
            pages.append(e.page)
        return pages

    def evict(self, pool, need_free: int, on_evict=None) -> int:
        """Drop entries LRU-first until the pool has need_free free
        pages or the store is empty. Ties evict the deepest chain link
        first, and removal cascades to descendants (an orphaned child is
        unreachable — match() walks root-down). Returns pages dropped.

        ``on_evict(entry)`` is the device->host OFFLOAD handoff: called
        for every removed entry BEFORE its pool reference drops, while
        the page id still names valid rows — the engine collects the
        victims and dispatches one device gather for the batch (the
        copy executes before any later dispatch can overwrite the freed
        page, by device program order)."""
        if not self._entries or pool.free_pages >= need_free:
            return 0
        victims = sorted(self._entries.values(),
                         key=lambda e: (e.tick, -e.depth))
        dropped = 0
        for e in victims:
            if pool.free_pages >= need_free:
                break
            if e.key not in self._entries:
                continue    # already cascaded away
            dropped += self._remove_tree(pool, e.key, on_evict)
        self.evicted_pages += dropped
        return dropped

    def _remove_tree(self, pool, key: bytes, on_evict=None) -> int:
        n = 0
        stack = [key]
        while stack:
            k = stack.pop()
            e = self._entries.pop(k, None)
            if e is None:
                continue
            stack.extend(self._children.pop(k, ()))
            kids = self._children.get(e.parent)
            if kids is not None:
                kids.discard(k)
                if not kids:
                    del self._children[e.parent]
            if on_evict is not None:
                on_evict(e)
            if self._on_remove is not None:
                self._on_remove(k)
            if self.audit is not None:
                self.audit.ledger.record("evict", page=e.page, key=k)
            # kv_leak fault (ISSUE 15): suppress exactly one retention
            # drop at the production eviction seam — the injected
            # refcount leak the online auditor must catch (the page
            # stays referenced but reachable from no table or cache)
            if not (FAULTS.active and FAULTS.take("kv_leak")):
                pool.drop(e.page)
            n += 1
        return n

    def attach(self, pool, key: bytes, parent: bytes, page: int,
               depth: int) -> bool:
        """Re-enter a RESTORED page into the device tier: the host-tier
        hit just uploaded its rows into a freshly allocated page already
        referenced by the admitting slot's table — hold it and index it
        so the next match is device-resident (restore repopulates tier
        1, it doesn't bypass it). No-op if the key re-appeared (two
        concurrent restores of one chain dedup to the first)."""
        if key in self._entries:
            return False
        pool.hold(page)
        self._entries[key] = _Entry(key, parent, page, depth, self._tick)
        self._children.setdefault(parent, set()).add(key)
        if self._on_insert is not None:
            self._on_insert(key, depth)
        if self.audit is not None:
            self.audit.ledger.record("retain", page=page, key=key)
        return True

    def clear(self):
        """Forget everything WITHOUT touching a pool — for device-state
        resets, where the pool object itself is rebuilt and the old
        holds die with it. Counters survive (telemetry continuity)."""
        self._entries.clear()
        self._children.clear()
        if self._on_clear is not None:
            self._on_clear()

    # ---------- engine-side accounting helpers ----------

    def note_hit(self, rows: int):
        self.hits += 1
        self.hit_rows += int(rows)

    def note_miss(self):
        self.misses += 1


class PoolPrefixIndex:
    """Cross-replica chain-hash index for an EnginePool (ISSUE 14).

    Maps chain key -> {replica_id: depth} for every page currently
    retained in SOME replica's device tier. Fed by the per-replica
    PrefixPageCache membership callbacks (each fires on its own engine
    loop thread — all methods lock), queried by the pool's admission
    router: "which replica holds the longest live chain match for this
    prompt?" Depths are chain positions (0 = first page), so a replica
    matching keys [0, d) serves d pages of prefill for free.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._where: dict[bytes, dict[int, int]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._where)

    def keys(self) -> list:
        """Every chain key some replica's device tier retains — the
        host's contribution to the cluster routing digest (ISSUE 17)."""
        with self._lock:
            return list(self._where)

    def note_insert(self, replica: int, key: bytes, depth: int) -> None:
        with self._lock:
            self._where.setdefault(key, {})[replica] = depth

    def note_remove(self, replica: int, key: bytes) -> None:
        with self._lock:
            holders = self._where.get(key)
            if holders is not None:
                holders.pop(replica, None)
                if not holders:
                    del self._where[key]

    def clear_replica(self, replica: int) -> int:
        """Forget every key a replica held (device reset, replica
        death). Returns how many keys it was holding."""
        n = 0
        with self._lock:
            for key in list(self._where):
                holders = self._where[key]
                if replica in holders:
                    del holders[replica]
                    n += 1
                    if not holders:
                        del self._where[key]
        return n

    def match_depths(self, keys) -> dict:
        """{replica: matched_pages} of CONTIGUOUS root-down chain
        matches over ``keys``. A replica r appears with value d iff it
        holds keys[0..d-1]; replicas drop out of the running at their
        first gap (a hole hides everything past it — pages encode
        absolute position)."""
        depths: dict = {}
        cur: set = set()
        with self._lock:
            for i, k in enumerate(keys):
                holders = self._where.get(k)
                if not holders:
                    break
                cur = set(holders) if i == 0 else (cur & set(holders))
                if not cur:
                    break
                for r in cur:
                    depths[r] = i + 1
        return depths

    def replica_pages(self, replica: int) -> int:
        with self._lock:
            return sum(1 for h in self._where.values() if replica in h)


def build_scope(family: str, cfg, page_size: int, cache_dtype) -> bytes:
    """The engine's scope recipe: family + attention geometry + context
    + cache dtype + page size. Everything that changes what a page's KV
    rows MEAN must be in here."""
    return kvcache.page_scope(
        page_size, family,
        getattr(cfg, "num_layers", 0), getattr(cfg, "num_kv_heads", 0),
        getattr(cfg, "head_dim_", getattr(cfg, "head_dim", 0)),
        getattr(cfg, "vocab_size", 0),
        getattr(cfg, "rope_theta", 0), str(cache_dtype))
