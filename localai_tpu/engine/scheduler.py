"""Priority-aware preemptive scheduler.

The engine historically served strictly FIFO: `_prefill_queue` was walked
in arrival order, admission popped the oldest queued request, and a slot
held its device pages until completion.  This module owns the per-tick run
decision instead:

  * every request carries a ``priority`` class (``high`` / ``normal`` /
    ``low``), set per-request (OpenAI body field -> gRPC invocation
    metadata) or as a model default on the options wire;
  * each class gets a weighted fair share of the packed-prefill token
    budget via deficit round-robin — a burst of one class cannot
    monopolize a tick, but unused budget rolls to whoever has work;
  * under pool pressure or a higher-priority arrival the engine PREEMPTS
    an active victim slot: the slot pauses at a burst boundary, its
    committed pages stay retained in the prefix cache (and offload to the
    host tier under pressure through the normal reclaim path), and the
    request parks in a resume queue until capacity returns.  Resume is
    plain re-admission — the chained-hash splice (device or host tier)
    restores the KV, and a killed host entry degrades to a re-prefill of
    the identical token history (the continuation is conditioned exactly
    as a fresh submission of that history would be);
  * a starvation guard bounds how often one request may be preempted
    (``max_preemptions``) and ages long-queued work up one effective
    class so ``low`` traffic cannot wait forever behind a ``high`` flood.

The scheduler holds no engine state beyond bookkeeping: pausing, paging
and re-admission stay in `engine.py`; this module only decides *who* runs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# Priority classes, highest first.  Rank is the index: lower rank wins.
PRIORITY_CLASSES: Tuple[str, ...] = ("high", "normal", "low")
PRIORITY_RANK: Dict[str, int] = {c: i for i, c in enumerate(PRIORITY_CLASSES)}
DEFAULT_PRIORITY = "normal"
DEFAULT_WEIGHTS = "4:2:1"


def normalize_priority(value: Any, default: str = DEFAULT_PRIORITY) -> str:
    """Map arbitrary wire input to a known class; unknown -> default."""
    if isinstance(value, str):
        v = value.strip().lower()
        if v in PRIORITY_RANK:
            return v
    return default


def parse_priority_weights(spec: str) -> Tuple[int, ...]:
    """Parse ``high:normal:low`` colon-separated integer weights.

    Option values ride a comma-joined wire, hence colons.  Raises
    ``ValueError`` on anything that is not exactly three positive ints.
    """
    parts = [p.strip() for p in str(spec).split(":")]
    if len(parts) != len(PRIORITY_CLASSES):
        raise ValueError(
            f"priority_weights needs {len(PRIORITY_CLASSES)} colon-separated "
            f"integers (high:normal:low), got {spec!r}"
        )
    try:
        weights = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(f"priority_weights must be integers, got {spec!r}")
    if any(w <= 0 for w in weights):
        raise ValueError(f"priority_weights must be positive, got {spec!r}")
    return weights


@dataclass
class ResumeEntry:
    """A preempted request parked until capacity returns.

    Carries everything the engine needs to re-admit the request as a
    continuation: the full token history (prompt + committed generated
    tokens), the streaming detokenizer state, and the accounting that
    must survive the pause (first-token time, decoded counts, mirostat
    state, preemption count).
    """

    req: Any
    ids: List[int]  # prompt + generated tokens processed so far
    priority: str = DEFAULT_PRIORITY
    generated: List[int] = field(default_factory=list)
    n_decoded: int = 0
    prompt_len: int = 0
    detok: Any = None
    held_text: str = ""
    t_start: float = 0.0
    t_first_token: Optional[float] = None
    t_prefill_ms: float = 0.0
    mu: Optional[float] = None
    preempt_count: int = 1
    t_parked: float = field(default_factory=time.monotonic)


class Scheduler:
    """Deficit-round-robin priority scheduler with a resume queue.

    Engine contract per tick:
      1. ``begin_tick(budget)`` refreshes the per-class prefill deficits.
      2. ``take(cls, want)`` caps how many prompt tokens a slot of class
         ``cls`` may pack this tick (charged via the return value).
      3. ``pick_queued(snapshot)`` orders queued work for admission.
      4. ``pick_victim(active)`` chooses a preemption victim when a
         higher-priority request cannot be admitted.
      5. ``park``/``pop_resume`` manage paused requests.
    """

    def __init__(
        self,
        weights: Tuple[int, ...] = parse_priority_weights(DEFAULT_WEIGHTS),
        max_preemptions: int = 2,
        aging_ms: float = 4000.0,
    ):
        self.weights = tuple(weights)
        self.max_preemptions = int(max_preemptions)
        self.aging_ms = float(aging_ms)
        # DRR deficit counters, one per class, in prompt tokens.
        self._deficit = [0] * len(PRIORITY_CLASSES)
        self._resume: List[ResumeEntry] = []
        # counters (exported via engine metrics())
        self.preemptions = 0
        self.adoptions = 0      # entries migrated IN from a sibling
        self.resumes = 0
        self.resume_reprefills = 0
        self.resume_restore_rows = 0
        self.aged_promotions = 0

    # ---- class helpers -------------------------------------------------

    def effective_rank(self, priority: str, waited_s: float) -> int:
        """Rank after aging: long-queued work is promoted one class."""
        rank = PRIORITY_RANK.get(priority, PRIORITY_RANK[DEFAULT_PRIORITY])
        if rank > 0 and self.aging_ms > 0 and waited_s * 1000.0 >= self.aging_ms:
            rank -= 1
        return rank

    # ---- deficit round-robin over the prefill token budget -------------

    def begin_tick(self, budget: int, pending_by_class: List[int]) -> None:
        """Refresh deficits for one packed-prefill walk.

        Each class with pending prompt tokens earns its weighted share of
        ``budget``; classes with no work forfeit their share to the ones
        that have some (work-conserving).  Deficits carry over so a class
        shortchanged by granularity (chunk boundaries) catches up on the
        next tick, but are clamped to one budget so an idle class cannot
        bank unbounded credit.
        """
        active = [i for i, n in enumerate(pending_by_class) if n > 0]
        if not active:
            return
        wsum = sum(self.weights[i] for i in active)
        for i in range(len(PRIORITY_CLASSES)):
            if i in active:
                share = budget * self.weights[i] // max(1, wsum)
                self._deficit[i] = min(self._deficit[i] + share, 2 * budget)
            else:
                self._deficit[i] = 0

    def take(self, rank: int, want: int, slack: int = 0) -> int:
        """Grant up to ``want`` prompt tokens against class ``rank``'s deficit.

        ``slack`` is budget no other class can use this tick (their queues
        are empty); it is granted beyond the deficit so the walk stays
        work-conserving.
        """
        if want <= 0:
            return 0
        grant = min(want, self._deficit[rank] + max(0, slack))
        used_deficit = min(grant, self._deficit[rank])
        self._deficit[rank] -= used_deficit
        return grant

    def deficit(self, rank: int) -> int:
        return self._deficit[rank]

    def burst_share(self, dec_rank: Optional[int],
                    pending_by_class: List[int], cap: int) -> int:
        """Weighted decode-burst budget (the PR-10 follow-up: DRR
        weights used to shape prefill admission only, so a saturating
        low-class decode fleet held full bursts while high-class prompts
        queued a whole burst behind each tick).

        When prompt work of a class STRICTLY higher-priority than every
        decoding slot is pending, shrink the burst to the decoding
        class's weighted share so the loop returns to admission sooner.
        Neutral (returns ``cap``) whenever nothing higher is waiting —
        single-class traffic and ``preempt=0`` (no scheduler at all)
        keep today's sizing bit-for-bit."""
        if dec_rank is None or cap <= 1:
            return cap
        best = None
        for r, n in enumerate(pending_by_class):
            if n > 0:
                best = r
                break
        if best is None or best >= dec_rank:
            return cap
        w = self.weights
        return max(1, cap * w[dec_rank] // max(1, w[dec_rank] + w[best]))

    # ---- queue ordering ------------------------------------------------

    def order_queued(self, entries: List[Tuple[str, float, Any]]) -> List[Any]:
        """Order queued items for admission.

        ``entries`` is ``[(priority, enqueue_monotonic, item), ...]``.
        Sort by aged effective rank, then arrival (stable FIFO within a
        class).  Returns the items, best first.
        """
        now = time.monotonic()
        ranked = []
        for pr, t_enq, item in entries:
            base = PRIORITY_RANK.get(pr, PRIORITY_RANK[DEFAULT_PRIORITY])
            rank = self.effective_rank(pr, now - t_enq)
            if rank < base:
                self.aged_promotions += 1
            ranked.append((rank, t_enq, item))
        ranked.sort(key=lambda e: (e[0], e[1]))
        return [item for _, _, item in ranked]

    # ---- shedding ------------------------------------------------------

    def pick_shed_victim(
        self, newcomer_rank: int, queued: List[Tuple[str, float, Any]]
    ) -> Optional[Any]:
        """Queue-wait-aware shedding: longest-queued of the lowest class.

        Only returns a victim whose class is STRICTLY lower than the
        newcomer's — same-class pressure still sheds the newcomer (keeps
        the PR-7 contract: a full queue of equals refuses the arrival).
        """
        worst = None
        worst_key = None
        for pr, t_enq, item in queued:
            rank = PRIORITY_RANK.get(pr, PRIORITY_RANK[DEFAULT_PRIORITY])
            if rank <= newcomer_rank:
                continue
            key = (rank, -t_enq)  # lowest class first, then longest-queued
            if worst_key is None or key > worst_key:
                worst_key = key
                worst = item
        return worst

    # ---- preemption ----------------------------------------------------

    def pick_victim(
        self, incoming_rank: int, active: List[Tuple[int, str, float, int]]
    ) -> Optional[int]:
        """Choose a slot to preempt for an incoming request of ``incoming_rank``.

        ``active`` is ``[(slot, priority, t_start, preempt_count), ...]``
        for slots the engine deems pausable.  Picks the lowest class
        strictly below the incoming rank, newest start first (oldest work
        has sunk the most cost), skipping slots already preempted
        ``max_preemptions`` times.  Returns the slot or None.
        """
        best = None
        best_key = None
        for slot, pr, t_start, n_pre in active:
            rank = PRIORITY_RANK.get(pr, PRIORITY_RANK[DEFAULT_PRIORITY])
            if rank <= incoming_rank:
                continue
            if n_pre >= self.max_preemptions:
                continue
            key = (rank, t_start)  # lowest class, then most recent start
            if best_key is None or key > best_key:
                best_key = key
                best = slot
        return best

    # ---- resume queue --------------------------------------------------

    def park(self, entry: ResumeEntry) -> None:
        self.preemptions += 1
        self._resume.append(entry)

    def adopt(self, entry: ResumeEntry) -> None:
        """Park a resume entry MIGRATED from a sibling replica (ISSUE
        14) — identical to ``park`` except the preemption happened (and
        was counted) on the source engine, so this one's counter must
        not move. List append is atomic under the GIL, so the pool may
        call this from its own thread while the engine loop pops."""
        self.adoptions += 1
        self._resume.append(entry)

    def remove_parked(self, request_id: str) -> Optional[ResumeEntry]:
        """Pop the parked entry for ``request_id`` (migration-out of a
        request that was paused, not active), or None."""
        for i, e in enumerate(self._resume):
            if getattr(e.req, "request_id", None) == request_id:
                return self._resume.pop(i)
        return None

    def drain_parked(self) -> List[ResumeEntry]:
        """Remove and return ALL parked entries (replica died: siblings
        adopt its whole resume queue)."""
        out, self._resume = self._resume, []
        return out

    def _best_resume_index(self) -> int:
        now = time.monotonic()
        best_i = 0
        best_key = None
        for i, e in enumerate(self._resume):
            key = (self.effective_rank(e.priority, now - e.t_parked), e.t_parked)
            if best_key is None or key < best_key:
                best_key = key
                best_i = i
        return best_i

    def peek_resume(self) -> Optional[ResumeEntry]:
        """Best parked request (aged rank, oldest park first), not removed."""
        if not self._resume:
            return None
        return self._resume[self._best_resume_index()]

    def pop_resume(self) -> Optional[ResumeEntry]:
        """Next parked request to restore: best aged rank, oldest park first."""
        if not self._resume:
            return None
        return self._resume.pop(self._best_resume_index())

    def requeue_front(self, entry: ResumeEntry) -> None:
        """Put a resume entry back (admission failed); keeps its park time."""
        self._resume.insert(0, entry)

    @property
    def resume_depth(self) -> int:
        return len(self._resume)

    def resume_priorities(self) -> List[str]:
        return [e.priority for e in self._resume]

    def stats(self) -> Dict[str, Any]:
        return {
            "preemptions": self.preemptions,
            "adoptions": self.adoptions,
            "resumes": self.resumes,
            "resume_reprefills": self.resume_reprefills,
            "resume_restore_rows": self.resume_restore_rows,
            "aged_promotions": self.aged_promotions,
            "resume_depth": len(self._resume),
            "weights": dict(zip(PRIORITY_CLASSES, self.weights)),
        }
