"""Host-RAM tier of the paged KV store: offloaded pages + disk persistence.

PR 2's PrefixPageCache retains released chains only while their pages are
resident in the DEVICE pool — under pool pressure `_reclaim_pages` evicts
LRU chains and the next turn of that conversation pays a full prefill
again. DejaVu (arXiv:2403.01876) shows KV state streams off-accelerator
and restores faster than recompute; PRESERVE (arXiv:2501.08192) shows the
restore cost hides entirely when issued ahead of the step that needs it.
This module is the second tier those papers describe, applied at page
granularity under the existing pool:

  * ENTRIES are keyed by the SAME chained block hash the device-tier
    store uses (kvcache.page_chain_hash, model+page-size scoped), so a
    chain lookup spans both tiers with one key sequence: device pages
    cover links [0, d), host entries continue [d, h). Eviction cascades
    subtrees (prefix_cache._remove_tree), so the device tier is always
    prefix-closed and the two tiers never interleave.
  * CONTENT is the page's raw device representation copied to pinned
    host numpy — int8 pages keep their quantized {q, scales} leaves,
    bf16 pages stay bf16 (ml_dtypes) — so a restore is a byte-exact
    upload, never a requantization.
  * The LRU now CASCADES device -> host -> gone: the engine offloads a
    chain as `_reclaim_pages` evicts it (the device->host handoff), and
    this store evicts its own entries LRU-first when `kv_host_pool_mb`
    is exceeded (the host->gone edge). Orphaned children cascade away
    exactly like the device tier — match() walks root-down.
  * PERSISTENCE: save() serializes the store to one .npz (the prompt-
    cache container format) with a version tag and the full page SCOPE
    (family + attention geometry + cache dtype + page size); load()
    ignores — never crashes on — a corrupted, truncated, mismatched-
    version or mismatched-scope file, so offloaded chains survive
    graceful restarts of the same model only.

Thread safety: the engine loop matches/takes entries while the sync
worker inserts freshly gathered pages — every public method locks.

SHARED MODE (ISSUE 14): one HostPageStore may back N engine replicas of
the same model (EnginePool). Each replica keeps its own device tier;
this store is the one tier they all restore from, so it additionally
tracks WHICH owners (replica ids, migration tokens) currently map each
chain key. Budget eviction never removes an entry — or an ancestor of
an entry, removal cascades down — that some owner still maps: a sibling
replica's device tier or an in-flight migration may be about to splice
it back. CRC-corrupt entries are still dropped regardless (bad bytes
must go; the mapper re-prefills). When every entry is protected the
byte budget degrades to best-effort rather than evicting mapped state.
"""

from __future__ import annotations

import logging
import os
import threading
import zlib

import numpy as np

from localai_tpu.services.faults import FAULTS

log = logging.getLogger(__name__)

STORE_VERSION = 1


def _to_savable(a: np.ndarray):
    """(.npy-safe array, dtype name): ml_dtypes bfloat16 is not a numpy
    wire dtype, so it rides as a same-shape uint16 view."""
    name = str(a.dtype)
    if name == "bfloat16":
        return a.view(np.uint16), name
    return a, name


def _from_savable(a: np.ndarray, name: str) -> np.ndarray:
    if name == "bfloat16":
        import ml_dtypes

        return a.view(ml_dtypes.bfloat16)
    return a.astype(name, copy=False) if str(a.dtype) != name else a


def _leaf_bytes(rows) -> int:
    if isinstance(rows, dict):
        return sum(int(v.nbytes) for v in rows.values())
    return int(rows.nbytes)


def _page_crc(k, v) -> int:
    """CRC32 over both pages' leaf bytes, in a stable leaf order.
    Host RAM holding gigabytes of KV state for hours is exactly where a
    flipped bit silently corrupts generations — a restore must be
    byte-exact or not happen at all (re-prefill is always correct)."""
    crc = 0
    for rows in (k, v):
        leaves = rows.values() if isinstance(rows, dict) else (rows,)
        for a in leaves:
            crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return crc


class _HostEntry:
    __slots__ = ("key", "parent", "depth", "tick", "k", "v", "nbytes",
                 "crc", "dk", "dv", "dcrc")

    def __init__(self, key: bytes, parent: bytes, depth: int, tick: int,
                 k, v, dk=None, dv=None):
        self.key = key
        self.parent = parent
        self.depth = depth
        self.tick = tick
        # one page of K / V rows in the device representation:
        # [L, page_size, KV, hd] arrays, or {"q", "s"} dicts when int8
        self.k = k
        self.v = v
        self.nbytes = _leaf_bytes(k) + _leaf_bytes(v)
        self.crc = _page_crc(k, v)
        # ISSUE 13: optional DRAFT-model planes for the same page id (the
        # paged draft KV shares the main page table). Independently
        # CRC'd: a corrupt draft plane decays losslessly to a target-only
        # entry (speculation re-warms from scratch) instead of forcing a
        # re-prefill of correct target state.
        self.dk = dk
        self.dv = dv
        self.dcrc = _page_crc(dk, dv) if dk is not None else 0
        if dk is not None:
            self.nbytes += _leaf_bytes(dk) + _leaf_bytes(dv)


class RestoreStager:
    """Double-buffered staging for restore uploads (ROADMAP PR-3
    follow-up, ISSUE 9 satellite): two alternating host buffer sets, so
    the batch an in-flight host->device scatter is still reading can
    never be refilled by the NEXT restore — the upload overlaps the tail
    prefill dispatch instead of re-allocating (or worse, clobbering) one
    shared buffer. Buffers are keyed by (name, shape, dtype) and reused
    across restores of the same batch shape, killing the per-restore
    np.stack/np.concatenate allocation churn."""

    def __init__(self):
        self._bufs: list[dict] = [{}, {}]
        self._flip = 0

    def begin(self) -> int:
        """Start a new restore batch; returns the parity to stage into
        (the OTHER set from the previous — possibly in-flight — batch)."""
        self._flip ^= 1
        return self._flip

    def stage(self, parity: int, name, shape, dtype) -> np.ndarray:
        """A reusable staging buffer of the given shape/dtype."""
        bufs = self._bufs[parity]
        key = (name, tuple(shape), np.dtype(dtype).str)
        a = bufs.get(key)
        if a is None:
            a = bufs[key] = np.empty(shape, dtype)
        return a

    def fill(self, parity: int, name, entries, get, batch: int):
        """Stage ``[get(e) for e in entries]`` along axis 1, zero-padding
        columns up to ``batch``; handles the {"q","s"} int8 page dicts."""
        first = get(entries[0])
        if isinstance(first, dict):
            return {leaf: self.fill(parity, (name, leaf), entries,
                                    lambda e, lf=leaf: get(e)[lf], batch)
                    for leaf in first}
        shape = first.shape[:1] + (batch,) + first.shape[1:]
        a = self.stage(parity, name, shape, first.dtype)
        a[:, 0] = first
        for i, e in enumerate(entries[1:], start=1):
            a[:, i] = get(e)
        if batch > len(entries):
            a[:, len(entries):] = 0
        return a


class PrefetchPipeline:
    """Decode-time prefetch-ahead bookkeeping (ISSUE 16).

    The engine's prefetch tick scans queued requests, predicts which
    host-tier chain links their admission will restore, and uploads
    those pages into DETACHED device pages (refs == 1, owned by this
    pipeline) AHEAD of the admission — so the restore that would
    otherwise sit synchronously on the admission path is already done
    (PRESERVE, arXiv:2501.08192). Admission claims the contiguous
    prefix of its continuation chain from here; whatever the prediction
    got wrong ages out and is reclaimed as WASTED.

    Engine-loop-thread only — no locking. The pipeline owns pure
    bookkeeping: the pool owns the pages (each registered page carries
    one detached reference that transfers on claim() or is unref'd by
    the caller on expiry), the HostPageStore owns the counters, and the
    auditor sees registered pages as caller-declared extras."""

    __slots__ = ("pages", "seen_rids", "tick", "max_age")

    def __init__(self, max_age: int = 64):
        # chain key -> [page, parent, depth, tick_registered]
        self.pages: dict[bytes, list] = {}
        # request ids a prefetch pass has already scanned: a SYNC
        # restore for one of these means the pipeline predicted the
        # need but lost the race — that's a PREFETCH_LATE, the metric
        # the CI gate holds at zero in steady state
        self.seen_rids: set = set()
        self.tick = 0
        self.max_age = int(max_age)

    def __len__(self) -> int:
        return len(self.pages)

    def register(self, key: bytes, parent: bytes, page: int, depth: int):
        """Track one restored detached page under its chain key."""
        self.pages[key] = [int(page), parent, int(depth), self.tick]

    def claim(self, key: bytes):
        """Take a prefetched page for admission — ownership of the
        detached reference transfers to the caller. None if the key was
        never prefetched (or already claimed/expired)."""
        return self.pages.pop(key, None)

    def expire(self) -> list:
        """Pop entries older than max_age ticks — the prediction missed
        (request cancelled upstream, prompt diverged, chain superseded).
        Returns [(key, [page, parent, depth, tick]), ...]; the caller
        unrefs each page and counts it WASTED."""
        cutoff = self.tick - self.max_age
        old = [k for k, rec in self.pages.items() if rec[3] < cutoff]
        return [(k, self.pages.pop(k)) for k in old]

    def drain(self) -> list:
        """Pop everything (pool-pressure raid, device reset)."""
        out = list(self.pages.items())
        self.pages.clear()
        self.seen_rids.clear()
        return out


class HostPageStore:
    """Byte-budgeted host-RAM index of offloaded pages."""

    def __init__(self, scope: bytes, page_size: int, budget_mb: int):
        self.scope = scope
        self.page_size = page_size
        self.budget_bytes = max(1, int(budget_mb)) << 20
        self._lock = threading.Lock()
        self._entries: dict[bytes, _HostEntry] = {}
        self._children: dict[bytes, set] = {}
        self._tick = 0
        self._bytes = 0
        # shared mode (ISSUE 14): chain key -> set of owner tokens that
        # still map it (replica ids from device-tier inserts, migration
        # pins). May name keys with no host entry yet — an owner can map
        # a key whose offload is still in flight through the sync worker.
        self._mapped: dict[bytes, set] = {}
        # telemetry (monotonic totals -> localai_kv_offload_*_total)
        self.offloaded_pages = 0
        self.offloaded_bytes = 0
        self.restored_pages = 0
        self.restores = 0        # admissions that restored from this tier
        self.hits = 0            # = restores (exported under _hits_total)
        self.misses = 0          # tier consulted, chain not present
        self.evicted_pages = 0   # host -> gone (budget eviction)
        self.corrupt_dropped = 0  # CRC mismatch at get(): tree dropped
        self.evict_blocked = 0   # budget evictions skipped: key mapped
        # prefetch-ahead pipeline (ISSUE 16): restores issued BEFORE the
        # admission/burst that needs them -> localai_kv_prefetch_*_total
        self.prefetch_issued = 0   # pages restored ahead of need
        self.prefetch_hits = 0     # prefetched pages claimed by admission
        self.prefetch_late = 0     # sync restores the pipeline predicted
        #                            but lost the race on
        self.prefetch_wasted = 0   # prefetched pages reclaimed unclaimed
        self.prefetch_inflight = 0  # restore batches in the sync worker
        # lifecycle ledger/auditor (ISSUE 15): attached by the owning
        # engine (owned store) or the EnginePool's SharedKV (shared
        # store); None = zero-cost no-op
        self.audit = None
        # federated peer tier (ISSUE 17): a kv_stream.FederatedKV
        # attached when clustering is armed. get() consults it on a
        # local miss (fetched entries are CRC-verified and inserted
        # HERE before the caller sees them) and contains_any() consults
        # peer membership. None = single-host: both hooks dissolve into
        # one `is not None` check, so cluster=off stays bit-for-bit.
        self.federated = None

    # ---------- introspection ----------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def pages(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "pages": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "offloaded_pages": self.offloaded_pages,
                "offloaded_bytes": self.offloaded_bytes,
                "restored_pages": self.restored_pages,
                "restores": self.restores,
                "hits": self.hits,
                "misses": self.misses,
                "evicted_pages": self.evicted_pages,
                "corrupt_dropped": self.corrupt_dropped,
                "mapped_keys": len(self._mapped),
                "evict_blocked": self.evict_blocked,
                "prefetch_issued": self.prefetch_issued,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_late": self.prefetch_late,
                "prefetch_wasted": self.prefetch_wasted,
                "prefetch_inflight": self.prefetch_inflight,
            }

    # ---------- shared-mode mapping refcounts (ISSUE 14) ----------

    def map_key(self, key: bytes, owner) -> None:
        """Record that ``owner`` (a replica id or migration token) maps
        this chain key: its device tier holds the page, or a migration
        is about to splice it on another replica. Mapped entries — and
        their ancestors, since removal cascades down — are exempt from
        budget eviction until the last owner unmaps."""
        with self._lock:
            self._mapped.setdefault(key, set()).add(owner)

    def unmap_key(self, key: bytes, owner) -> None:
        with self._lock:
            owners = self._mapped.get(key)
            if owners is not None:
                owners.discard(owner)
                if not owners:
                    del self._mapped[key]

    def unmap_owner(self, owner) -> int:
        """Drop every mapping held by ``owner`` (replica device-tier
        clear, replica death, migration pin release). Returns how many
        keys the owner was mapping."""
        n = 0
        with self._lock:
            for key in list(self._mapped):
                owners = self._mapped[key]
                if owner in owners:
                    owners.discard(owner)
                    n += 1
                    if not owners:
                        del self._mapped[key]
        return n

    def mapped_count(self, key: bytes) -> int:
        with self._lock:
            owners = self._mapped.get(key)
            return len(owners) if owners else 0

    def _protected_keys_locked(self) -> set:
        """Keys budget eviction must skip: every mapped key that has a
        host entry, plus all its ancestors (evicting an ancestor would
        cascade the mapped descendant away)."""
        protected: set = set()
        for key in self._mapped:
            k = key
            while k in self._entries and k not in protected:
                protected.add(k)
                k = self._entries[k].parent
        return protected

    # ---------- store operations ----------

    def put(self, key: bytes, parent: bytes, depth: int, k, v,
            dk=None, dv=None) -> bool:
        """Insert one offloaded page (device->host handoff). Duplicate
        keys are touched, not replaced — content is identical by hash
        construction (a later put MAY attach draft planes a draft-less
        entry is missing; the target content itself never changes).
        Evicts LRU-first past the byte budget."""
        with self._lock:
            self._tick += 1
            e = self._entries.get(key)
            if e is not None:
                e.tick = self._tick
                if dk is not None and e.dk is None:
                    e.dk, e.dv = dk, dv
                    e.dcrc = _page_crc(dk, dv)
                    extra = _leaf_bytes(dk) + _leaf_bytes(dv)
                    e.nbytes += extra
                    self._bytes += extra
                    self._evict_to_budget_locked()
                return False
            e = _HostEntry(key, parent, depth, self._tick, k, v, dk, dv)
            if e.nbytes > self.budget_bytes:
                return False     # a single page over budget: never admit
            self._entries[key] = e
            self._children.setdefault(parent, set()).add(key)
            self._bytes += e.nbytes
            self.offloaded_pages += 1
            self.offloaded_bytes += e.nbytes
            if self.audit is not None:
                self.audit.ledger.record("offload", key=key)
            self._evict_to_budget_locked()
            return True

    def get(self, key: bytes):
        """Entry for a chain key (LRU-touched), or None — the host half
        of the two-tier chain walk. On a local miss the federated peer
        tier (ISSUE 17) is consulted OUTSIDE the store lock: a peer's
        entry is fetched, CRC-verified and inserted locally, then read
        back through the normal local path — so every caller-visible
        entry passed the same integrity gate regardless of where it
        came from. Any transport failure is a plain miss (re-prefill)."""
        e = self.get_local(key)
        if e is not None:
            return e
        fed = self.federated
        if fed is not None and fed.fetch_into([key]):
            return self.get_local(key)
        return None

    def get_local(self, key: bytes):
        """The local half of get(): LRU-touched CRC-checked read of
        THIS store only — never the federated tier. The wire server
        serves peers through this accessor (a served fetch recursing
        into the peer tier would let two cold hosts chase each other's
        misses forever). The page CRC is verified on EVERY read: a
        corrupted entry (and its now-untrusted subtree) is dropped and
        reported as a miss, so the caller re-prefills and the
        generation stays byte-exact."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            if FAULTS.active and FAULTS.take("host_store_corrupt") is not None:
                leaf = next(iter(e.k.values())) if isinstance(e.k, dict) \
                    else e.k
                flat = np.ascontiguousarray(leaf).view(np.uint8).reshape(-1)
                flat[0] ^= 0xFF
                if isinstance(e.k, dict):
                    e.k[next(iter(e.k))] = flat.view(leaf.dtype).reshape(
                        leaf.shape)
                else:
                    e.k = flat.view(leaf.dtype).reshape(leaf.shape)
            if _page_crc(e.k, e.v) != e.crc:
                log.warning("kv host store: CRC mismatch on page depth=%d"
                            " — dropping subtree, forcing re-prefill",
                            e.depth)
                self._remove_tree_locked(key)
                self.corrupt_dropped += 1
                return None
            if e.dk is not None and _page_crc(e.dk, e.dv) != e.dcrc:
                # draft planes are an acceleration, not correctness:
                # decay the entry to target-only (lossless — speculation
                # just re-warms) instead of dropping the whole subtree
                log.warning("kv host store: draft CRC mismatch on page "
                            "depth=%d — dropping draft planes only",
                            e.depth)
                extra = _leaf_bytes(e.dk) + _leaf_bytes(e.dv)
                e.dk = e.dv = None
                e.dcrc = 0
                e.nbytes -= extra
                self._bytes -= extra
            self._tick += 1
            e.tick = self._tick
            return e

    def contains(self, key: bytes) -> bool:
        """LOCAL membership only — offload/pin/await logic must reason
        about THIS store's contents, never a peer's."""
        with self._lock:
            return key in self._entries

    def contains_any(self, key: bytes) -> bool:
        """Membership across the local store AND the federated peer
        tier — the cheap availability probe the admission walk and the
        prefetch scan use (no LRU touch, no CRC, no transfer). A
        contains_any()=True / get()=None race is already a handled
        path for every caller (identical to a local CRC drop between
        probe and read): availability shrinks and the walk re-selects
        or re-prefills."""
        if self.contains(key):
            return True
        fed = self.federated
        return fed is not None and fed.peer_has(key)

    def note_restore(self, n_pages: int):
        with self._lock:
            self.restores += 1
            self.hits += 1
            self.restored_pages += int(n_pages)
            if self.audit is not None:
                self.audit.ledger.record("restore")

    def note_miss(self):
        with self._lock:
            self.misses += 1

    # ---------- prefetch-ahead telemetry (ISSUE 16) ----------

    def note_prefetch_issued(self, n_pages: int):
        with self._lock:
            self.prefetch_issued += int(n_pages)
            self.prefetch_inflight += 1
            if self.audit is not None:
                self.audit.ledger.record("prefetch")

    def note_prefetch_done(self):
        """One prefetch restore batch retired from the sync worker."""
        with self._lock:
            self.prefetch_inflight = max(0, self.prefetch_inflight - 1)

    def note_prefetch_hit(self, n_pages: int):
        with self._lock:
            self.prefetch_hits += int(n_pages)

    def note_prefetch_late(self, n: int = 1):
        with self._lock:
            self.prefetch_late += int(n)

    def note_prefetch_wasted(self, n_pages: int):
        with self._lock:
            self.prefetch_wasted += int(n_pages)

    def _evict_to_budget_locked(self):
        if self._bytes <= self.budget_bytes:
            return
        protected = self._protected_keys_locked() if self._mapped else ()
        victims = sorted(self._entries.values(),
                         key=lambda e: (e.tick, -e.depth))
        for e in victims:
            if self._bytes <= self.budget_bytes:
                return
            if e.key not in self._entries:
                continue
            if e.key in protected:
                # a sibling replica (or in-flight migration) still maps
                # this entry or a descendant: never evict it under
                # budget pressure — the budget turns best-effort instead
                self.evict_blocked += 1
                continue
            self._remove_tree_locked(e.key)

    def _remove_tree_locked(self, key: bytes) -> int:
        """Remove an entry and every descendant (an orphaned child is
        unreachable — the chain walk is root-down). host -> gone."""
        n = 0
        stack = [key]
        while stack:
            k = stack.pop()
            e = self._entries.pop(k, None)
            if e is None:
                continue
            stack.extend(self._children.pop(k, ()))
            kids = self._children.get(e.parent)
            if kids is not None:
                kids.discard(k)
                if not kids:
                    del self._children[e.parent]
            self._bytes -= e.nbytes
            self.evicted_pages += 1
            if self.audit is not None:
                self.audit.ledger.record("host_evict", key=k)
            n += 1
        return n

    def audit_scan(self, sample_crc: int = 4, rng=None) -> list:
        """Invariant scan for the KV auditor (ISSUE 15). Families:

        * host_bytes — the running ``_bytes`` total matches the summed
          entry sizes, and each entry's recorded nbytes matches its
          plane shapes (no double counting across tiers: an entry is
          counted once, at its recorded size, device residency never
          touches ``_bytes``).
        * host_children — the parent->children map and the entries'
          parent links agree in both directions (a broken cascade would
          strand unreachable entries against the byte budget). Absent
          parents are legal: offload can land a child whose parent was
          evicted, and load() replays entries without requiring them.
        * host_crc — recompute the stored CRC of up to ``sample_crc``
          randomly sampled entries (bit-rot in retained host pages).
          Sibling-mapped chains are preferred in the sample since their
          corruption is the cross-replica hazard; eviction of a mapped
          chain itself is prevented structurally at the budget seam
          (``_protected_keys_locked``) and shows up here as a dangling
          map only while an offload is legitimately in flight, so it is
          not a hard violation.

        Dict violations ``{"check", "detail"}``; empty list = clean."""
        out = []
        with self._lock:
            total = 0
            for key, e in self._entries.items():
                nb = _leaf_bytes(e.k) + _leaf_bytes(e.v)
                if e.dk is not None:
                    nb += _leaf_bytes(e.dk) + _leaf_bytes(e.dv)
                if nb != e.nbytes:
                    out.append({"check": "host_bytes",
                                "detail": f"entry {key[:8].hex()} nbytes "
                                          f"{e.nbytes} != plane sum {nb}"})
                total += e.nbytes
                kids = self._children.get(e.parent)
                if kids is None or key not in kids:
                    out.append({"check": "host_children",
                                "detail": f"entry {key[:8].hex()} missing "
                                          f"from parent "
                                          f"{e.parent[:8].hex()} kid set"})
            if total != self._bytes:
                out.append({"check": "host_bytes",
                            "detail": f"byte accounting drift: running "
                                      f"{self._bytes} != summed {total} "
                                      f"over {len(self._entries)} entries"})
            for parent, kids in self._children.items():
                for c in kids:
                    e = self._entries.get(c)
                    if e is None:
                        out.append({"check": "host_children",
                                    "detail": f"kid set of "
                                              f"{parent[:8].hex()} names "
                                              f"absent entry "
                                              f"{c[:8].hex()}"})
                    elif e.parent != parent:
                        out.append({"check": "host_children",
                                    "detail": f"entry {c[:8].hex()} parent "
                                              f"link disagrees with kid "
                                              f"set of {parent[:8].hex()}"})
            ns = min(int(sample_crc), len(self._entries))
            if ns > 0:
                keys = [k for k in self._mapped if k in self._entries]
                rest = [k for k in self._entries if k not in self._mapped]
                if rng is not None and len(rest) > ns:
                    idx = rng.choice(len(rest), size=ns, replace=False)
                    rest = [rest[int(i)] for i in idx]
                for key in (keys + rest)[:ns]:
                    e = self._entries[key]
                    if _page_crc(e.k, e.v) != e.crc:
                        out.append({"check": "host_crc",
                                    "detail": f"retained entry "
                                              f"{key[:8].hex()} failed CRC "
                                              f"spot-check"})
                    elif e.dk is not None and _page_crc(e.dk, e.dv) != e.dcrc:
                        out.append({"check": "host_crc",
                                    "detail": f"draft planes of "
                                              f"{key[:8].hex()} failed CRC "
                                              f"spot-check"})
        return out

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._children.clear()
            self._mapped.clear()
            self._bytes = 0

    # ---------- disk persistence ----------

    def save(self, path: str) -> bool:
        """Serialize the store (atomically) for reload at the next engine
        start. Entries are written in LRU order so load() replays the
        recency ranking. Draft planes (ISSUE 13) are NOT persisted — the
        wire format stays target-only; a reloaded entry restores without
        them and speculation re-warms."""
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: e.tick)
        if not entries:
            # nothing retained: leave no stale file behind
            try:
                if os.path.exists(path):
                    os.remove(path)
            except OSError:
                pass
            return False
        quant = isinstance(entries[0].k, dict)
        payload = {
            "version": np.int32(STORE_VERSION),
            "scope": np.frombuffer(self.scope, np.uint8),
            "page_size": np.int32(self.page_size),
            "keys": np.stack([np.frombuffer(e.key, np.uint8)
                              for e in entries]),
            "parents": np.stack([np.frombuffer(e.parent, np.uint8)
                                 for e in entries]),
            "depths": np.asarray([e.depth for e in entries], np.int32),
            "quant": np.int32(1 if quant else 0),
        }
        if quant:
            payload["kq"] = np.stack([e.k["q"] for e in entries])
            payload["ks"] = np.stack([e.k["s"] for e in entries])
            payload["vq"] = np.stack([e.v["q"] for e in entries])
            payload["vs"] = np.stack([e.v["s"] for e in entries])
            payload["dtype"] = np.asarray("int8")
        else:
            karr, kname = _to_savable(np.stack([e.k for e in entries]))
            varr, _ = _to_savable(np.stack([e.v for e in entries]))
            payload["kd"] = karr
            payload["vd"] = varr
            payload["dtype"] = np.asarray(kname)
        try:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **payload)
            os.replace(tmp, path)
            return True
        except Exception:
            log.exception("kv host store save failed: %s", path)
            return False

    def load(self, path: str) -> int:
        """Reload a persisted store. Any defect — unreadable, truncated,
        wrong version, wrong scope (model/geometry/dtype/page size) —
        means the file is IGNORED, never crashed on. Returns the number
        of entries restored."""
        if not path or not os.path.exists(path):
            return 0
        try:
            data = np.load(path, allow_pickle=False)
            if int(data["version"]) != STORE_VERSION:
                log.warning("kv host store %s: version %s != %s, ignoring",
                            path, int(data["version"]), STORE_VERSION)
                return 0
            if (bytes(data["scope"].tobytes()) != self.scope
                    or int(data["page_size"]) != self.page_size):
                log.warning("kv host store %s: scope/page-size mismatch "
                            "(different model or layout), ignoring", path)
                return 0
            keys = data["keys"]
            parents = data["parents"]
            depths = data["depths"]
            quant = bool(int(data["quant"]))
            if quant:
                kq, ks, vq, vs = (data["kq"], data["ks"], data["vq"],
                                  data["vs"])
            else:
                name = str(data["dtype"])
                kd = _from_savable(data["kd"], name)
                vd = _from_savable(data["vd"], name)
            n = 0
            loaded_bytes = 0
            for i in range(keys.shape[0]):
                if quant:
                    k = {"q": kq[i], "s": ks[i]}
                    v = {"q": vq[i], "s": vs[i]}
                else:
                    k, v = kd[i], vd[i]
                if self.put(bytes(keys[i].tobytes()),
                            bytes(parents[i].tobytes()),
                            int(depths[i]), k, v):
                    n += 1
                    loaded_bytes += _leaf_bytes(k) + _leaf_bytes(v)
            # loaded pages were offloaded by a PREVIOUS process — don't
            # double-count them in this process's offload totals
            with self._lock:
                self.offloaded_pages = max(0, self.offloaded_pages - n)
                self.offloaded_bytes = max(
                    0, self.offloaded_bytes - loaded_bytes)
            return n
        except Exception:
            log.exception("kv host store %s unreadable, ignoring", path)
            return 0
