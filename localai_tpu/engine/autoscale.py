"""SLO-driven replica autoscaling policy (ISSUE 19).

Pure decision logic, no threads and no engine imports: the pool's
housekeeping thread gathers an :class:`~..services.sysobs.AutoscaleSignals`
snapshot on its normal cadence and feeds it to
:meth:`AutoscalePolicy.sample`, which returns either a new target
replica count or None. The pool owns the actuator
(``EnginePool.resize``); this module owns *when* and *why*.

Design rules the thresholds encode:

* **Scale out strictly before the shed.** The triggers — short-window
  SLO burn, queue fill fraction, page pressure with a backlog — are all
  leading indicators that fire while requests are still being admitted.
  ``queue_out_frac`` defaults to half of ``max_queued_requests``: by
  the time admission would return Retry-After (queue full), the scaler
  has already acted.
* **Never flap.** Two independent brakes: a same-direction *dwell*
  (one step, then wait for the new replica's effect to show in the
  signals) and an opposite-direction *cool-down* (a scale-in within
  ``cooldown_s`` of a scale-out is refused outright, and vice versa).
  Refused decisions are counted per direction in ``flaps_suppressed``
  — the bench gate ``AUTOSCALE_FLAPS=0`` pins that the *executed*
  sequence never reverses inside the cool-down window.
* **Every decision carries its evidence.** The signal snapshot that
  justified a decision is stored on the decision record and flight-
  recorded, so "why did we scale at 03:12" is answerable from the dump
  directory alone.

The clock is injectable so dwell/cool-down arithmetic is unit-testable
with hand-picked timestamps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..services import sysobs


class AutoscalePolicy:
    """Hysteretic scale-out/scale-in decision engine.

    ``sample(signals)`` returns the new target replica count when a
    change is warranted, else None. One step per decision (N -> N+1 or
    N -> N-1): a big enough backlog re-fires on the next sample after
    the dwell, which is self-pacing — each added replica gets a chance
    to move the signals before the next is paid for.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 burn_out: float = 1.0, burn_in: float = 0.05,
                 queue_out_frac: float = 0.5,
                 interval_s: float = 0.25,
                 dwell_s: float = 2.0, cooldown_s: float = 4.0,
                 idle_in_s: float = 1.5,
                 clock=time.monotonic, flight=None):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.burn_out = float(burn_out)
        self.burn_in = float(burn_in)
        self.queue_out_frac = float(queue_out_frac)
        self.interval_s = float(interval_s)
        self.dwell_s = float(dwell_s)
        self.cooldown_s = float(cooldown_s)
        self.idle_in_s = float(idle_in_s)
        self.clock = clock
        self.flight = flight

        self.decisions = {"out": 0, "in": 0}
        self.flaps_suppressed = {"out": 0, "in": 0}
        self.flaps = 0               # executed reversals inside cooldown
        self.last_decision: Optional[dict] = None
        self.log = deque(maxlen=16)  # recent decision records

        self._t_sample = -1e18
        self._last_change = (-1e18, "")   # (t, direction)
        self._idle_since: Optional[float] = None
        self._lock = threading.Lock()

    # -- decision core ---------------------------------------------------

    def sample(self, sig: "sysobs.AutoscaleSignals") -> Optional[int]:
        """Feed one signal snapshot; returns the new target replica
        count, or None for no change. Cheap when rate-limited — callers
        may invoke on every housekeeping tick."""
        now = self.clock()
        with self._lock:
            if now - self._t_sample < self.interval_s:
                return None
            self._t_sample = now

            n = max(1, int(sig.replicas))
            want_out, out_reason = self._want_out(sig, n)
            want_in, in_reason = self._want_in(sig, n, now)

            if want_out:
                return self._decide(now, "out", n, min(
                    self.max_replicas, n + 1), out_reason, sig)
            if want_in:
                return self._decide(now, "in", n, max(
                    self.min_replicas, n - 1), in_reason, sig)
            return None

    def _want_out(self, sig, n):
        if n >= self.max_replicas:
            return False, ""
        if sig.burn_5m >= self.burn_out:
            return True, f"slo_burn {sig.burn_5m:.2f} >= {self.burn_out}"
        if sig.queue_frac >= self.queue_out_frac:
            return True, (f"queue_frac {sig.queue_frac:.2f} >= "
                          f"{self.queue_out_frac}")
        if sig.free_page_frac < 0.0625 and sig.queued > 0:
            return True, (f"page_pressure free={sig.free_page_frac:.3f} "
                          f"queued={sig.queued}")
        return False, ""

    def _want_in(self, sig, n, now):
        idle = (sig.queued == 0 and sig.busy_frac < 0.5
                and sig.burn_5m <= self.burn_in)
        if not idle:
            self._idle_since = None
            return False, ""
        if self._idle_since is None:
            self._idle_since = now
        if n <= self.min_replicas:
            return False, ""
        held = now - self._idle_since
        if held < self.idle_in_s:
            return False, ""
        return True, (f"idle {held:.1f}s (busy={sig.busy_frac:.2f} "
                      f"burn={sig.burn_5m:.2f})")

    def _decide(self, now, direction, cur, tgt, reason, sig):
        if tgt == cur:
            return None
        t_last, d_last = self._last_change
        if d_last and d_last != direction and now - t_last < self.cooldown_s:
            self.flaps_suppressed[direction] += 1
            return None
        if d_last == direction and now - t_last < self.dwell_s:
            self.flaps_suppressed[direction] += 1
            return None
        if d_last and d_last != direction and now - t_last < self.cooldown_s:
            # Unreachable (the cooldown branch above returns) — kept as
            # a belt-and-braces counter the AUTOSCALE_FLAPS=0 gate pins.
            self.flaps += 1
        self._last_change = (now, direction)
        self._idle_since = None
        self.decisions[direction] += 1
        rec = {"t": round(now, 3), "direction": direction,
               "from": cur, "to": tgt, "reason": reason,
               "signals": sig.asdict()}
        self.last_decision = rec
        self.log.append(rec)
        if self.flight is not None:
            try:
                self.flight.dump("autoscale_" + direction, rec,
                                 tag="autoscale")
            except Exception:
                pass
        return tgt

    # -- introspection ---------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "decisions": dict(self.decisions),
                "flaps_suppressed": dict(self.flaps_suppressed),
                "flaps": self.flaps,
                "last_decision": dict(self.last_decision)
                if self.last_decision else None,
                "params": {
                    "min": self.min_replicas, "max": self.max_replicas,
                    "burn_out": self.burn_out, "burn_in": self.burn_in,
                    "queue_out_frac": self.queue_out_frac,
                    "dwell_s": self.dwell_s,
                    "cooldown_s": self.cooldown_s,
                    "idle_in_s": self.idle_in_s,
                },
            }
