"""Host-side page allocator for the paged KV cache (ops/kvcache.py).

The device holds the page POOL ([L, n_pages, page_size, KV, hd]) and a
snapshot of the page TABLE; this module owns the table's numpy mirror
plus everything the device cannot do: the free list, per-page REFERENCE
COUNTS, lazy allocation, and copy-on-write sharing decisions. The engine
commits the mirror to the device (kvcache.with_page_table) before each
dispatch that touches the cache — a ~KB upload, only when dirty.

Sharing model (the zero-copy prefix path):
  * share(src, dst, rows) points dst's leading table entries at src's
    FULL pages covering rows[0:rows] and bumps their refcounts — no KV
    rows move. Only full pages are ever shared, and only rows that are
    strictly read-only for the source (its committed prompt prefix), so
    the source never writes into a shared page.
  * The first page the NEW request writes (the one containing its first
    divergent row) is CLONED by the engine when its refcount is > 1
    (kvcache.clone_page) — classic copy-on-write; pages past it are
    allocated fresh.
  * release(slot, keep_rows) drops refcounts; a page returns to the
    free list when its last referent lets go. Freed slots RETAIN their
    prefix pages (keep_rows = committed tokens) so a later request with
    the same prefix reuses them in place — the paged analogue of the
    contiguous layout's cache_tokens retention.

Pool sizing: num_pages defaults to num_slots * max_context / page_size —
exactly the contiguous reservation, so the default config never uses
more HBM than before; sharing + lazy allocation make it use less.
Shrinking num_pages oversubscribes HBM against actual (not worst-case)
usage; the engine reclaims retained pages of free slots on pressure.

Page lifecycle (PR 2 cross-release prefix cache, PR 3 host offload):
every page moves through
    free -> active -> retained -> (reused | offloaded | free)
where OFFLOADED means the page's rows were copied to the host-RAM tier
(engine/kv_offload.py) as `_reclaim_pages` evicted its retention hold —
the device page itself returns to the free list, and a later prefix-
cache hit on the chain RESTORES the rows into freshly allocated device
pages (alloc_many below) spliced into the new slot's table.
"Active" means some slot's table references it; "retained" means its
ONLY references are holds placed by the engine's PrefixPageCache
(engine/prefix_cache.py) — the page's KV rows outlive the slot that
wrote them and can be spliced into a later request's table with zero
copies. hold()/drop() are the retention refcount half; the cache owns
the hash index and the LRU order, the pool owns the truth about which
pages are reclaimable. The free list is FIFO (oldest-freed page is
reallocated first), so a just-evicted page's rows survive as long as
the pool allows — cheap insurance for racing re-admissions.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from localai_tpu.services.kv_audit import KVLifecycleError


class PoolExhausted(RuntimeError):
    """No free page; the engine reclaims retained prefixes and retries."""


class PagePool:
    def __init__(self, num_slots: int, max_context: int, page_size: int,
                 num_pages: int = 0):
        if max_context % page_size:
            raise ValueError(
                f"max_context {max_context} not a multiple of page_size "
                f"{page_size}")
        self.page_size = page_size
        self.max_pages = max_context // page_size
        self.num_pages = num_pages or num_slots * self.max_pages
        self.num_slots = num_slots
        # sentinel num_pages = unallocated (drops scatters, zero-fills
        # gathers on device)
        self.ptab = np.full((num_slots, self.max_pages), self.num_pages,
                            np.int32)
        self.refs = np.zeros((self.num_pages,), np.int32)
        # references held by the prefix cache (subset of refs): a page
        # with refs == held > 0 is RETAINED — alive only for reuse
        self.held = np.zeros((self.num_pages,), np.int32)
        self.owned = np.zeros((num_slots,), np.int32)  # table entries in use
        self._free = deque(range(self.num_pages))
        self.dirty = True      # device table snapshot is stale
        # lifecycle ledger/auditor (ISSUE 15): a services.kv_audit
        # KVAuditor, attached by the engine when kv_audit != off. Every
        # hook below gates on one `is not None` check so the off mode
        # stays a zero-cost no-op on the hot path.
        self.audit = None

    def _fail(self, op: str, detail: str, page: int = -1, slot=None):
        """Structured lifecycle error (ISSUE 15 satellite): reports
        through the attached auditor, then raises — unconditionally, so
        the rule survives ``python -O`` (the bare asserts it replaces
        did not)."""
        err = KVLifecycleError(op, detail, page=page, slot=slot)
        if self.audit is not None:
            self.audit.lifecycle_violation(err)
        raise err

    # ---------- accounting ----------

    def pages_for(self, rows: int) -> int:
        return -(-int(rows) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def retained_pages(self) -> int:
        """Pages alive ONLY through prefix-cache holds (reclaimable by
        LRU eviction without touching any slot)."""
        return int(((self.refs > 0) & (self.refs == self.held)).sum())

    @property
    def active_pages(self) -> int:
        """Pages some slot table (or an in-flight detached clone) still
        references — NOT reclaimable."""
        return int(((self.refs > 0) & (self.refs > self.held)).sum())

    @property
    def oversubscription(self) -> float:
        """Worst-case logical demand over physical pages: > 1.0 means
        kv_pool_pages was shrunk below num_slots * max_context rows and
        admission relies on reclaim/eviction under full load."""
        return self.num_slots * self.max_pages / float(self.num_pages)

    def fragmentation(self) -> dict:
        """Free-list shape for the memory-watermark telemetry (ISSUE 8):
        how many free pages sit in HOLES below the allocated region vs
        in the contiguous free TAIL of page-id space. Pages are
        interchangeable (the table indirects every access) so holes cost
        nothing for correctness — but a hole-heavy free list means the
        pool has churned through its whole id space, which is the signal
        that retained-page eviction (not fresh allocation) is serving
        admissions."""
        free = len(self._free)
        if free == 0:
            return {"free_pages": 0, "tail_pages": 0, "hole_pages": 0,
                    "ratio": 0.0}
        free_ids = set(self._free)
        tail = 0
        for p in range(self.num_pages - 1, -1, -1):
            if p not in free_ids:
                break
            tail += 1
        holes = free - tail
        return {"free_pages": free, "tail_pages": tail, "hole_pages": holes,
                "ratio": round(holes / float(free), 4)}

    def slot_rows_capacity(self, slot: int) -> int:
        return int(self.owned[slot]) * self.page_size

    def page_refs(self, slot: int, page_idx: int) -> int:
        p = int(self.ptab[slot, page_idx])
        return int(self.refs[p]) if p < self.num_pages else 0

    # ---------- allocation ----------

    def _alloc(self) -> int:
        if not self._free:
            raise PoolExhausted(
                f"page pool exhausted ({self.num_pages} pages of "
                f"{self.page_size} rows)")
        p = self._free.popleft()
        self.refs[p] = 1
        if self.audit is not None:
            self.audit.ledger.record("alloc", page=p)
        return p

    def alloc_detached(self) -> int:
        """One page owned by nobody yet (copy-on-write clone target);
        hand it to replace() or free it via unref_detached()."""
        return self._alloc()

    def alloc_many(self, n: int) -> list:
        """Up to ``n`` detached pages (host-tier RESTORE allocation) —
        returns what the free list can give without raising, so a
        partial host-chain restore degrades to a shorter reuse instead
        of failing admission. Callers adopt() or unref_detached() each
        page."""
        out = []
        while len(out) < n and self._free:
            out.append(self._alloc())
        return out

    def unref_detached(self, page: int):
        if self.refs[page] <= 0:
            self._fail("free", "unref of an already-free page", page=page)
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free.append(page)
            if self.audit is not None:
                self.audit.ledger.record("free", page=page)

    def ensure(self, slot: int, rows: int) -> bool:
        """Allocate pages so the slot can hold ``rows`` logical rows
        (lazy, page granularity). Returns True if the table changed."""
        need = min(self.pages_for(rows), self.max_pages)
        changed = False
        while self.owned[slot] < need:
            self.ptab[slot, self.owned[slot]] = self._alloc()
            self.owned[slot] += 1
            changed = True
        if changed:
            self.dirty = True
        return changed

    def release(self, slot: int, keep_rows: int = 0):
        """Drop the slot's pages beyond those covering keep_rows."""
        keep = min(self.pages_for(keep_rows), self.max_pages)
        if self.audit is not None and self.owned[slot] > keep:
            self.audit.ledger.record("release", slot=slot)
        while self.owned[slot] > keep:
            self.owned[slot] -= 1
            i = int(self.owned[slot])
            self.unref_detached(int(self.ptab[slot, i]))
            self.ptab[slot, i] = self.num_pages
            self.dirty = True

    def demote(self, slot: int, start_idx: int, k: int) -> list:
        """Remove ``k`` table entries starting at ``start_idx`` from the
        MIDDLE of a live slot's table and compact the tail left — the
        snap-back window's cold-middle demotion (ISSUE 16). The caller
        must have already captured the pages' rows (host-tier offload
        gather dispatched BEFORE this call — program order protects the
        content, same discipline as _reclaim_pages) or be running an
        explicit drop/compression policy. Returns the removed page ids;
        each is unref'd here (freed if this table held the last ref).

        After the shift the slot's table is COMPACT again: owned[] still
        equals the table-entry count, so the PR-15 auditor's table
        invariants hold with no special casing — the engine re-bases the
        slot's row coordinates by ``k * page_size`` to match."""
        k = int(k)
        start_idx = int(start_idx)
        owned = int(self.owned[slot])
        if k <= 0:
            return []
        if start_idx < 0 or start_idx + k > owned:
            self._fail("demote",
                       f"demote() range [{start_idx}, {start_idx + k}) "
                       f"outside owned {owned}", slot=slot)
        removed = [int(self.ptab[slot, start_idx + i]) for i in range(k)]
        if self.audit is not None:
            self.audit.ledger.record("demote", page=removed[0], slot=slot)
        self.ptab[slot, start_idx:owned - k] = \
            self.ptab[slot, start_idx + k:owned]
        self.ptab[slot, owned - k:owned] = self.num_pages
        self.owned[slot] = owned - k
        self.dirty = True
        for p in removed:
            self.unref_detached(p)
        return removed

    # ---------- sharing / copy-on-write ----------

    def share(self, src: int, dst: int, rows: int) -> int:
        """Point dst's leading entries at src's full pages covering
        rows[0:rows]; refcounts bump, nothing is copied. dst must own no
        pages. Returns the rows actually shared (a page multiple)."""
        n = min(int(rows) // self.page_size, int(self.owned[src]))
        if self.owned[dst] != 0:
            self._fail("share", "share() into a non-empty slot",
                       slot=(src, dst))
        for i in range(n):
            p = int(self.ptab[src, i])
            self.ptab[dst, i] = p
            self.refs[p] += 1
        self.owned[dst] = n
        if n:
            self.dirty = True
            if self.audit is not None:
                self.audit.ledger.record(
                    "share", page=int(self.ptab[src, 0]), slot=(src, dst))
        return n * self.page_size

    def hold(self, page: int):
        """Prefix-cache retention reference: keeps the page (and its KV
        rows) alive after every slot table lets go. Must only be placed
        on a page that is currently referenced (refs > 0) — a free page
        has no content worth retaining."""
        if self.refs[page] <= 0:
            self._fail("hold", "hold() on an unreferenced page", page=page)
        self.refs[page] += 1
        self.held[page] += 1
        if self.audit is not None:
            self.audit.ledger.record("hold", page=page)

    def drop(self, page: int):
        """Release a hold() reference (cache eviction / entry dedup)."""
        if self.held[page] <= 0:
            self._fail("drop", "drop() without a matching hold()", page=page)
        self.held[page] -= 1
        if self.audit is not None:
            self.audit.ledger.record("drop", page=page)
        self.unref_detached(page)

    def splice(self, dst: int, pages) -> int:
        """Point dst's leading table entries at an explicit page list
        (the prefix cache's chain match) and bump refcounts — share()'s
        sibling for pages whose owning slot no longer exists. dst must
        own no pages. Returns the rows spliced (a page multiple)."""
        if self.owned[dst] != 0:
            self._fail("splice", "splice() into a non-empty slot", slot=dst)
        n = min(len(pages), self.max_pages)
        for i in range(n):
            p = int(pages[i])
            if self.refs[p] <= 0:
                self._fail("splice", "splice() of a freed page",
                           page=p, slot=dst)
            self.ptab[dst, i] = p
            self.refs[p] += 1
        self.owned[dst] = n
        if n:
            self.dirty = True
            if self.audit is not None:
                self.audit.ledger.record("splice", page=int(pages[0]),
                                         slot=dst)
        return n * self.page_size

    def adopt(self, slot: int, page: int):
        """Append a detached (freshly cloned) page to the slot's table —
        the commit half of a boundary-page clone."""
        i = int(self.owned[slot])
        if i >= self.max_pages:
            self._fail("adopt", "adopt() into a full table",
                       page=page, slot=slot)
        if self.refs[page] <= 0:
            self._fail("adopt", "adopt() of a freed page",
                       page=page, slot=slot)
        self.ptab[slot, i] = page
        self.owned[slot] = i + 1
        self.dirty = True
        if self.audit is not None:
            self.audit.ledger.record("adopt", page=page, slot=slot)

    def cow_page(self, slot: int, row: int) -> int:
        """Table index of the page containing ``row`` IF the slot owns it
        and it is shared (refcount > 1) — i.e. writing row requires a
        clone first. -1 otherwise."""
        i = int(row) // self.page_size
        if i < self.owned[slot] and self.page_refs(slot, i) > 1:
            return i
        return -1

    def replace(self, slot: int, page_idx: int, new_page: int):
        """Swap a (cloned) page into the slot's table (COW commit)."""
        old = int(self.ptab[slot, page_idx])
        self.ptab[slot, page_idx] = new_page
        if self.audit is not None:
            self.audit.ledger.record("clone", page=new_page, slot=slot)
        self.unref_detached(old)
        self.dirty = True
