"""Host-side page allocator for the paged KV cache (ops/kvcache.py).

The device holds the page POOL ([L, n_pages, page_size, KV, hd]) and a
snapshot of the page TABLE; this module owns the table's numpy mirror
plus everything the device cannot do: the free list, per-page REFERENCE
COUNTS, lazy allocation, and copy-on-write sharing decisions. The engine
commits the mirror to the device (kvcache.with_page_table) before each
dispatch that touches the cache — a ~KB upload, only when dirty.

Sharing model (the zero-copy prefix path):
  * share(src, dst, rows) points dst's leading table entries at src's
    FULL pages covering rows[0:rows] and bumps their refcounts — no KV
    rows move. Only full pages are ever shared, and only rows that are
    strictly read-only for the source (its committed prompt prefix), so
    the source never writes into a shared page.
  * The first page the NEW request writes (the one containing its first
    divergent row) is CLONED by the engine when its refcount is > 1
    (kvcache.clone_page) — classic copy-on-write; pages past it are
    allocated fresh.
  * release(slot, keep_rows) drops refcounts; a page returns to the
    free list when its last referent lets go. Freed slots RETAIN their
    prefix pages (keep_rows = committed tokens) so a later request with
    the same prefix reuses them in place — the paged analogue of the
    contiguous layout's cache_tokens retention.

Pool sizing: num_pages defaults to num_slots * max_context / page_size —
exactly the contiguous reservation, so the default config never uses
more HBM than before; sharing + lazy allocation make it use less.
Shrinking num_pages oversubscribes HBM against actual (not worst-case)
usage; the engine reclaims retained pages of free slots on pressure.
"""

from __future__ import annotations

import numpy as np


class PoolExhausted(RuntimeError):
    """No free page; the engine reclaims retained prefixes and retries."""


class PagePool:
    def __init__(self, num_slots: int, max_context: int, page_size: int,
                 num_pages: int = 0):
        if max_context % page_size:
            raise ValueError(
                f"max_context {max_context} not a multiple of page_size "
                f"{page_size}")
        self.page_size = page_size
        self.max_pages = max_context // page_size
        self.num_pages = num_pages or num_slots * self.max_pages
        self.num_slots = num_slots
        # sentinel num_pages = unallocated (drops scatters, zero-fills
        # gathers on device)
        self.ptab = np.full((num_slots, self.max_pages), self.num_pages,
                            np.int32)
        self.refs = np.zeros((self.num_pages,), np.int32)
        self.owned = np.zeros((num_slots,), np.int32)  # table entries in use
        self._free = list(range(self.num_pages - 1, -1, -1))
        self.dirty = True      # device table snapshot is stale

    # ---------- accounting ----------

    def pages_for(self, rows: int) -> int:
        return -(-int(rows) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def slot_rows_capacity(self, slot: int) -> int:
        return int(self.owned[slot]) * self.page_size

    def page_refs(self, slot: int, page_idx: int) -> int:
        p = int(self.ptab[slot, page_idx])
        return int(self.refs[p]) if p < self.num_pages else 0

    # ---------- allocation ----------

    def _alloc(self) -> int:
        if not self._free:
            raise PoolExhausted(
                f"page pool exhausted ({self.num_pages} pages of "
                f"{self.page_size} rows)")
        p = self._free.pop()
        self.refs[p] = 1
        return p

    def alloc_detached(self) -> int:
        """One page owned by nobody yet (copy-on-write clone target);
        hand it to replace() or free it via unref_detached()."""
        return self._alloc()

    def unref_detached(self, page: int):
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free.append(page)

    def ensure(self, slot: int, rows: int) -> bool:
        """Allocate pages so the slot can hold ``rows`` logical rows
        (lazy, page granularity). Returns True if the table changed."""
        need = min(self.pages_for(rows), self.max_pages)
        changed = False
        while self.owned[slot] < need:
            self.ptab[slot, self.owned[slot]] = self._alloc()
            self.owned[slot] += 1
            changed = True
        if changed:
            self.dirty = True
        return changed

    def release(self, slot: int, keep_rows: int = 0):
        """Drop the slot's pages beyond those covering keep_rows."""
        keep = min(self.pages_for(keep_rows), self.max_pages)
        while self.owned[slot] > keep:
            self.owned[slot] -= 1
            i = int(self.owned[slot])
            self.unref_detached(int(self.ptab[slot, i]))
            self.ptab[slot, i] = self.num_pages
            self.dirty = True

    # ---------- sharing / copy-on-write ----------

    def share(self, src: int, dst: int, rows: int) -> int:
        """Point dst's leading entries at src's full pages covering
        rows[0:rows]; refcounts bump, nothing is copied. dst must own no
        pages. Returns the rows actually shared (a page multiple)."""
        n = min(int(rows) // self.page_size, int(self.owned[src]))
        assert self.owned[dst] == 0, "share() into a non-empty slot"
        for i in range(n):
            p = int(self.ptab[src, i])
            self.ptab[dst, i] = p
            self.refs[p] += 1
        self.owned[dst] = n
        if n:
            self.dirty = True
        return n * self.page_size

    def adopt(self, slot: int, page: int):
        """Append a detached (freshly cloned) page to the slot's table —
        the commit half of a boundary-page clone."""
        i = int(self.owned[slot])
        assert i < self.max_pages
        self.ptab[slot, i] = page
        self.owned[slot] = i + 1
        self.dirty = True

    def cow_page(self, slot: int, row: int) -> int:
        """Table index of the page containing ``row`` IF the slot owns it
        and it is shared (refcount > 1) — i.e. writing row requires a
        clone first. -1 otherwise."""
        i = int(row) // self.page_size
        if i < self.owned[slot] and self.page_refs(slot, i) > 1:
            return i
        return -1

    def replace(self, slot: int, page_idx: int, new_page: int):
        """Swap a (cloned) page into the slot's table (COW commit)."""
        old = int(self.ptab[slot, page_idx])
        self.ptab[slot, page_idx] = new_page
        self.unref_detached(old)
        self.dirty = True
