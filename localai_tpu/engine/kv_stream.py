"""Federated KV tier: fetch/push chain entries from peer hosts' stores.

The client half of ISSUE 17's cross-host KV streaming transport
(services/kv_wire.py is the serving half). A ``FederatedKV`` sits
BEHIND a host's ``HostPageStore`` lookup — ``store.federated`` — so the
existing two-tier chain walk transparently grows a third tier:

    device pages -> local host store -> peer host stores -> re-prefill

A restore miss on the local tier consults peers before falling back to
re-prefill; whatever a peer ships is CRC-recomputed on arrival (exactly
like the persisted-store reload path) and inserted into the LOCAL store
first, so the engine's restore path reads only local, verified bytes.
Any transport failure — refused connect, severed stream, CRC reject,
scope mismatch — degrades to a plain miss: the caller re-prefills the
identical token history, byte-identical output, just slower (the PR-3
contract, now spanning hosts; DejaVu arXiv:2403.01876).

Peer health mirrors federation.py's Worker: a connect/stream failure
stamps ``failed_at`` and the peer sits out a cooldown window instead of
being hammered on every miss. Membership probes (``peer_has``) keep a
short-TTL negative cache so an admission walk over a long cold chain
costs one HAS round-trip per peer, not one per page.

``store.federated`` stays None unless clustering is armed, so
``cluster=off`` is bit-for-bit the single-host path — the store-level
hook dissolves into one ``is not None`` check.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Optional

from localai_tpu.services.kv_wire import (OP_DIGEST, OP_ERR, OP_FETCH,
                                          OP_HAS, OP_HELLO, OP_OK, OP_PUSH,
                                          OP_STATS, WIRE_VERSION, WireError,
                                          _jdump, _jload, pack_entries,
                                          recv_frame, send_frame,
                                          unpack_entries)

log = logging.getLogger(__name__)

# a failed peer sits out this long before being retried (default for
# the kv_stream_cooldown_ms knob — tune it together with the ISSUE-20
# failure-detector windows so the KV tier and the control plane agree
# on how long a flaky peer sits out)
PEER_COOLDOWN_S = 5.0
# negative membership answers are cached this long (admission probes of
# a cold chain must not ask the same peer the same question per page);
# default for the kv_stream_negcache_ms knob
NEG_TTL_S = 0.5


class KVStreamClient:
    """One framed, reconnecting connection to a peer's KVWireServer.

    Thread-safe: the engine loop, the sync worker, and the cluster
    router may all fetch concurrently; frames are request/response, so
    one lock serializes the socket. Reconnect + HELLO happen lazily on
    the next request after any failure."""

    def __init__(self, address: str, scope: bytes, page_size: int,
                 timeout_s: float = 5.0,
                 cooldown_s: float = PEER_COOLDOWN_S):
        host, _, port = address.rpartition(":")
        self.address = address
        self._addr = (host or "127.0.0.1", int(port))
        self.scope = scope
        self.page_size = int(page_size)
        self.timeout_s = float(timeout_s)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._sock = None
        self.failed_at = 0.0
        self.peer_host = -1

    def online(self, cooldown_s: Optional[float] = None) -> bool:
        cd = self.cooldown_s if cooldown_s is None else cooldown_s
        return (time.monotonic() - self.failed_at) > cd

    # ---- transport ----

    def _connect_locked(self):
        s = socket.create_connection(self._addr, timeout=self.timeout_s)
        s.settimeout(self.timeout_s)
        try:
            send_frame(s, OP_HELLO, _jdump(
                {"version": WIRE_VERSION, "scope": self.scope.hex(),
                 "page_size": self.page_size}))
            op, payload = recv_frame(s)
            if op != OP_OK:
                raise WireError(f"HELLO refused: {_jload(payload)}")
            self.peer_host = int(_jload(payload).get("host", -1))
        except Exception:
            s.close()
            raise
        self._sock = s

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def request(self, op: int, payload: bytes = b"") -> tuple:
        """One round-trip; raises WireError/OSError on failure (the
        socket is dropped — the next call reconnects + re-HELLOs)."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect_locked()
                send_frame(self._sock, op, payload)
                rop, rpayload = recv_frame(self._sock)
            except (OSError, WireError):
                self._close_locked()
                self.failed_at = time.monotonic()
                raise
            if rop == OP_ERR:
                raise WireError(str(_jload(rpayload).get("error", "?")))
            self.failed_at = 0.0
            return rop, rpayload

    def close(self):
        with self._lock:
            self._close_locked()

    # ---- convenience ops ----

    def has(self, keys: list) -> list:
        _, payload = self.request(OP_HAS, _jdump(
            {"keys": [k.hex() for k in keys]}))
        return [bool(b) for b in _jload(payload)["has"]]

    def fetch(self, keys: list) -> bytes:
        """Raw entry payload for ``keys`` (b"" = nothing present)."""
        _, payload = self.request(OP_FETCH, _jdump(
            {"keys": [k.hex() for k in keys]}))
        return payload

    def push(self, body: bytes) -> dict:
        _, payload = self.request(OP_PUSH, body)
        return _jload(payload)

    def digest(self) -> dict:
        _, payload = self.request(OP_DIGEST)
        return _jload(payload)

    def stats(self) -> dict:
        _, payload = self.request(OP_STATS)
        return _jload(payload)


class FederatedKV:
    """The peer tier behind one HostPageStore's lookup.

    Attach with ``attach()``; from then on ``store.get`` consults
    ``fetch_into`` on a local miss and ``store.contains_any`` consults
    ``peer_has``. Entries always land in the LOCAL store before the
    caller sees them — the wire tier fills the local tier, it never
    substitutes for it — so every engine read stays a local, CRC-checked
    ``get_local``.

    Conservation (ISSUE 15, lifted cluster-wide): an entry in flight on
    the wire is a DECLARED EXTRA, never a leak — ``inflight`` counts
    outstanding fetch/push round-trips and must read zero once the
    cluster is quiesced (ClusterRouter.kv_audit_sweep enforces it)."""

    def __init__(self, store, peers: list,
                 neg_ttl_s: float = NEG_TTL_S):
        self.store = store
        self.peers = list(peers)
        self.neg_ttl_s = float(neg_ttl_s)
        self._lock = threading.Lock()
        self._neg: dict = {}         # key -> monotonic stamp of last miss
        self.inflight = 0
        # telemetry -> localai_kv_stream_{pages,bytes,fetches,hits,
        # misses}_total (+ pushes/corrupt for /debug/kv)
        self.fetches = 0             # fetch round-trips issued
        self.hits = 0                # fetch round-trips that landed pages
        self.misses = 0              # round-trips that landed nothing
        self.pages = 0               # entries admitted from peers
        self.bytes = 0               # payload bytes received
        self.pushes = 0              # push round-trips issued
        self.pushed_pages = 0        # entries shipped by push
        self.corrupt_rejected = 0    # CRC-rejected on arrival
        self.has_queries = 0

    def attach(self):
        self.store.federated = self
        return self

    def detach(self):
        if self.store.federated is self:
            self.store.federated = None

    def close(self):
        self.detach()
        for p in self.peers:
            p.close()

    # ---- membership ----

    def peer_has(self, key: bytes) -> bool:
        """Does ANY online peer hold this chain key? Negative answers
        are cached for ``neg_ttl_s``; positives are not cached at all —
        the follow-up get() lands the entry locally, which IS the
        cache."""
        now = time.monotonic()
        with self._lock:
            t = self._neg.get(key)
            if t is not None and now - t < self.neg_ttl_s:
                return False
            self.has_queries += 1
        for p in self.peers:
            if not p.online():
                continue
            try:
                if p.has([key])[0]:
                    return True
            except (OSError, WireError):
                continue
        with self._lock:
            self._neg[key] = now
            if len(self._neg) > 65536:
                self._neg.clear()
        return False

    # ---- fetch ----

    def _admit(self, payload: bytes) -> int:
        """CRC-verify and insert a fetched payload into the local store.
        Returns entries admitted; rejects ride the corrupt counter and
        degrade to a miss (the caller re-prefills — always correct)."""
        from localai_tpu.engine.kv_offload import _page_crc

        store = self.store
        ents = unpack_entries(payload, store.scope, store.page_size)
        n = 0
        for ent in ents:
            if _page_crc(ent["k"], ent["v"]) != ent["crc"]:
                with self._lock:
                    self.corrupt_rejected += 1
                log.warning("kv stream: CRC reject on fetched page "
                            "depth=%d — degrading to re-prefill",
                            ent["depth"])
                continue
            dk, dv = ent["dk"], ent["dv"]
            if dk is not None and _page_crc(dk, dv) != ent["dcrc"]:
                dk = dv = None   # draft planes decay, target survives
            store.put(ent["key"], ent["parent"], ent["depth"],
                      ent["k"], ent["v"], dk=dk, dv=dv)
            if store.audit is not None:
                store.audit.ledger.record("stream_in", key=ent["key"])
            n += 1
        return n

    def fetch_into(self, keys: list) -> int:
        """Fetch ``keys`` from the first online peer that has them and
        insert into the local store. Returns entries admitted. Every
        failure mode (dead peer, severed stream, CRC reject) returns 0
        for the missing keys — a plain miss."""
        want = [k for k in keys if not self.store.contains(k)]
        if not want:
            return 0
        with self._lock:
            self.inflight += 1
            self.fetches += 1
        admitted = 0
        try:
            for p in self.peers:
                if not p.online():
                    continue
                try:
                    payload = p.fetch(want)
                except (OSError, WireError) as e:
                    log.warning("kv stream: fetch from %s failed: %s",
                                p.address, e)
                    continue
                if not payload:
                    continue
                try:
                    n = self._admit(payload)
                except WireError as e:
                    log.warning("kv stream: bad payload from %s: %s",
                                p.address, e)
                    continue
                admitted += n
                with self._lock:
                    self.pages += n
                    self.bytes += len(payload)
                if admitted:
                    break        # one peer served the chain: done
        finally:
            with self._lock:
                self.inflight -= 1
                if admitted:
                    self.hits += 1
                    for k in want:
                        self._neg.pop(k, None)
                else:
                    self.misses += 1
        return admitted

    def prefetch(self, keys: list) -> int:
        """Batch-fetch a whole chain ahead of an admission (disagg
        handoff, crash re-adoption) — one FETCH round-trip for every
        key not already local."""
        return self.fetch_into(list(keys))

    # ---- push ----

    def push_to(self, peer: "KVStreamClient", keys: list) -> int:
        """Ship local entries for ``keys`` to one peer (disagg chain
        retirement / proactive replication). Returns entries the peer
        accepted; 0 on any failure (the puller-side federated tier
        still covers the chain, so push is an optimization, never a
        correctness dependency)."""
        store = self.store
        ents = []
        for k in keys:
            e = store.get_local(k)
            if e is None:
                break            # chains are root-down: stop at a hole
            ents.append(e)
        if not ents:
            return 0
        body = pack_entries(store.scope, store.page_size, ents)
        with self._lock:
            self.inflight += 1
        try:
            r = peer.push(body)
        except (OSError, WireError) as e:
            log.warning("kv stream: push to %s failed: %s",
                        peer.address, e)
            return 0
        finally:
            with self._lock:
                self.inflight -= 1
        n = int(r.get("accepted", 0))
        with self._lock:
            self.pushes += 1
            self.pushed_pages += n
            self.bytes += len(body)
        if store.audit is not None:
            for e in ents[:n]:
                store.audit.ledger.record("stream_out", key=e.key)
        return n

    # ---- observability ----

    def stats(self) -> dict:
        with self._lock:
            return {
                "peers": len(self.peers),
                "peers_online": sum(1 for p in self.peers if p.online()),
                "inflight": self.inflight,
                "fetches": self.fetches,
                "hits": self.hits,
                "misses": self.misses,
                "pages": self.pages,
                "bytes": self.bytes,
                "pushes": self.pushes,
                "pushed_pages": self.pushed_pages,
                "corrupt_rejected": self.corrupt_rejected,
                "has_queries": self.has_queries,
            }
