"""GPTQ / AWQ quantized-safetensors ingestion.

Covers the reference's autogptq + exllama2 backends
(/root/reference/backend/python/autogptq/backend.py:1-152,
exllama2/backend.py:1-138 — thin wrappers that hand a GPTQ-format
checkpoint to a CUDA dequant kernel). The TPU-native equivalent: unpack
the 4/8-bit packed linears host-side, then stream them through the SAME
cast/quantize/shard path every other checkpoint takes
(engine/weights.py) — by default re-quantized to the framework's
weight-only per-out-channel int8 {q, s} form (ops/quant.py), so a
"quantized checkpoint" keeps its memory intent on the chip while the MXU
consumes dequantized bf16 tiles.

Formats (conventions stated explicitly, since they are load-bearing):
- **GPTQ** (AutoGPTQ v1 / HF ``quant_method: "gptq"``):
  ``qweight`` int32 [in/pack, out] packed along the INPUT axis, value k
  of each int32 at bit offset k*bits; ``qzeros`` int32 [groups,
  out/pack] packed along the OUTPUT axis; ``scales`` f16 [groups, out];
  optional ``g_idx`` int32 [in] (act-order / desc_act). Dequant:
  ``W[i,o] = scales[g(i),o] * (wq[i,o] - (zeros[g(i),o] + 1))`` — the
  v1 "+1" zero-point offset.
- **AWQ** (AutoAWQ / HF ``quant_method: "awq"``): ``qweight`` int32
  [in, out/pack] packed along the OUTPUT axis with the interleaved
  column order [0, 2, 4, 6, 1, 3, 5, 7] per int32; ``qzeros``
  [groups, out/pack] same order; ``scales`` f16 [groups, out]; no +1
  offset, no g_idx (always sequential groups).

pack = 32 // bits; bits in {2, 4, 8} (3-bit does not divide 32 and is
rejected loudly).
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

import numpy as np

_AWQ_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)


class QuantMeta:
    def __init__(self, method: str, bits: int, group_size: int,
                 desc_act: bool = False, sym: bool = False):
        if bits not in (2, 4, 8):
            raise ValueError(
                f"{method} bits={bits} unsupported (must divide 32: 2/4/8)")
        self.method = method
        self.bits = bits
        self.group_size = group_size
        self.desc_act = desc_act
        self.sym = sym

    def __repr__(self):
        return (f"QuantMeta({self.method}, bits={self.bits}, "
                f"group_size={self.group_size}, desc_act={self.desc_act})")


def detect(model_dir: str) -> Optional[QuantMeta]:
    """QuantMeta if the checkpoint dir is GPTQ/AWQ-quantized, else None.

    Looks at ``quantize_config.json`` (AutoGPTQ) then
    ``config.json:quantization_config`` (HF transformers)."""
    qc = os.path.join(model_dir, "quantize_config.json")
    d = None
    method = "gptq"
    if os.path.isfile(qc):
        with open(qc) as f:
            d = json.load(f)
        method = (d.get("quant_method") or d.get("checkpoint_format")
                  or "gptq").lower()
    else:
        cfgp = os.path.join(model_dir, "config.json")
        if os.path.isfile(cfgp):
            with open(cfgp) as f:
                d = json.load(f).get("quantization_config")
            if d is not None:
                method = (d.get("quant_method") or "gptq").lower()
    if d is None:
        return None
    if method not in ("gptq", "awq"):
        raise ValueError(f"unsupported quant_method {method!r} "
                         "(gptq/awq are ingestible)")
    return QuantMeta(
        method, int(d.get("bits", 4)), int(d.get("group_size", 128)),
        bool(d.get("desc_act", False)), bool(d.get("sym", False)))


def _unpack_rows(packed: np.ndarray, bits: int) -> np.ndarray:
    """int32 [R, C] -> uint8/16 [R*pack, C]: value k of each int32 sits
    at bit offset k*bits and expands DOWN the row axis."""
    pack = 32 // bits
    shifts = (np.arange(pack, dtype=np.uint32) * bits)[None, :, None]
    vals = (packed.astype(np.uint32)[:, None, :] >> shifts) & ((1 << bits) - 1)
    return vals.reshape(packed.shape[0] * pack, packed.shape[1])


def _unpack_cols(packed: np.ndarray, bits: int) -> np.ndarray:
    """int32 [R, C] -> [R, C*pack]: value k expands ALONG the column axis."""
    pack = 32 // bits
    shifts = (np.arange(pack, dtype=np.uint32) * bits)[None, None, :]
    vals = (packed.astype(np.uint32)[:, :, None] >> shifts) & ((1 << bits) - 1)
    return vals.reshape(packed.shape[0], packed.shape[1] * pack)


def _awq_deinterleave(cols: np.ndarray, bits: int) -> np.ndarray:
    """Undo AWQ's per-int32 column interleave: unpacked position k within
    each block of ``pack`` columns holds logical column _AWQ_ORDER[k]."""
    pack = 32 // bits
    if pack != 8:
        return cols  # the interleave is defined for 4-bit (pack=8) only
    C = cols.shape[1]
    idx = np.arange(C)
    inv = np.empty(8, np.int64)
    for k, col in enumerate(_AWQ_ORDER):
        inv[col] = k
    src = (idx // 8) * 8 + inv[idx % 8]
    return cols[:, src]


def dequant_linear(get: Callable[[str], np.ndarray], prefix: str,
                   meta: QuantMeta) -> np.ndarray:
    """Dequantize one quantized Linear to dense f32 **[in, out]** (the
    transposed-for-matmul orientation the stacked pytree wants).

    ``get(name)`` fetches raw tensors; ``prefix`` is the module path
    (e.g. "model.layers.3.self_attn.q_proj")."""
    qweight = get(prefix + ".qweight")
    qzeros = get(prefix + ".qzeros")
    scales = np.asarray(get(prefix + ".scales"), np.float32)  # [G, out]
    if meta.method == "awq":
        wq = _awq_deinterleave(_unpack_cols(qweight, meta.bits), meta.bits)
        zeros = _awq_deinterleave(_unpack_cols(qzeros, meta.bits), meta.bits)
        zeros = zeros.astype(np.float32)
    else:
        wq = _unpack_rows(qweight, meta.bits)                 # [in, out]
        zeros = _unpack_cols(qzeros, meta.bits).astype(np.float32) + 1.0
    I, O = wq.shape
    G = scales.shape[0]
    if meta.method == "gptq" and meta.desc_act:
        g_idx = np.asarray(get(prefix + ".g_idx"), np.int64)  # [in]
    else:
        gs = meta.group_size if meta.group_size > 0 else I
        g_idx = np.minimum(np.arange(I) // gs, G - 1)
    return scales[g_idx] * (wq.astype(np.float32) - zeros[g_idx])


def has_quant_linear(names, prefix: str) -> bool:
    return (prefix + ".qweight") in names
