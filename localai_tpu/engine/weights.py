"""HF safetensors checkpoint -> stacked JAX param pytree.

Replaces the reference's GGUF weight pipeline (llama.cpp model loading +
core/config/guesser.go GGUF header parsing) with the TPU-native flow:
HF safetensors shards are memory-mapped, per-layer tensors are stacked on
a leading layer axis (for the scan-over-layers forward), cast to bf16, and
placed shard-by-shard onto the device mesh so peak host memory stays at
one tensor, not one model.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("localai_tpu.weights")

try:
    from safetensors import safe_open
except ImportError:  # pragma: no cover
    safe_open = None


def _open_shards(model_dir: str):
    """Yield (name -> shard accessor) across all safetensors files."""
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    handles = {f: safe_open(f, framework="np") for f in files}
    name_to_file = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        for name, fname in index["weight_map"].items():
            name_to_file[name] = handles[os.path.join(model_dir, fname)]
    else:
        for f, h in handles.items():
            for name in h.keys():
                name_to_file[name] = h
    return name_to_file


def find_gguf(model_dir: str) -> Optional[str]:
    """Path to the GGUF file a model dir/path refers to, if any: either the
    path itself or the single *.gguf inside a directory with no safetensors
    (the shape an ``ollama://`` / gallery pull produces)."""
    if model_dir.endswith(".gguf") and os.path.isfile(model_dir):
        return model_dir
    if os.path.isdir(model_dir):
        ggufs = sorted(glob.glob(os.path.join(model_dir, "*.gguf")))
        sts = glob.glob(os.path.join(model_dir, "*.safetensors"))
        if len(ggufs) == 1 and not sts:
            return ggufs[0]
    return None


_QUANT_NAMES = {"embed", "lm_head", "wq", "wk", "wv", "wo",
                "w_gate", "w_up", "w_down"}


def _make_put(cfg, mesh, dtype, quantize, adapter=None, pace=None):
    """Leaf placer: host array + pytree path -> (LoRA-merged) cast /
    int8/int4-quantized / mesh-sharded device leaf. ``pace`` (streaming
    loads, ISSUE 19) is called with each host leaf before placement —
    the accounting/chaos/yield seam of ``stream_llama_params``."""

    def leaf_spec(spec_path: tuple):
        from localai_tpu.parallel import sharding as shardlib

        node = shardlib.llama_param_specs(cfg.tie_word_embeddings)
        for k in spec_path:
            node = node[k]
        return node

    def put(arr: np.ndarray, spec_path: tuple):
        if pace is not None:
            pace(arr)
        leaf_name = spec_path[-1]
        if adapter is not None and spec_path[0] == "layers" \
                and adapter.targets_leaf(leaf_name, cfg.num_layers):
            # merge W += scale*(B@A) BEFORE cast/quantization (reference:
            # LoraAdapter applied at load, grpc-server.cpp:2295-2309);
            # in-place per layer — no full-leaf delta buffer
            arr = np.array(arr, np.float32)  # always a fresh writable copy
            adapter.apply_to_leaf(leaf_name, cfg.num_layers, arr)
        if quantize in ("int8", "int4") and leaf_name in _QUANT_NAMES:
            from localai_tpu.ops.quant import (quantize_weight,
                                               quantize_weight_int4)

            # int4 applies to the layer matmuls only; embed/lm_head stay
            # int8 (see models/llama.py quantize_params for why)
            if quantize == "int4" and spec_path[0] == "layers":
                # the group count must divide the tp degree on the
                # contraction axis or the scale can't shard with its
                # weight (e.g. llama-2's 11008 FFN: 86 groups vs tp=8)
                divisor = 1
                if mesh is not None:
                    from localai_tpu.parallel.sharding import fit_spec

                    axis = fit_spec(mesh, arr.shape,
                                    leaf_spec(spec_path))[-2]
                    if axis is not None:
                        divisor = mesh.shape[axis]
                leaf = quantize_weight_int4(arr, shard_divisor=divisor)
            else:
                leaf = quantize_weight(arr)
        else:
            leaf = jnp.asarray(arr, dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from localai_tpu.ops.quant import scale_spec
            from localai_tpu.parallel.sharding import fit_spec

            node = fit_spec(
                mesh, (leaf["q"] if isinstance(leaf, dict) else leaf).shape,
                leaf_spec(spec_path))
            if isinstance(leaf, dict):
                q = jax.device_put(leaf["q"], NamedSharding(mesh, node))
                s = jax.device_put(leaf["s"], NamedSharding(
                    mesh, scale_spec(leaf, node)))
                return {"q": q, "s": s}
            return jax.device_put(leaf, NamedSharding(mesh, node))
        return leaf

    return put


def _assemble(source, put) -> dict:
    """Fold a (spec_path, host array) stream into the stacked pytree,
    placing each leaf as it arrives and freeing the host copy — peak
    host memory is one stacked leaf, not the dense model."""
    params: dict = {"layers": {}}
    for spec_path, arr in source:
        node = params
        for k in spec_path[:-1]:
            node = node[k]
        node[spec_path[-1]] = put(arr, spec_path)
        del arr
    return params


def _host_leaf_source(model_dir: str, cfg, quantize: str = ""):
    """-> (iterator of (spec_path, host np array), effective_quantize).

    The single checkpoint-format front door: GGUF (dequantized host-side
    by engine/gguf.py), HF safetensors (+GPTQ/AWQ detection, which may
    upgrade ``quantize`` — hence it is returned), or the RANDOM-weights
    bench gate. Iteration is leaf-at-a-time in every case; both
    load_llama_params and the ISSUE-19 streaming loader/prefetcher
    consume this."""
    gguf_path = find_gguf(model_dir)
    if gguf_path is not None:
        from localai_tpu.engine import gguf as gguflib

        g = gguflib.open_gguf(gguf_path)
        return gguflib.iter_llama_tensors(g, cfg), quantize
    try:
        tensors = _open_shards(model_dir)
    except FileNotFoundError:
        if os.environ.get("LOCALAI_ALLOW_RANDOM_WEIGHTS") == "1":
            # BENCH/TEST ONLY: a config.json-only dir serves random weights
            # through the same cast/quantize/shard path — lets the full
            # serving stack run benchmark-shaped models (e.g. 8B int8 on
            # one chip) without writing a multi-GB checkpoint to disk.
            # Gated: silently serving garbage from an incomplete real
            # checkpoint would be far worse than this convenience.
            return _iter_random_leaves(cfg), quantize
        raise

    def get(name: str) -> np.ndarray:
        h = tensors[name]
        return h.get_tensor(name)

    from localai_tpu.engine import gptq as gptqlib

    qmeta = gptqlib.detect(model_dir)
    if qmeta is not None and not quantize:
        # a GPTQ/AWQ checkpoint carries a memory intent; default to the
        # TPU-native weight-only int8 so loading it doesn't silently
        # inflate to dense bf16 (set quantization explicitly to override)
        quantize = "int8"

    L = cfg.num_layers

    def linear_T(name: str) -> np.ndarray:
        """Linear weight as [in, out]; GPTQ/AWQ-packed modules are
        dequantized host-side (engine/gptq.py) in that orientation."""
        base = name[: -len(".weight")]
        if qmeta is not None and base + ".qweight" in tensors:
            return gptqlib.dequant_linear(get, base, qmeta)
        return get(name).T

    def stack(fmt: str, transpose: bool = False) -> np.ndarray:
        mats = []
        for i in range(L):
            name = fmt.format(i=i)
            mats.append(linear_T(name) if transpose else get(name))
        return np.stack(mats)

    def gen():
        p = "model.layers.{i}."
        yield ("embed",), get("model.embed_tokens.weight")
        yield ("layers", "attn_norm"), stack(p + "input_layernorm.weight")
        yield ("layers", "wq"), stack(p + "self_attn.q_proj.weight", transpose=True)
        yield ("layers", "wk"), stack(p + "self_attn.k_proj.weight", transpose=True)
        yield ("layers", "wv"), stack(p + "self_attn.v_proj.weight", transpose=True)
        yield ("layers", "wo"), stack(p + "self_attn.o_proj.weight", transpose=True)
        yield ("layers", "mlp_norm"), stack(p + "post_attention_layernorm.weight")
        yield ("layers", "w_gate"), stack(p + "mlp.gate_proj.weight", transpose=True)
        yield ("layers", "w_up"), stack(p + "mlp.up_proj.weight", transpose=True)
        yield ("layers", "w_down"), stack(p + "mlp.down_proj.weight", transpose=True)
        yield ("final_norm",), get("model.norm.weight")
        if not cfg.tie_word_embeddings:
            yield ("lm_head",), linear_T("lm_head.weight")

    return gen(), quantize


def load_llama_params(
    model_dir: str,
    cfg,
    mesh=None,
    dtype=jnp.bfloat16,
    quantize: str = "",
    lora_adapter: str = "",
    lora_scale: float = 1.0,
) -> dict:
    """Load HF llama/mistral/qwen2-style weights into the stacked pytree.

    When ``mesh`` is given, each leaf is placed with the tensor-parallel
    sharding from parallel/sharding.py as it is assembled. quantize="int8"
    converts matmul weights to weight-only per-channel int8 at load time
    (reference parity: quantized GGUF serving). ``lora_adapter`` (a PEFT
    adapter dir) is merged into the weights as they stream (engine/lora.py).

    GGUF checkpoints (a .gguf path, or a dir holding one — what the
    ``ollama://``/``oci://`` puller produces) are dequantized host-side by
    engine/gguf.py and flow through the same cast/quantize/place path.
    """
    from localai_tpu.engine.lora import maybe_adapter

    adapter = maybe_adapter(lora_adapter, lora_scale)
    source, quantize = _host_leaf_source(model_dir, cfg, quantize)
    return _assemble(source, _make_put(cfg, mesh, dtype, quantize, adapter))


def stream_llama_params(
    model_dir: str,
    cfg,
    mesh=None,
    dtype=jnp.bfloat16,
    quantize: str = "",
    lora_adapter: str = "",
    lora_scale: float = 1.0,
    prefetcher: "Optional[WeightPrefetcher]" = None,
) -> tuple:
    """Streaming variant of :func:`load_llama_params` -> (params, stats).

    Same leaves, same cast/quantize/place path, two differences (ISSUE
    19, the warm scale-out / gallery-swap spin-up path):

    * a per-leaf pace hook — an explicit GIL yield so serving sibling
      threads keep their cadence while a multi-GB load streams, plus the
      ``weight_stream_slow_ms`` chaos seam (a slow disk/NFS source must
      degrade the LOAD, never the siblings);
    * when ``prefetcher`` holds this model's parsed leaves (predicted
      ahead of time from the gallery request log), the file-read /
      GPTQ-dequant / per-layer stack work is already paid — the warm
      path only casts and places, which is the measured SWAP_WARM_MS
      win.

    ``stats``: {leaves, bytes, prefetch_hit, ms}.
    """
    from localai_tpu.engine.lora import maybe_adapter
    from localai_tpu.services.faults import FAULTS

    t0 = time.monotonic()
    stats = {"leaves": 0, "bytes": 0, "prefetch_hit": False, "ms": 0.0}

    def pace(arr):
        stats["leaves"] += 1
        stats["bytes"] += int(arr.nbytes)
        if FAULTS.active:
            ms = FAULTS.take("weight_stream_slow_ms")
            if ms:
                time.sleep(min(30.0, float(ms) / 1000.0))
        time.sleep(0)   # explicit GIL yield between leaves

    adapter = maybe_adapter(lora_adapter, lora_scale)
    entry = prefetcher.consume(model_dir) if prefetcher is not None else None
    if entry is not None:
        stats["prefetch_hit"] = True
        source = iter(entry.leaves)
        if not quantize:
            quantize = entry.quantize
    else:
        source, quantize = _host_leaf_source(model_dir, cfg, quantize)
    params = _assemble(
        source, _make_put(cfg, mesh, dtype, quantize, adapter, pace=pace))
    stats["ms"] = (time.monotonic() - t0) * 1000.0
    return params, stats


class _PrefetchEntry:
    __slots__ = ("leaves", "quantize", "nbytes")

    def __init__(self, leaves, quantize, nbytes):
        self.leaves = leaves        # [(spec_path, host np array), ...]
        self.quantize = quantize    # effective (GPTQ detection applied)
        self.nbytes = nbytes


class WeightPrefetcher:
    """Host-side parsed-leaf cache for predicted-next models (ISSUE 19,
    PRESERVE-style).

    ``prefetch()`` parses a checkpoint into its final host leaves (file
    reads, GPTQ dequant, per-layer stacking, cast to the serving dtype —
    the expensive host half of a load) on a background thread, bounded
    by ``budget_mb``; a later ``stream_llama_params(..., prefetcher=...)``
    for that model consumes the entry and only pays device placement of
    already-device-dtype bytes (half the volume for a bf16 load of an
    f32 checkpoint). Entries are popped on consume (the leaves feed
    placement directly; keeping them would double host RAM) and
    abandoned — not trimmed — when a model exceeds the budget: a partial
    cache can't make a load warm.
    """

    def __init__(self, budget_mb: int = 8192):
        self.budget_bytes = max(1, int(budget_mb)) * 1024 * 1024
        self._cache: dict = {}      # model_dir -> _PrefetchEntry
        self._inflight: dict = {}   # model_dir -> Thread
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.bytes_total = 0        # bytes warmed into cache, lifetime
        self.prefetches = 0         # completed warms
        self.aborted = 0            # over-budget / failed warms

    def prefetch(self, model_dir: str, cfg, quantize: str = "",
                 dtype=jnp.bfloat16, wait: bool = False):
        """Warm ``model_dir`` in the background (idempotent while cached
        or in flight). ``dtype`` is the serving dtype the eventual load
        will request — unquantized leaves are pre-cast to it host-side
        so the consume path places the exact device bytes. ``wait=True``
        blocks until the warm finishes — bench/test use; production
        callers fire and forget."""
        with self._lock:
            t = self._inflight.get(model_dir)
            if t is None and model_dir not in self._cache:
                t = threading.Thread(
                    target=self._warm,
                    args=(model_dir, cfg, quantize, dtype),
                    name="weight-prefetch", daemon=True)
                self._inflight[model_dir] = t
                t.start()
        if wait and t is not None:
            t.join()

    def _warm(self, model_dir: str, cfg, quantize: str, dtype=None):
        try:
            source, q = _host_leaf_source(model_dir, cfg, quantize)
            leaves, total = [], 0
            for spec_path, arr in source:
                arr = np.asarray(arr)
                if dtype is not None and not q:
                    # pre-cast to the serving dtype: quantized loads keep
                    # f32 (quantize_weight wants full precision); a later
                    # load at a different dtype just re-casts — correct,
                    # merely not warm
                    arr = np.ascontiguousarray(arr.astype(dtype))
                total += int(arr.nbytes)
                if total > self.budget_bytes:
                    # abandon, don't trim: a partial cache still pays
                    # the cold path and would pin host RAM for nothing
                    self.aborted += 1
                    log.warning("weight prefetch of %s abandoned: %d B "
                                "exceeds budget %d B", model_dir, total,
                                self.budget_bytes)
                    return
                leaves.append((spec_path, arr))
                time.sleep(0)   # same politeness as the streaming load
            with self._lock:
                self._cache[model_dir] = _PrefetchEntry(leaves, q, total)
                self.bytes_total += total
                self.prefetches += 1
        except Exception:
            self.aborted += 1
            log.warning("weight prefetch of %s failed", model_dir,
                        exc_info=True)
        finally:
            with self._lock:
                self._inflight.pop(model_dir, None)

    def consume(self, model_dir: str) -> Optional[_PrefetchEntry]:
        """Pop the cached entry for a model about to load (hit), or None
        (miss — counted either way, exported as the hit/miss metrics)."""
        with self._lock:
            e = self._cache.pop(model_dir, None)
        if e is None:
            self.misses += 1
            return None
        self.hits += 1
        return e

    def cached(self, model_dir: str) -> bool:
        with self._lock:
            return model_dir in self._cache

    def snapshot(self) -> dict:
        with self._lock:
            cached = {d: e.nbytes for d, e in self._cache.items()}
        return {"hits": self.hits, "misses": self.misses,
                "bytes_total": self.bytes_total,
                "prefetches": self.prefetches, "aborted": self.aborted,
                "cached": cached,
                "budget_bytes": self.budget_bytes}


def random_params(cfg, dtype=jnp.bfloat16, quantize: str = "") -> dict:
    """Public entry for benchmark-shaped random weights: leaf-at-a-time
    host init streamed through the standard cast/quantize/place path, so
    an 8B never exists densely in f32 (32 GB) on host or device."""
    return _random_llama_params(cfg, _make_put(cfg, None, dtype, quantize))


def _iter_random_leaves(cfg):
    """Leaf-at-a-time random weights (see the gate in _host_leaf_source)."""
    rng = np.random.default_rng(0)
    hd = cfg.head_dim_
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, V = cfg.num_heads, cfg.num_kv_heads, cfg.vocab_size

    def mk(shape, fan_in):
        a = rng.standard_normal(shape, dtype=np.float32)
        a /= np.float32(np.sqrt(fan_in))
        return a

    leaves = [
        (("embed",), lambda: mk((V, D), D)),
        (("layers", "attn_norm"), lambda: np.ones((L, D), np.float32)),
        (("layers", "wq"), lambda: mk((L, D, H * hd), D)),
        (("layers", "wk"), lambda: mk((L, D, KV * hd), D)),
        (("layers", "wv"), lambda: mk((L, D, KV * hd), D)),
        (("layers", "wo"), lambda: mk((L, H * hd, D), H * hd)),
        (("layers", "mlp_norm"), lambda: np.ones((L, D), np.float32)),
        (("layers", "w_gate"), lambda: mk((L, D, F), D)),
        (("layers", "w_up"), lambda: mk((L, D, F), D)),
        (("layers", "w_down"), lambda: mk((L, F, D), F)),
        (("final_norm",), lambda: np.ones((D,), np.float32)),
    ]
    if not cfg.tie_word_embeddings:
        leaves.append((("lm_head",), lambda: mk((D, V), D)))
    for spec_path, gen in leaves:
        yield spec_path, gen()


def _random_llama_params(cfg, put) -> dict:
    return _assemble(_iter_random_leaves(cfg), put)


def save_llama_params(params: dict, cfg, model_dir: str):
    """Write params back to HF layout (single shard). Test/export helper."""
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    out = {}
    ly = params["layers"]
    np32 = lambda a: np.asarray(jax.device_get(a), np.float32)
    out["model.embed_tokens.weight"] = np32(params["embed"])
    out["model.norm.weight"] = np32(params["final_norm"])
    if "lm_head" in params:
        out["lm_head.weight"] = np32(params["lm_head"]).T
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        out[p + "input_layernorm.weight"] = np32(ly["attn_norm"][i])
        out[p + "self_attn.q_proj.weight"] = np32(ly["wq"][i]).T
        out[p + "self_attn.k_proj.weight"] = np32(ly["wk"][i]).T
        out[p + "self_attn.v_proj.weight"] = np32(ly["wv"][i]).T
        out[p + "self_attn.o_proj.weight"] = np32(ly["wo"][i]).T
        out[p + "post_attention_layernorm.weight"] = np32(ly["mlp_norm"][i])
        out[p + "mlp.gate_proj.weight"] = np32(ly["w_gate"][i]).T
        out[p + "mlp.up_proj.weight"] = np32(ly["w_up"][i]).T
        out[p + "mlp.down_proj.weight"] = np32(ly["w_down"][i]).T
    save_file(out, os.path.join(model_dir, "model.safetensors"))
