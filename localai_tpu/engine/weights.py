"""HF safetensors checkpoint -> stacked JAX param pytree.

Replaces the reference's GGUF weight pipeline (llama.cpp model loading +
core/config/guesser.go GGUF header parsing) with the TPU-native flow:
HF safetensors shards are memory-mapped, per-layer tensors are stacked on
a leading layer axis (for the scan-over-layers forward), cast to bf16, and
placed shard-by-shard onto the device mesh so peak host memory stays at
one tensor, not one model.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:
    from safetensors import safe_open
except ImportError:  # pragma: no cover
    safe_open = None


def _open_shards(model_dir: str):
    """Yield (name -> shard accessor) across all safetensors files."""
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    handles = {f: safe_open(f, framework="np") for f in files}
    name_to_file = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        for name, fname in index["weight_map"].items():
            name_to_file[name] = handles[os.path.join(model_dir, fname)]
    else:
        for f, h in handles.items():
            for name in h.keys():
                name_to_file[name] = h
    return name_to_file


def find_gguf(model_dir: str) -> Optional[str]:
    """Path to the GGUF file a model dir/path refers to, if any: either the
    path itself or the single *.gguf inside a directory with no safetensors
    (the shape an ``ollama://`` / gallery pull produces)."""
    if model_dir.endswith(".gguf") and os.path.isfile(model_dir):
        return model_dir
    if os.path.isdir(model_dir):
        ggufs = sorted(glob.glob(os.path.join(model_dir, "*.gguf")))
        sts = glob.glob(os.path.join(model_dir, "*.safetensors"))
        if len(ggufs) == 1 and not sts:
            return ggufs[0]
    return None


_QUANT_NAMES = {"embed", "lm_head", "wq", "wk", "wv", "wo",
                "w_gate", "w_up", "w_down"}


def _make_put(cfg, mesh, dtype, quantize, adapter=None):
    """Leaf placer: host array + pytree path -> (LoRA-merged) cast /
    int8/int4-quantized / mesh-sharded device leaf."""

    def leaf_spec(spec_path: tuple):
        from localai_tpu.parallel import sharding as shardlib

        node = shardlib.llama_param_specs(cfg.tie_word_embeddings)
        for k in spec_path:
            node = node[k]
        return node

    def put(arr: np.ndarray, spec_path: tuple):
        leaf_name = spec_path[-1]
        if adapter is not None and spec_path[0] == "layers" \
                and adapter.targets_leaf(leaf_name, cfg.num_layers):
            # merge W += scale*(B@A) BEFORE cast/quantization (reference:
            # LoraAdapter applied at load, grpc-server.cpp:2295-2309);
            # in-place per layer — no full-leaf delta buffer
            arr = np.array(arr, np.float32)  # always a fresh writable copy
            adapter.apply_to_leaf(leaf_name, cfg.num_layers, arr)
        if quantize in ("int8", "int4") and leaf_name in _QUANT_NAMES:
            from localai_tpu.ops.quant import (quantize_weight,
                                               quantize_weight_int4)

            # int4 applies to the layer matmuls only; embed/lm_head stay
            # int8 (see models/llama.py quantize_params for why)
            if quantize == "int4" and spec_path[0] == "layers":
                # the group count must divide the tp degree on the
                # contraction axis or the scale can't shard with its
                # weight (e.g. llama-2's 11008 FFN: 86 groups vs tp=8)
                divisor = 1
                if mesh is not None:
                    from localai_tpu.parallel.sharding import fit_spec

                    axis = fit_spec(mesh, arr.shape,
                                    leaf_spec(spec_path))[-2]
                    if axis is not None:
                        divisor = mesh.shape[axis]
                leaf = quantize_weight_int4(arr, shard_divisor=divisor)
            else:
                leaf = quantize_weight(arr)
        else:
            leaf = jnp.asarray(arr, dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from localai_tpu.ops.quant import scale_spec
            from localai_tpu.parallel.sharding import fit_spec

            node = fit_spec(
                mesh, (leaf["q"] if isinstance(leaf, dict) else leaf).shape,
                leaf_spec(spec_path))
            if isinstance(leaf, dict):
                q = jax.device_put(leaf["q"], NamedSharding(mesh, node))
                s = jax.device_put(leaf["s"], NamedSharding(
                    mesh, scale_spec(leaf, node)))
                return {"q": q, "s": s}
            return jax.device_put(leaf, NamedSharding(mesh, node))
        return leaf

    return put


def load_llama_params(
    model_dir: str,
    cfg,
    mesh=None,
    dtype=jnp.bfloat16,
    quantize: str = "",
    lora_adapter: str = "",
    lora_scale: float = 1.0,
) -> dict:
    """Load HF llama/mistral/qwen2-style weights into the stacked pytree.

    When ``mesh`` is given, each leaf is placed with the tensor-parallel
    sharding from parallel/sharding.py as it is assembled. quantize="int8"
    converts matmul weights to weight-only per-channel int8 at load time
    (reference parity: quantized GGUF serving). ``lora_adapter`` (a PEFT
    adapter dir) is merged into the weights as they stream (engine/lora.py).

    GGUF checkpoints (a .gguf path, or a dir holding one — what the
    ``ollama://``/``oci://`` puller produces) are dequantized host-side by
    engine/gguf.py and flow through the same cast/quantize/place path.
    """
    from localai_tpu.engine.lora import maybe_adapter

    adapter = maybe_adapter(lora_adapter, lora_scale)
    gguf_path = find_gguf(model_dir)
    if gguf_path is not None:
        from localai_tpu.engine import gguf as gguflib

        g = gguflib.open_gguf(gguf_path)
        put = _make_put(cfg, mesh, dtype, quantize, adapter)
        params: dict = {"layers": {}}
        # leaf-at-a-time: dequantize (f16 host), place on device, free —
        # peak host memory is one stacked leaf, not the dense model
        for spec_path, arr in gguflib.iter_llama_tensors(g, cfg):
            node = params
            for k in spec_path[:-1]:
                node = node[k]
            node[spec_path[-1]] = put(arr, spec_path)
            del arr
        return params
    try:
        tensors = _open_shards(model_dir)
    except FileNotFoundError:
        if os.environ.get("LOCALAI_ALLOW_RANDOM_WEIGHTS") == "1":
            # BENCH/TEST ONLY: a config.json-only dir serves random weights
            # through the same cast/quantize/shard path — lets the full
            # serving stack run benchmark-shaped models (e.g. 8B int8 on
            # one chip) without writing a multi-GB checkpoint to disk.
            # Gated: silently serving garbage from an incomplete real
            # checkpoint would be far worse than this convenience.
            return _random_llama_params(
                cfg, _make_put(cfg, mesh, dtype, quantize, adapter))
        raise

    def get(name: str) -> np.ndarray:
        h = tensors[name]
        return h.get_tensor(name)

    from localai_tpu.engine import gptq as gptqlib

    qmeta = gptqlib.detect(model_dir)
    if qmeta is not None and not quantize:
        # a GPTQ/AWQ checkpoint carries a memory intent; default to the
        # TPU-native weight-only int8 so loading it doesn't silently
        # inflate to dense bf16 (set quantization explicitly to override)
        quantize = "int8"

    put = _make_put(cfg, mesh, dtype, quantize, adapter)

    L = cfg.num_layers

    def linear_T(name: str) -> np.ndarray:
        """Linear weight as [in, out]; GPTQ/AWQ-packed modules are
        dequantized host-side (engine/gptq.py) in that orientation."""
        base = name[: -len(".weight")]
        if qmeta is not None and base + ".qweight" in tensors:
            return gptqlib.dequant_linear(get, base, qmeta)
        return get(name).T

    def stack(fmt: str, transpose: bool = False) -> np.ndarray:
        mats = []
        for i in range(L):
            name = fmt.format(i=i)
            mats.append(linear_T(name) if transpose else get(name))
        return np.stack(mats)

    p = "model.layers.{i}."
    params = {
        "embed": put(get("model.embed_tokens.weight"), ("embed",)),
        "layers": {
            "attn_norm": put(stack(p + "input_layernorm.weight"), ("layers", "attn_norm")),
            "wq": put(stack(p + "self_attn.q_proj.weight", transpose=True), ("layers", "wq")),
            "wk": put(stack(p + "self_attn.k_proj.weight", transpose=True), ("layers", "wk")),
            "wv": put(stack(p + "self_attn.v_proj.weight", transpose=True), ("layers", "wv")),
            "wo": put(stack(p + "self_attn.o_proj.weight", transpose=True), ("layers", "wo")),
            "mlp_norm": put(stack(p + "post_attention_layernorm.weight"), ("layers", "mlp_norm")),
            "w_gate": put(stack(p + "mlp.gate_proj.weight", transpose=True), ("layers", "w_gate")),
            "w_up": put(stack(p + "mlp.up_proj.weight", transpose=True), ("layers", "w_up")),
            "w_down": put(stack(p + "mlp.down_proj.weight", transpose=True), ("layers", "w_down")),
        },
        "final_norm": put(get("model.norm.weight"), ("final_norm",)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = put(linear_T("lm_head.weight"), ("lm_head",))
    return params


def random_params(cfg, dtype=jnp.bfloat16, quantize: str = "") -> dict:
    """Public entry for benchmark-shaped random weights: leaf-at-a-time
    host init streamed through the standard cast/quantize/place path, so
    an 8B never exists densely in f32 (32 GB) on host or device."""
    return _random_llama_params(cfg, _make_put(cfg, None, dtype, quantize))


def _random_llama_params(cfg, put) -> dict:
    """Leaf-at-a-time random weights (see the gate in load_llama_params)."""
    rng = np.random.default_rng(0)
    hd = cfg.head_dim_
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, V = cfg.num_heads, cfg.num_kv_heads, cfg.vocab_size

    def mk(shape, fan_in):
        a = rng.standard_normal(shape, dtype=np.float32)
        a /= np.float32(np.sqrt(fan_in))
        return a

    leaves = [
        (("embed",), lambda: mk((V, D), D)),
        (("layers", "attn_norm"), lambda: np.ones((L, D), np.float32)),
        (("layers", "wq"), lambda: mk((L, D, H * hd), D)),
        (("layers", "wk"), lambda: mk((L, D, KV * hd), D)),
        (("layers", "wv"), lambda: mk((L, D, KV * hd), D)),
        (("layers", "wo"), lambda: mk((L, H * hd, D), H * hd)),
        (("layers", "mlp_norm"), lambda: np.ones((L, D), np.float32)),
        (("layers", "w_gate"), lambda: mk((L, D, F), D)),
        (("layers", "w_up"), lambda: mk((L, D, F), D)),
        (("layers", "w_down"), lambda: mk((L, F, D), F)),
        (("final_norm",), lambda: np.ones((D,), np.float32)),
    ]
    if not cfg.tie_word_embeddings:
        leaves.append((("lm_head",), lambda: mk((D, V), D)))
    params: dict = {"layers": {}}
    for spec_path, gen in leaves:
        arr = gen()
        node = params
        for k in spec_path[:-1]:
            node = node[k]
        node[spec_path[-1]] = put(arr, spec_path)
        del arr
    return params


def save_llama_params(params: dict, cfg, model_dir: str):
    """Write params back to HF layout (single shard). Test/export helper."""
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    out = {}
    ly = params["layers"]
    np32 = lambda a: np.asarray(jax.device_get(a), np.float32)
    out["model.embed_tokens.weight"] = np32(params["embed"])
    out["model.norm.weight"] = np32(params["final_norm"])
    if "lm_head" in params:
        out["lm_head.weight"] = np32(params["lm_head"]).T
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        out[p + "input_layernorm.weight"] = np32(ly["attn_norm"][i])
        out[p + "self_attn.q_proj.weight"] = np32(ly["wq"][i]).T
        out[p + "self_attn.k_proj.weight"] = np32(ly["wk"][i]).T
        out[p + "self_attn.v_proj.weight"] = np32(ly["wv"][i]).T
        out[p + "self_attn.o_proj.weight"] = np32(ly["wo"][i]).T
        out[p + "post_attention_layernorm.weight"] = np32(ly["mlp_norm"][i])
        out[p + "mlp.gate_proj.weight"] = np32(ly["w_gate"][i]).T
        out[p + "mlp.up_proj.weight"] = np32(ly["w_up"][i]).T
        out[p + "mlp.down_proj.weight"] = np32(ly["w_down"][i]).T
    save_file(out, os.path.join(model_dir, "model.safetensors"))
