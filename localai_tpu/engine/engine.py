"""The TPU serving engine: continuous batching over a compiled decode step.

Re-design of the reference's slot-based continuous-batching server
(reference: backend/cpp/llama/grpc-server.cpp — llama_client_slot :162-301,
task queue utils.hpp:192,336, update_slots hot loop :1578-2013) for XLA's
compilation model:

  * The decode step is ONE jitted function over ALL slots, compiled once —
    inactive slots ride along masked (static shapes, no recompiles).
  * Prompts are prefilled in CHUNKS between decode steps (reference packs
    prompt chunks and decode tokens into one llama_batch, :1671+; XLA's
    static shapes make separate interleaved steps the natural mapping), so
    admitting a long prompt never stalls decode for active slots by more
    than one chunk's compute.
  * KV PREFIX REUSE: per-slot cache contents are tracked host-side; a new
    request is admitted into the free slot sharing the longest common
    token prefix and only the suffix is prefilled (reference:
    grpc-server.cpp:1721-1835 cache_tokens common-prefix reuse).
  * CONTEXT SHIFT: when a slot's cache fills mid-generation, the engine
    re-prefills the tail half of the context into the slot (chunked, so
    other slots keep decoding) and generation continues — the recompute
    equivalent of the reference's KV surgery (llama_kv_cache_seq_rm/add,
    grpc-server.cpp:1832,1916-1927), which XLA's immutable buffers and
    RoPE'd keys make the honest TPU design.
  * Sampling (full per-slot parameter suite) and the penalty-ring update
    are fused INTO the compiled steps — no per-token host round-trip for
    anything but the sampled ids themselves.
  * Admission/stop logic runs host-side on a dedicated engine thread,
    mirroring the reference's queue thread (grpc-server.cpp:2083-2096).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.engine import sampling
from localai_tpu.engine.detok import IncrementalDetokenizer
from localai_tpu.models import llama


@dataclasses.dataclass
class EngineConfig:
    num_slots: int = 8
    max_context: int = 2048
    prefill_buckets: tuple = (32, 128, 512, 2048)
    prefill_chunk: int = 512   # max prompt tokens processed between decode steps
    context_shift: bool = True  # re-prefill tail window when a slot's cache fills
    cache_dtype: Any = jnp.bfloat16
    # speculative decoding: draft proposals per round (0 disables even
    # when a draft model is loaded); greedy slots only
    n_draft: int = 4
    # decode BURST: run up to this many decode steps per device dispatch
    # (lax.scan), amortizing per-dispatch overhead (measured ~3-12 ms on the
    # serving chip — larger than one step's compute). Grammar-constrained
    # slots ride bursts speculatively (verify + free rollback at processing
    # time); bursts clamp to cache-capacity conditions, see _pick_burst.
    decode_burst: int = 16


@dataclasses.dataclass
class GenRequest:
    prompt_ids: list
    params: sampling.SamplingParamsHost = dataclasses.field(
        default_factory=sampling.SamplingParamsHost
    )
    max_new_tokens: int = 256
    stop_sequences: list = dataclasses.field(default_factory=list)
    ignore_eos: bool = False
    grammar: str = ""               # GBNF constrained decoding
    # prompt-cache persistence (reference: backend.proto:132-138,
    # options.go:182-191): committed KV rows + tokens saved to this path
    # on finish, restored on prefix match at admission
    prompt_cache_path: str = ""
    prompt_cache_ro: bool = False   # restore only, never write
    prompt_cache_all: bool = False  # persist generated rows too
    # multimodal (LLaVA-style): projected image embeddings to inject at
    # absolute prompt positions (prompt_ids holds pad tokens there)
    mm_positions: list = dataclasses.field(default_factory=list)  # [P] ints
    mm_vectors: Any = None          # np [P, hidden] float32
    request_id: str = ""
    # filled by engine:
    out: "queue.Queue" = None  # receives StreamEvent, then None sentinel

    def __post_init__(self):
        if not self.request_id:
            self.request_id = uuid.uuid4().hex[:16]
        if self.out is None:
            self.out = queue.Queue()


@dataclasses.dataclass
class StreamEvent:
    token_id: int
    text: str               # finalized delta (may be "")
    logprob: float
    finish_reason: Optional[str] = None  # "stop" | "length" | None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    timings: Optional[dict] = None
    error: Optional[str] = None
    # burst-coalesced events carry every member token (r3: emitting one
    # queue event per token cost ~0.35 ms/token of host time on the 1-core
    # serving host — GIL/wakeup churn — and serialized against the next
    # dispatch; the engine now emits ONE event per slot per processed
    # burst). token_id/logprob above are the LAST member's.
    token_ids: Optional[list] = None
    logprobs: Optional[list] = None


def event_ids(events) -> list:
    """Flatten a stream of (possibly coalesced) events to token ids."""
    out = []
    for e in events:
        if e.token_ids:
            out.extend(e.token_ids)
        elif e.token_id >= 0:
            out.append(e.token_id)
    return out


def _merge_events(evs: list) -> StreamEvent:
    last = evs[-1]
    return dataclasses.replace(
        last,
        text="".join(e.text for e in evs),
        token_ids=[e.token_id for e in evs],
        logprobs=[e.logprob for e in evs],
    )


class _Burst:
    """A dispatched decode burst awaiting host processing."""
    __slots__ = ("n_steps", "slots", "ids_all", "lps_all", "mu_out", "ids_np",
                 "lps_np", "folded", "skip_slots")

    def __init__(self, n_steps, slots, ids_all, lps_all, mu_out):
        self.n_steps = n_steps
        self.slots = slots          # [(index, _Slot snapshot), ...]
        self.ids_all = ids_all      # device [K, S]
        self.lps_all = lps_all
        self.mu_out = mu_out        # device [S] mirostat state after the burst
        self.ids_np = None
        self.lps_np = None
        self.folded = False
        # slots whose host state was rolled back AFTER this burst was
        # dispatched (grammar rollback): the burst's tokens for them are
        # conditioned on a discarded token and must be dropped wholesale
        self.skip_slots: set = set()


class _Slot:
    __slots__ = (
        "req", "detok", "generated", "held_text", "prompt_len",
        "t_start", "t_first_token", "n_decoded", "t_prefill_ms",
        "grammar", "gstate", "bias_base", "cur_penalty",
        "phase", "pending", "written", "reused", "cache_len", "committed",
        "mm_pos", "mm_vec", "spec_ok",
    )

    def __init__(self, req: GenRequest, detok, prompt_len: int):
        self.req = req
        self.detok = detok
        self.generated: list[int] = []
        self.held_text = ""   # text withheld due to partial stop-seq match
        self.prompt_len = prompt_len
        self.t_start = time.monotonic()
        self.t_first_token = 0.0
        self.n_decoded = 0
        self.t_prefill_ms = 0.0
        self.grammar = None     # functions.grammars.automaton.Grammar
        self.gstate = None      # current frozenset state
        self.bias_base = None   # np [V] logit_bias row under the grammar mask
        self.cur_penalty = None  # last uploaded penalty row (identity-compared)
        self.phase = "prefill"  # "prefill" -> "decode"
        self.mm_pos = None      # np [P] absolute prompt positions (P-bucketed)
        self.mm_vec = None      # np [P, hidden] injected embeddings
        self.spec_ok = False    # greedy+ungrammared: may join spec rounds
        self.pending: list[int] = []   # prompt tokens not yet prefilled
        self.written = 0        # cache rows already valid for this request
        self.reused = 0         # prefix tokens reused from a previous request
        self.cache_len = 0      # rows occupied in the slot's KV cache
        self.committed = 0      # rows whose KV write has actually executed


class Engine:
    """Owns the model state and a background step-loop thread."""

    def __init__(
        self,
        model_cfg: llama.LlamaConfig,
        params,
        tokenizer,
        engine_cfg: EngineConfig = None,
        eos_token_ids: Optional[set] = None,
        mesh=None,
        param_shardings=None,
        draft: Optional[tuple] = None,   # (LlamaConfig, params) draft model
    ):
        self.cfg = model_cfg
        self.ecfg = engine_cfg or EngineConfig()
        self.tokenizer = tokenizer
        self.mesh = mesh
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        V = model_cfg.vocab_size

        self.params = params
        # speculative decoding (greedy-lossless; see engine/speculative.py)
        self.draft_cfg, self.draft_params = draft if draft else (None, None)
        self._state_shardings = self._make_state_shardings()
        # device-resident state: big (KV cache), rarely-mutated (bias), or
        # not host-mirrorable (PRNG keys). Everything per-slot and small
        # lives as HOST numpy — admissions/releases are then free in-place
        # writes instead of ~3ms `.at[].set` dispatches, and the arrays ride
        # to the device as ordinary jit args each step.
        self.ck, self.cv = llama.init_cache(model_cfg, S, C, self.ecfg.cache_dtype)
        # draft cache is allocated LAZILY at the first spec-eligible
        # admission (r2 allocated it up front, doubling per-slot KV HBM
        # even when no request could ever speculate)
        self.dck = self.dcv = None
        self.bias = jnp.zeros((S, V), jnp.float32)
        self.rng_keys = jax.vmap(jax.random.key_data)(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(S, dtype=jnp.uint32))
        )
        self.slot_params = sampling.make_slot_params(S)
        self.ring, self.ring_pos = sampling.make_ring(S)
        self.mu = sampling.make_mu(S)
        self.lengths = np.zeros((S,), np.int32)
        self.cur_tokens = np.zeros((S,), np.int32)
        self.active_dev = np.zeros((S,), np.bool_)
        self._bias_dirty = np.zeros((S,), np.bool_)
        self._shard_state()

        if eos_token_ids:
            self.eos_ids = set(eos_token_ids)
        else:
            self.eos_ids = set()
            eid = getattr(tokenizer, "eos_token_id", None)
            if eid is not None:
                self.eos_ids.add(int(eid))

        # host mirrors
        self.slots: list[Optional[_Slot]] = [None] * S
        self._cache_tokens: list[list[int]] = [[] for _ in range(S)]
        self._prefill_queue: list[int] = []   # slot ids awaiting prefill chunks
        self._cancelled: set = set()
        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._load_time = time.monotonic()
        self._total_tokens = 0
        self._reused_total = 0

        self._burst_fns: dict[int, Callable] = {}
        self._chunk_fns: dict[int, Callable] = {}
        self._final_fns: dict[tuple, Callable] = {}
        self._spec_fn = None
        self._spec_turn = True   # mixed-traffic spec/burst alternation
        self._last_active_key = None

        # pipelined decode state: device-side burst-to-burst chain of
        # (tokens, lengths, ring, ring_pos), the not-yet-processed burst,
        # and whether host events invalidated the chain
        self._chain = None
        self._chain_dirty = True
        self._inflight: Optional[_Burst] = None
        # async prefill: up to TWO final-prefill groups may be in flight
        # (FIFO) — a second group dispatches while the first computes, so
        # wave turnover isn't serialized through one pending slot
        self._pending_prefill: list = []

        # effective prefill buckets always include the chunk size; both are
        # clamped to the cache capacity (a bucket larger than max_context
        # could never be written and would crash the prefill KV update)
        self._chunk = min(self.ecfg.prefill_chunk, C)
        self._buckets = tuple(sorted(set(
            [b for b in self.ecfg.prefill_buckets if b <= min(self._chunk, C)]
            + [self._chunk])))
        # fresh final prefills batch up to this many prompts per dispatch
        # (padded by repeating the last entry, so only two compiled batch
        # sizes exist per bucket: 1 and _final_pad). Sized for the wave-
        # turnover case (r3 trace: all slots finishing together serialized
        # 4 groups of 8 through one pending slot, stalling the device ~1s
        # per wave): one group should swallow half the fleet.
        self._final_pad = max(8, min(16, self.ecfg.num_slots))

        # grammar-constrained decoding (lazy: built on first grammar request)
        self._grammar_cache: dict[str, Any] = {}
        self._mask_builder = None
        self._token_strs: Optional[list] = None

        # loop-phase tracing (LOCALAI_ENGINE_TRACE=1): cumulative seconds
        # per phase + counts, dumped at shutdown — the tool that found the
        # r3 serving-vs-kernel gap
        import os as _os

        self._trace = _os.environ.get("LOCALAI_ENGINE_TRACE", "") == "1"
        self._tstats: dict = {}
        # non-None while _process_burst coalesces per-slot events
        self._sink_buf: Optional[dict] = None
        # in-flight prefill dedup: leader slot -> [(sib_slot, snap, leader
        # snap, ids)]; KV rows fork when the leader's prefill commits
        self._fork_waiters: dict = {}
        self._fork_fns: dict = {}
        # grammar slots whose mask row changed since the last device flush
        self._gbias_flush: set = set()

    def _tmark(self, key: str, t0: float):
        if self._trace:
            t = time.monotonic()
            s = self._tstats.setdefault(key, [0.0, 0])
            s[0] += t - t0
            s[1] += 1

    def _make_state_shardings(self) -> Optional[dict]:
        """NamedShardings for the engine's device state when serving on a
        mesh (parallel/sharding.py cache_spec: slots on dp, kv heads on tp).
        Falls back to replication per axis when sizes don't divide — a
        wrong-but-silent replicated cache is exactly the HBM waste this
        exists to avoid, so only shard what divides evenly."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        dp = self.mesh.shape.get("dp", 1)
        tp = self.mesh.shape.get("tp", 1)
        slot_ax = "dp" if dp > 1 and self.ecfg.num_slots % dp == 0 else None
        kv_ax = "tp" if tp > 1 and self.cfg.num_kv_heads % tp == 0 else None

        def ns(*spec):
            return NamedSharding(self.mesh, P(*spec))

        return {
            "cache": ns(None, slot_ax, None, kv_ax, None),  # [L, S, C, KV, hd]
            "slot_vec": ns(slot_ax),                        # [S]
            "slot_mat": ns(slot_ax, None),                  # [S, V] / [S, 2]
        }

    def _shard_state(self):
        """Commit device-resident state to the mesh (ADVICE r1: without this
        the dp/tp cache sharding was never applied in the real serving path —
        every device held a full replica of the KV cache). Host-numpy slot
        state needs no commitment — it enters jitted steps as arguments and
        GSPMD places it."""
        sh = self._state_shardings
        if sh is None:
            return
        self.ck = jax.device_put(self.ck, sh["cache"])
        self.cv = jax.device_put(self.cv, sh["cache"])
        self.bias = jax.device_put(self.bias, sh["slot_mat"])
        self.rng_keys = jax.device_put(self.rng_keys, sh["slot_mat"])

    # ---------- jitted step bodies ----------

    def _decode_burst_body(self, params, tokens, ck, cv, lengths, ring, ring_pos,
                           bias, keys, slot_params, active, mu, n_steps: int,
                           flags: tuple = (True, True, True)):
        """n_steps decode+sample steps in ONE dispatch (lax.scan).

        Per-dispatch overhead on the serving chip is comparable to one step's
        compute, so bursts are the single biggest serving-throughput lever.
        bias/slot_params/active are constant across the burst (the engine
        forces n_steps=1 whenever a grammar slot needs per-token bias).
        """
        C = self.ecfg.max_context

        def step(carry, _):
            tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
            # inactive slots (free / mid-prefill) must NOT write KV: force
            # their write position to C so the scatter's mode="drop" discards
            # it — otherwise every decode step would clobber row 0 of slots
            # holding reusable prefixes or in-flight prefill chunks
            write_lengths = jnp.where(active, lengths, C)
            logits, ck, cv = llama.decode_step(params, self.cfg, tokens,
                                               write_lengths, ck, cv)
            ids, logprobs, new_keys, new_mu = sampling.sample(
                logits, slot_params, ring, ring_pos, bias, keys, mu,
                use_penalties=flags[0], use_typical=flags[1],
                use_mirostat=flags[2])
            # only active slots consume RNG/mirostat state; a prefilling
            # slot's seeded state must not advance with others' decode steps
            keys = jnp.where(active[:, None], new_keys, keys)
            mu = jnp.where(active, new_mu, mu)
            ring, ring_pos = sampling.update_ring(ring, ring_pos, ids, active)
            lengths = lengths + active.astype(jnp.int32)
            tokens = jnp.where(active, ids, tokens)
            return (tokens, ck, cv, lengths, ring, ring_pos, keys, mu), (ids, logprobs)

        carry = (tokens, ck, cv, lengths, ring, ring_pos, keys, mu)
        carry, (ids_all, lps_all) = jax.lax.scan(step, carry, None, length=n_steps)
        tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
        # tokens/lengths/ring/mu are returned as DEVICE handles so the next
        # burst can chain off them without a host round-trip (pipelined
        # decode); the host separately mirrors the same evolution from the
        # emitted ids for use whenever admissions/releases reset slot state
        # (mu is device-only knowledge: it is folded back from this output)
        return ids_all, lps_all, ck, cv, keys, (tokens, lengths, ring, ring_pos, mu)

    def _prefill_chunk_body(self, params, tokens, seq_len, ck, cv, slot, start_pos,
                            mm_pos=None, mm_vec=None):
        """Non-final chunk: write KV only, no sampling. (The penalty ring is
        seeded host-side at admission from the full prompt tail.)"""
        _, ck, cv = llama.prefill(params, self.cfg, tokens, seq_len, ck, cv, slot,
                                  start_pos, continued=True,
                                  mm_pos=mm_pos, mm_vec=mm_vec)
        return ck, cv

    def _prefill_final_body(self, params, tokens, seq_len, ck, cv, slot, start_pos,
                            ring, ring_pos, bias, keys, slot_params, mu,
                            continued: bool, mm_pos=None, mm_vec=None):
        """Final chunk for a BATCH of B prompts: write KV, sample each one's
        first output token. slot may contain duplicate entries (batch
        padding repeats the last prompt; duplicate KV writes and key
        scatters are idempotent — same inputs, last write wins)."""
        logits, ck, cv = llama.prefill(params, self.cfg, tokens, seq_len, ck, cv,
                                       slot, start_pos, continued=continued,
                                       mm_pos=mm_pos, mm_vec=mm_vec)
        sp_rows = jax.tree.map(lambda a: jnp.take(jnp.asarray(a), slot, axis=0),
                               slot_params)
        bias_rows = jnp.take(bias, slot, axis=0)
        key_rows = jnp.take(keys, slot, axis=0)
        ring_rows = jnp.take(jnp.asarray(ring), slot, axis=0)
        rpos_rows = jnp.take(jnp.asarray(ring_pos), slot, axis=0)
        mu_rows = jnp.take(jnp.asarray(mu), slot, axis=0)
        ids, logprobs, new_keys, new_mu = sampling.sample(
            logits, sp_rows, ring_rows, rpos_rows, bias_rows, key_rows, mu_rows)
        keys = keys.at[slot].set(new_keys)
        mu = jnp.asarray(mu).at[slot].set(new_mu)
        return ids, logprobs, ck, cv, keys, mu

    def _get_burst_fn(self, n_steps: int, flags: tuple = (True, True, True)):
        key = (n_steps, flags)
        fn = self._burst_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: self._decode_burst_body(*a, n_steps=n_steps,
                                                   flags=flags),
                donate_argnums=(2, 3, 8))
            self._burst_fns[key] = fn
        return fn

    def _get_chunk_fn(self, bucket: int):
        fn = self._chunk_fns.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_chunk_body, donate_argnums=(3, 4))
            self._chunk_fns[bucket] = fn
        return fn

    def _get_draft_chunk_fn(self, bucket: int):
        """Draft-model prompt ingestion (the draft has its OWN config —
        the target-cfg chunk body would mis-shape or mis-parameterize it)."""
        key = ("draft", bucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda p, t, s, ck, cv, sl, st: llama.prefill(
                    p, self.draft_cfg, t, s, ck, cv, sl, st,
                    continued=True)[1:],
                donate_argnums=(3, 4))
            self._chunk_fns[key] = fn
        return fn

    def _get_final_fn(self, bucket: int, batch: int, continued: bool):
        key = (bucket, batch, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: self._prefill_final_body(*a, continued=continued),
                donate_argnums=(3, 4, 10))
            self._final_fns[key] = fn
        return fn

    # multimodal prefill variants (B=1, lazily compiled on first vision
    # request; keyed additionally on the image-embedding bucket P)

    def _get_mm_chunk_fn(self, bucket: int, pbucket: int):
        key = ("mm", bucket, pbucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(self._prefill_chunk_body, donate_argnums=(3, 4))
            self._chunk_fns[key] = fn
        return fn

    def _get_mm_final_fn(self, bucket: int, pbucket: int, continued: bool):
        key = ("mm", bucket, pbucket, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: self._prefill_final_body(*a[:13], continued=continued,
                                                    mm_pos=a[13], mm_vec=a[14]),
                donate_argnums=(3, 4, 10))
            self._final_fns[key] = fn
        return fn

    # ---------- public API ----------

    def precompile(self):
        """Compile + execute every jitted variant the serving loop can hit
        (burst sizes, prefill buckets x fresh/continued) BEFORE taking
        traffic. A cold XLA compile costs 20-40s on the serving chip;
        hitting one mid-wave stalls every active request (measured: one
        stray burst-size compile turned a 7s bench wave into 40s).

        Bursts run with all slots inactive — a state-preserving no-op.
        Prefill warmups write one garbage row into (free) slot 0's cache;
        admission reseeds all per-slot state, so this is invisible to
        traffic. Mirrors the reference's LoadToMemory warmup
        (core/startup/startup.go:148-176); pairs with the persistent
        compilation cache (utils/jaxtools.py) so restarts compile fast."""
        k = 1
        ks = []
        while k <= self.ecfg.decode_burst:
            ks.append(k)
            k *= 2
        for k in ks:
            for flags in ((False, False, False), (True, True, True)):
                fn = self._get_burst_fn(k, flags)
                _, _, self.ck, self.cv, self.rng_keys, _ = fn(
                    self.params, self.cur_tokens, self.ck, self.cv, self.lengths,
                    self.ring, self.ring_pos, self.bias, self.rng_keys,
                    self.slot_params, self.active_dev, self.mu)
        for bucket in self._buckets:
            one = np.ones((1,), np.int32)
            zero = np.zeros((1,), np.int32)
            tokens = np.zeros((1, bucket), np.int32)
            if bucket == self._chunk:
                # non-final chunks always use the full chunk bucket
                self.ck, self.cv = self._get_chunk_fn(bucket)(
                    self.params, tokens, one, self.ck, self.cv, zero, zero)
            for batch, continued in ((1, False), (1, True),
                                     (self._final_pad, False)):
                if batch == 1:
                    tb, sb = tokens, one
                    slotb = startb = zero
                else:
                    tb = np.zeros((batch, bucket), np.int32)
                    sb = np.ones((batch,), np.int32)
                    slotb = startb = np.zeros((batch,), np.int32)
                fn = self._get_final_fn(bucket, batch, continued)
                _, _, self.ck, self.cv, self.rng_keys, _ = fn(
                    self.params, tb, sb, self.ck, self.cv, slotb, startb,
                    self.ring, self.ring_pos, self.bias, self.rng_keys,
                    self.slot_params, self.mu)
        jax.block_until_ready(self.ck)

    def start(self, precompile: bool = False):
        if precompile:
            self.precompile()
        self._thread = threading.Thread(target=self._run, name="engine-loop", daemon=True)
        self._thread.start()

    def shutdown(self):
        self._stop = True
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self._trace and self._tstats:
            import sys

            total = sum(v[0] for k, v in self._tstats.items()
                        if k != "burst_steps")
            for k, (sec, n) in sorted(self._tstats.items(),
                                      key=lambda kv: -kv[1][0]):
                print(f"[engine-trace] {k:14s} {sec:8.2f}s n={n:<7d} "
                      f"avg={sec/max(n,1)*1e3:7.2f}ms", file=sys.stderr)
            print(f"[engine-trace] traced total {total:.2f}s", file=sys.stderr)
        # close every consumer: queued requests and still-active slots
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.out.put(StreamEvent(token_id=-1, text="", logprob=0.0,
                                    finish_reason="stop", error="engine shut down"))
            req.out.put(None)
        for i, s in enumerate(self.slots):
            if s is not None:
                self.slots[i] = None
                s.req.out.put(StreamEvent(token_id=-1, text="", logprob=0.0,
                                          finish_reason="stop", error="engine shut down"))
                s.req.out.put(None)

    def _reset_device_state(self):
        S = self.ecfg.num_slots
        V = self.cfg.vocab_size
        self.ck, self.cv = llama.init_cache(self.cfg, S, self.ecfg.max_context,
                                            self.ecfg.cache_dtype)
        self.dck = self.dcv = None   # re-ensured at the next spec admission
        self.ring, self.ring_pos = sampling.make_ring(S)
        self.bias = jnp.zeros((S, V), jnp.float32)
        self.rng_keys = jax.vmap(jax.random.key_data)(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(S, dtype=jnp.uint32))
        )
        self.lengths = np.zeros((S,), np.int32)
        self.cur_tokens = np.zeros((S,), np.int32)
        self.active_dev = np.zeros((S,), np.bool_)
        self._bias_dirty = np.zeros((S,), np.bool_)
        self.slot_params = sampling.make_slot_params(S)
        self.mu = sampling.make_mu(S)
        self._shard_state()
        self._cache_tokens = [[] for _ in range(S)]
        self._prefill_queue = []
        self._chain = None
        self._chain_dirty = True
        self._inflight = None
        self._pending_prefill = []
        self._fork_waiters = {}
        self._gbias_flush = set()

    def submit(self, req: GenRequest) -> "queue.Queue":
        self._queue.put(req)
        self._wake.set()
        return req.out

    def cancel(self, request_id: str):
        """Cancel a queued or running request (reference parity:
        TASK_TYPE_CANCEL, utils.hpp:53-56). The slot is released at the
        next step boundary; a None sentinel closes the output queue."""
        self._cancelled.add(request_id)
        self._wake.set()

    def generate(self, req: GenRequest) -> Iterator[StreamEvent]:
        """Synchronous streaming helper."""
        out = self.submit(req)
        while True:
            ev = out.get()
            if ev is None:
                return
            yield ev

    def generate_text(self, req: GenRequest) -> tuple[str, list[StreamEvent]]:
        events = list(self.generate(req))
        return "".join(e.text for e in events), events

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def metrics(self) -> dict:
        """Parity with the reference's GetMetrics RPC (grpc-server.cpp:2465)."""
        active = [s for s in self.slots if s is not None]
        tok_s = 0.0
        for s in active:
            dt = time.monotonic() - (s.t_first_token or s.t_start)
            if s.n_decoded and dt > 0:
                tok_s += s.n_decoded / dt
        return {
            "slots_total": self.ecfg.num_slots,
            "slots_active": len(active),
            "queued": self._queue.qsize(),
            "total_tokens_generated": self._total_tokens,
            "tokens_per_second_active": tok_s,
            "prompt_tokens_reused": self._reused_total,
            "uptime_s": time.monotonic() - self._load_time,
        }

    # ---------- grammar-constrained decoding ----------

    def _grammar_for(self, text: str):
        """Compile (cached) + lazily build the vocab mask builder.

        Prefers the native C++ runtime (runtime/grammar.cc via
        functions/grammars/native.py) — a cold mask walk over a 32k vocab
        costs hundreds of ms in the python automaton vs ~ms native; the
        python path remains the fallback (and the semantic reference)."""
        from localai_tpu.functions.grammars import native
        from localai_tpu.functions.grammars.automaton import (
            Grammar, TokenMaskBuilder, token_strings)

        use_native = native.get_lib() is not None
        if self._mask_builder is None:
            self._token_strs = token_strings(self.tokenizer)
            builder_cls = (native.NativeMaskBuilder if use_native
                           else TokenMaskBuilder)
            self._mask_builder = builder_cls(
                self._token_strs, self.eos_ids, self.cfg.vocab_size)
        g = self._grammar_cache.get(text)
        if g is None:
            if len(self._grammar_cache) > 64:
                self._grammar_cache.clear()
            cls = native.NativeGrammar if use_native else Grammar
            g = cls.from_text(text)
            self._grammar_cache[text] = g
        return g

    def _advance_grammar(self, slot: int, s: _Slot, token_id: int) -> bool:
        """Advance the slot's grammar by the emitted token. Returns False if
        the token is outside the grammar (the caller rolls the slot back).
        The device bias row is NOT written here — burst processing advances
        several states per slot and only the LAST one's mask matters for
        the next dispatch, so rows are flushed once per processed burst
        (_flush_grammar_bias)."""
        piece = (self._token_strs[token_id]
                 if 0 <= token_id < len(self._token_strs) else None)
        if piece is None:
            return False
        nxt = s.grammar.advance_string(s.gstate, piece)
        if nxt is None:
            return False
        s.gstate = nxt
        penalty = self._mask_builder.penalty_row(s.grammar, nxt)
        if penalty is not s.cur_penalty:  # memoized per state: identity == equality
            s.cur_penalty = penalty
            self._gbias_flush.add(slot)
        return True

    def _flush_grammar_bias(self):
        """Write the pending grammar-mask rows to the device bias — ONE
        batched scatter per processed burst, not one dispatch per slot
        (32 grammared slots × ~1-2 ms per .at[].set halved constrained
        throughput when flushed individually)."""
        slots = [i for i in self._gbias_flush
                 if self.slots[i] is not None
                 and self.slots[i].grammar is not None]
        self._gbias_flush.clear()
        if not slots:
            return
        # pad the batch to a power of two by REPEATING the first slot
        # (duplicate scatter writes are idempotent): each distinct batch
        # size is its own XLA executable, and 20-40s compiles for 30
        # different sizes would stall serving for minutes
        k = 1
        while k < len(slots):
            k *= 2
        padded = slots + [slots[0]] * (k - len(slots))
        rows = np.stack([self.slots[i].bias_base + self.slots[i].cur_penalty
                         for i in padded])
        self.bias = self.bias.at[np.asarray(padded, np.int32)].set(
            jnp.asarray(rows))
        for i in slots:
            self._bias_dirty[i] = True

    def _rollback_grammar(self, slot: int, s: _Slot) -> bool:
        """Discard an invalid speculative token: grammar slots ride full
        bursts masked by their LAST-FLUSHED state (one burst stale under
        pipelining), so a mid-burst token can fall outside the grammar.
        Recompute semantics make the rollback free — reset the slot's
        device length to the last valid row; stale rows are rewritten.
        Returns False (the _process_burst signal to skip the slot's
        remaining burst tokens)."""
        s.generated.pop()
        s.n_decoded -= 1
        self._total_tokens -= 1
        s.committed = min(s.committed, s.cache_len)
        self.lengths[slot] = s.cache_len
        toks = self._cache_tokens[slot]
        self.cur_tokens[slot] = toks[-1] if toks else 0
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, toks)
        # ensure the next dispatch carries this state's mask
        self._gbias_flush.add(slot)
        self._chain_dirty = True
        # the PIPELINED in-flight burst (dispatched before this rollback
        # was known) sampled its tokens conditioned on the discarded one —
        # drop this slot from it wholesale: neither its fold nor its
        # emission may touch the corrected mirrors (r3 review finding)
        if self._inflight is not None:
            self._inflight.skip_slots.add(slot)
        return False

    # ---------- engine loop ----------

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _pick_slot(self, ids: list) -> tuple:
        """Free slot with the longest cached common prefix (reference:
        grpc-server.cpp:1721-1835). Returns (slot, reusable_len) or (None, 0)."""
        best, best_key = None, None
        for i, s in enumerate(self.slots):
            if s is not None:
                continue
            common = 0
            for a, b in zip(self._cache_tokens[i], ids):
                if a != b:
                    break
                common += 1
            # prefer the longest common prefix; on ties (esp. common == 0)
            # evict the slot with the LEAST cached content so unrelated
            # requests don't destroy another conversation's reusable prefix
            key = (common, -len(self._cache_tokens[i]))
            if best_key is None or key > best_key:
                best, best_key = i, key
        if best is None:
            return None, 0
        # always leave >= 1 token to prefill so we have last-position logits
        return best, min(best_key[0], len(ids) - 1)

    def _run(self):
        import logging

        log = logging.getLogger(__name__)
        while not self._stop:
            try:
                t0 = time.monotonic()
                admitted = self._admit()
                self._tmark("admit", t0)
                t0 = time.monotonic()
                prefilled = self._prefill_step()
                self._tmark("prefill", t0)
                t0 = time.monotonic()
                finalized = self._maybe_finalize_prefill()
                self._tmark("finalize", t0)
                decoding = any(s is not None and s.phase == "decode"
                               for s in self.slots)
                if decoding:
                    eligible = self._spec_eligible()
                    others = any(
                        s is not None and s.phase == "decode"
                        and not eligible[i]
                        for i, s in enumerate(self.slots))
                    if eligible.any() and not others:
                        self._spec_once(eligible)
                    elif eligible.any():
                        # MIXED traffic: alternate spec rounds (eligible
                        # slots) with normal bursts (the rest) — r2
                        # disabled speculation fleet-wide the moment one
                        # sampled request was active
                        if self._spec_turn:
                            self._spec_once(eligible)
                        else:
                            t0 = time.monotonic()
                            self._decode_once(exclude=eligible)
                            self._tmark("decode_once", t0)
                        self._spec_turn = not self._spec_turn
                    else:
                        t0 = time.monotonic()
                        self._decode_once()
                        self._tmark("decode_once", t0)
                else:
                    if self._inflight is not None:
                        # every participant finished during processing of the
                        # prior burst; fold/drop the stale burst now so its
                        # tokens can never leak into a re-admitted slot
                        self._process_burst(self._inflight)
                        self._inflight = None
                    if self._pending_prefill:
                        # nothing else to run — block on the prefill result
                        t0 = time.monotonic()
                        self._maybe_finalize_prefill(block=True)
                        self._tmark("finalize_block", t0)
                    elif not (admitted or prefilled or finalized):
                        self._wake.wait(timeout=0.05)
                        self._wake.clear()
            except Exception as e:  # never let the loop die: fail active requests
                log.exception("engine step failed")
                for i, s in enumerate(self.slots):
                    if s is not None:
                        s.req.out.put(StreamEvent(
                            token_id=-1, text="", logprob=0.0,
                            finish_reason="stop", error=f"{type(e).__name__}: {e}",
                        ))
                        s.req.out.put(None)
                        self._release_slot(i)
                # a failure inside a donated jitted call leaves ck/cv/ring/
                # keys pointing at deleted buffers — reinitialize device state
                # so the engine survives instead of erroring forever
                try:
                    self._reset_device_state()
                except Exception:
                    log.exception("device state reset failed; engine unusable")
                    self._stop = True

    def _admission_ready(self) -> bool:
        """Hold admissions briefly so batched prefill groups can form:
        completions arrive a few per decode burst, and admitting each
        singleton immediately costs a ~140ms prefill dispatch for one
        prompt. Admit when the queue can fill a decent group, when the
        engine is otherwise idle, or when the oldest wait exceeds one
        burst's latency."""
        if self._queue.empty() or self._free_count() == 0:
            return False
        qn = self._queue.qsize()
        if qn >= min(self._final_pad // 2, self._free_count()):
            return True
        n_decoding = sum(1 for s in self.slots
                         if s is not None and s.phase == "decode")
        if n_decoding < self.ecfg.num_slots // 2:
            return True  # light load: completions won't clump; admit now
        now = time.monotonic()
        oldest = getattr(self, "_oldest_queued_t", None)
        return oldest is not None and (now - oldest) > 0.35

    def _admit(self) -> bool:
        self._reap_cancelled()
        if not self._queue.empty() and getattr(self, "_oldest_queued_t", None) is None:
            self._oldest_queued_t = time.monotonic()
        if not self._admission_ready():
            return False
        self._oldest_queued_t = None
        admitted = False
        batch: list[GenRequest] = []
        while not self._queue.empty() and self._free_count() > len(batch):
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        # identical prompts admitted together prefill ONCE: the first
        # becomes the leader; the rest fork its KV rows on commit
        # (VERDICT r2 #5 — true shared-prefix for n>1)
        leaders: dict = {}
        for req in batch:
            if req.request_id in self._cancelled:
                self._cancelled.discard(req.request_id)
                req.out.put(None)
                continue
            key = None
            if not req.grammar and req.mm_vectors is None:
                # truncation depends on max_new_tokens; bucket it into the key
                key = (tuple(req.prompt_ids),
                       min(req.max_new_tokens, self.ecfg.max_context // 4))
            try:
                if key is not None and key in leaders:
                    lslot, lsnap, lids = leaders[key]
                    self._start_fork_sibling(req, lslot, lsnap, lids)
                else:
                    slot, ids, snap = self._start_request(req)
                    if key is not None and snap.mm_pos is None:
                        leaders[key] = (slot, snap, ids)
                admitted = True
            except Exception as e:
                import logging

                logging.getLogger(__name__).exception("admission failed")
                req.out.put(StreamEvent(
                    token_id=-1, text="", logprob=0.0, finish_reason="stop",
                    error=f"{type(e).__name__}: {e}",
                ))
                req.out.put(None)
        return admitted

    def _free_count(self) -> int:
        return sum(1 for s in self.slots if s is None)

    def _reap_cancelled(self):
        if not self._cancelled:
            return
        for i, s in enumerate(self.slots):
            if s is not None and s.req.request_id in self._cancelled:
                self._cancelled.discard(s.req.request_id)
                self._release_slot(i)
                s.req.out.put(None)
                # a cancelled LEADER must not strand fork-waiting siblings
                self._process_fork_waiters(i)

    def _start_request(self, req: GenRequest):
        """Admit a request: install sampling state and queue its prompt for
        chunked prefill. No model compute happens here."""
        C = self.ecfg.max_context
        ids = list(req.prompt_ids)
        # truncate the prompt head, keeping the tail (reference semantics:
        # grpc-server.cpp prompt truncation keeps the last part of the prompt)
        max_prompt = C - 1 - min(req.max_new_tokens, C // 4)
        shift = 0
        if len(ids) > max_prompt:
            shift = len(ids) - max_prompt
            ids = ids[-max_prompt:]
        if not ids:
            ids = [getattr(self.tokenizer, "eos_token_id", 0) or 0]

        mm_pos = mm_vec = None
        if req.mm_vectors is not None and len(req.mm_positions):
            pos = np.asarray(req.mm_positions, np.int64) - shift
            keep = (pos >= 0) & (pos < len(ids))
            pos = pos[keep]
            vec = np.asarray(req.mm_vectors, np.float32)[keep]
            pb = 16
            while pb < len(pos):
                pb *= 2
            # sentinel >= any bucket so the injection scatter DROPS pads
            # (negative sentinels would wrap to the last column)
            mm_pos = np.full((pb,), 1 << 30, np.int64)
            mm_pos[: len(pos)] = pos
            mm_vec = np.zeros((pb, self.cfg.hidden_size), np.float32)
            mm_vec[: len(pos)] = vec

        slot, common = self._pick_slot(ids)
        assert slot is not None, "_start_request called with no free slot"
        # a short accidental prefix match (e.g. two prompts sharing a BOS or
        # first word) is not worth the slow path it forces: continued
        # prefills run singly while fresh finals batch 8 per dispatch.
        # Reuse only prefixes long enough to beat that cost (real multi-turn
        # chats share hundreds of system/history tokens). Multimodal prompts
        # never reuse (their cache rows hold image embeddings, not tokens).
        if common < 16 or mm_pos is not None:
            common = 0
        if mm_pos is None:
            common = self._restore_prompt_cache(slot, req, ids, common)

        # install sampling state for the slot
        self.slot_params = sampling.set_slot(self.slot_params, slot, req.params)
        # mirostat v2 initializes mu at 2*tau (llama.cpp semantics)
        tau = req.params.mirostat_tau if req.params.mirostat_tau > 0 else 5.0
        self.mu[slot] = 2.0 * tau
        self.rng_keys = sampling.seed_slot_key(
            self.rng_keys, slot, req.params, fallback_seed=hash(req.request_id) & 0x7FFFFFFF
        )
        grammar = gstate = bias_base = penalty0 = None
        if req.grammar:
            grammar = self._grammar_for(req.grammar)
            gstate = grammar.initial_state()
            bias_base = np.zeros((self.cfg.vocab_size,), np.float32)
            for tok, b in (req.params.logit_bias or {}).items():
                t = int(tok)
                if 0 <= t < bias_base.shape[0]:
                    bias_base[t] = float(b)
            penalty0 = self._mask_builder.penalty_row(grammar, gstate)
            self.bias = self.bias.at[slot].set(jnp.asarray(bias_base + penalty0))
            self._bias_dirty[slot] = True
        elif req.params.logit_bias:
            self.bias = sampling.set_slot_logit_bias(self.bias, slot, req.params)
            self._bias_dirty[slot] = True
        elif self._bias_dirty[slot]:
            # clear a previous request's grammar mask / bias row; skipping
            # the device write for never-biased slots keeps admission free of
            # dispatches in the common case
            self.bias = self.bias.at[slot].set(0.0)
            self._bias_dirty[slot] = False

        # penalty ring covers the prompt tail (llama.cpp last-n semantics
        # include prompt tokens); reused prefixes are part of the prompt
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, ids)
        if common:
            self._reused_total += common

        s = _Slot(req, IncrementalDetokenizer(self.tokenizer), len(ids))
        s.grammar, s.gstate, s.bias_base = grammar, gstate, bias_base
        s.cur_penalty = penalty0
        s.mm_pos, s.mm_vec = mm_pos, mm_vec
        # per-SLOT speculation eligibility (r3; r2 was fleet-wide). Gates:
        #   * greedy, ungrammared, no logit_bias and no penalties — the
        #     spec verify accepts via raw argmax (speculative.py), so any
        #     logit shaping would silently diverge from the burst sampler;
        #   * no reused prefix (common == 0) — reused/restored rows exist
        #     only in the MAIN cache; the draft would attend over zeros
        #     for the prefix and every proposal would be garbage.
        sp = req.params
        s.spec_ok = (self.draft_params is not None and self.ecfg.n_draft > 0
                     and sp.temperature <= 0 and not req.grammar
                     and mm_pos is None and common == 0
                     and not sp.logit_bias
                     and sp.repeat_penalty in (0.0, 1.0)
                     and sp.presence_penalty == 0.0
                     and sp.frequency_penalty == 0.0)
        if s.spec_ok:
            self._ensure_draft_cache()
        s.pending = ids[common:]
        s.written = common
        s.reused = common
        # multimodal rows are image embeddings, not token embeddings — a
        # later text request must never "reuse" them as a token prefix
        self._cache_tokens[slot] = [] if mm_pos is not None else list(ids)
        self.slots[slot] = s
        self._prefill_queue.append(slot)
        return slot, ids, s

    def _start_fork_sibling(self, req: GenRequest, leader_slot: int,
                            leader_snap: "_Slot", ids: list):
        """Admit a request whose prompt is IDENTICAL to an in-flight
        leader's: install sampling state but prefill nothing — when the
        leader's prefill commits, its KV rows are forked to this slot and
        only the last prompt token is re-prefilled (for this slot's own
        first-token sampling). True shared-prefix for n>1 / simultaneous
        identical prompts (VERDICT r2 #5)."""
        slot, _ = self._pick_slot(ids)
        assert slot is not None
        self.slot_params = sampling.set_slot(self.slot_params, slot, req.params)
        tau = req.params.mirostat_tau if req.params.mirostat_tau > 0 else 5.0
        self.mu[slot] = 2.0 * tau
        self.rng_keys = sampling.seed_slot_key(
            self.rng_keys, slot, req.params,
            fallback_seed=hash(req.request_id) & 0x7FFFFFFF)
        if req.params.logit_bias:
            self.bias = sampling.set_slot_logit_bias(self.bias, slot, req.params)
            self._bias_dirty[slot] = True
        elif self._bias_dirty[slot]:
            self.bias = self.bias.at[slot].set(0.0)
            self._bias_dirty[slot] = False
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, ids)
        s = _Slot(req, IncrementalDetokenizer(self.tokenizer), len(ids))
        s.phase = "fork_wait"
        s.pending = []
        self._cache_tokens[slot] = []
        self.slots[slot] = s
        self._fork_waiters.setdefault(leader_slot, []).append(
            (slot, s, leader_snap, ids))

    def _get_fork_fn(self, shape_key):
        fn = self._fork_fns.get(shape_key)
        if fn is None:
            def body(ck, cv, src, dst, n):
                C = ck.shape[2]
                mask = (jnp.arange(C, dtype=jnp.int32) < n)[None, :, None, None]
                nk = jnp.where(mask, ck[:, src], ck[:, dst])
                nv = jnp.where(mask, cv[:, src], cv[:, dst])
                return ck.at[:, dst].set(nk), cv.at[:, dst].set(nv)

            fn = jax.jit(body, donate_argnums=(0, 1))
            self._fork_fns[shape_key] = fn
        return fn

    def _process_fork_waiters(self, leader_slot: int):
        """Called when a leader's final prefill resolves: fork its committed
        rows to each waiting sibling and queue their 1-token finals. A
        vanished/failed leader downgrades siblings to full prefills."""
        waiters = self._fork_waiters.pop(leader_slot, None)
        if not waiters:
            return
        for sib, s, lsnap, ids in waiters:
            if self.slots[sib] is not s:
                continue  # sibling cancelled while waiting
            leader_ok = (self.slots[leader_slot] is lsnap
                         and lsnap.phase == "decode"
                         and self._cache_tokens[leader_slot][:len(ids)] == ids)
            if leader_ok and len(ids) > 1:
                n = len(ids) - 1
                self.ck, self.cv = self._get_fork_fn("main")(
                    self.ck, self.cv, leader_slot, sib, n)
                # a sibling inherits spec eligibility only when the leader's
                # draft rows exist to fork and its own request qualifies
                # under the same admission gates (see _start_request)
                sp = s.req.params
                s.spec_ok = (lsnap.spec_ok and self.dck is not None
                             and sp.temperature <= 0 and not s.req.grammar
                             and not sp.logit_bias
                             and sp.repeat_penalty in (0.0, 1.0)
                             and sp.presence_penalty == 0.0
                             and sp.frequency_penalty == 0.0)
                if self.dck is not None and lsnap.spec_ok:
                    self.dck, self.dcv = self._get_fork_fn("draft")(
                        self.dck, self.dcv, leader_slot, sib, n)
                s.pending = [ids[-1]]
                s.written = n
                s.committed = n
                s.reused = n
                self._reused_total += n
                self._cache_tokens[sib] = list(ids[:-1])
            else:
                # leader gone or 1-token prompt: plain full prefill
                s.pending = list(ids)
                s.written = 0
                self._cache_tokens[sib] = list(ids)
            s.phase = "prefill"
            self._prefill_queue.append(sib)

    # ---------- prompt-cache persistence ----------

    def _get_restore_fn(self):
        fn = self._fork_fns.get("restore")
        if fn is None:
            def body(ck, cv, kfull, vfull, slot, n):
                C = ck.shape[2]
                mask = (jnp.arange(C, dtype=jnp.int32) < n)[None, :, None, None]
                nk = jnp.where(mask, kfull.astype(ck.dtype), ck[:, slot])
                nv = jnp.where(mask, vfull.astype(cv.dtype), cv[:, slot])
                return ck.at[:, slot].set(nk), cv.at[:, slot].set(nv)

            fn = jax.jit(body, donate_argnums=(0, 1))
            self._fork_fns["restore"] = fn
        return fn

    def _restore_prompt_cache(self, slot: int, req: GenRequest, ids: list,
                              common: int) -> int:
        """If the request names a prompt-cache file whose saved prefix beats
        the slot's own cached prefix, upload those KV rows and return the
        new reusable length (reference: prompt_cache_path restore,
        options.go:182-191)."""
        path = req.prompt_cache_path
        if not path or not os.path.exists(path):
            return common
        try:
            data = np.load(path)
            ctoks = data["tokens"].tolist()
        except Exception:
            log_ = __import__("logging").getLogger(__name__)
            log_.exception("unreadable prompt cache %s", path)
            return common
        m = 0
        for a, b in zip(ctoks, ids):
            if a != b:
                break
            m += 1
        m = min(m, len(ids) - 1, self.ecfg.max_context - 1)
        if m <= common or m < 16:
            return common
        L, _, C, KV, hd = self.ck.shape
        # float16 staging (matches the file; halves the host alloc +
        # host->device transfer vs float32 — this runs on the engine loop)
        kfull = np.zeros((L, C, KV, hd), np.float16)
        vfull = np.zeros((L, C, KV, hd), np.float16)
        kfull[:, :m] = data["k"][:, :m]
        vfull[:, :m] = data["v"][:, :m]
        self.ck, self.cv = self._get_restore_fn()(
            self.ck, self.cv, kfull, vfull, slot, m)
        return m

    def _save_prompt_cache(self, slot: int, s: "_Slot"):
        """Persist the slot's committed rows + tokens on finish."""
        req = s.req
        if not req.prompt_cache_path or req.prompt_cache_ro:
            return
        n = s.committed if req.prompt_cache_all else min(s.prompt_len,
                                                         s.committed)
        tokens = self._cache_tokens[slot][:n]
        n = min(n, len(tokens))
        if n < 16:
            return  # below the reuse threshold; not worth the file
        try:
            # slice on DEVICE now (the backing ck/cv buffers get donated to
            # the next dispatch; an independent slice survives that), at a
            # power-of-two length so only log2(C) slice programs compile.
            # The expensive device->host sync + disk write runs on a
            # background thread, off the serving loop (r3 review finding).
            n2 = 1
            while n2 < n:
                n2 *= 2
            n2 = min(n2, self.ecfg.max_context)
            k_dev = self.ck[:, slot, :n2]
            v_dev = self.cv[:, slot, :n2]
            path = req.prompt_cache_path
            toks = np.asarray(tokens[:n], np.int32)

            def write():
                try:
                    k = np.asarray(k_dev)[:, :n].astype(np.float16)
                    v = np.asarray(v_dev)[:, :n].astype(np.float16)
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        np.savez(f, tokens=toks, k=k, v=v)
                    os.replace(tmp, path)
                except Exception:
                    __import__("logging").getLogger(__name__).exception(
                        "prompt cache save failed: %s", path)

            threading.Thread(target=write, daemon=True,
                             name="prompt-cache-save").start()
        except Exception:
            __import__("logging").getLogger(__name__).exception(
                "prompt cache save failed: %s", req.prompt_cache_path)

    def _prefill_plan(self, slot: int):
        """(final, take, bucket, continued) for a slot's next chunk."""
        s = self.slots[slot]
        chunk = self._chunk
        remaining = len(s.pending)
        final = remaining <= chunk
        take = remaining if final else chunk
        bucket = self._bucket_for(take) if final else chunk
        return final, take, bucket, s.written > 0

    def _prefill_step(self) -> bool:
        """Process the next prompt chunk(s).

        Fresh FINAL chunks sharing a bucket are batched into ONE dispatch of
        up to _final_pad prompts (padded by repeating the last entry) — the
        reference packs all prompt chunks into one llama_batch
        (grpc-server.cpp:1671+); per-prompt dispatches cost ~150ms of
        overhead each on the serving tunnel. Long-prompt (chunked) and
        continued (prefix-reuse) prefills go singly. Up to TWO final
        groups are in flight at a time (see _maybe_finalize_prefill).
        """
        if len(self._pending_prefill) >= 2:
            return False
        while self._prefill_queue:
            slot = self._prefill_queue[0]
            s = self.slots[slot]
            if s is None or s.phase != "prefill":
                self._prefill_queue.pop(0)  # cancelled/stale entry
                continue
            break
        else:
            return False

        final, take, bucket, continued = self._prefill_plan(slot)

        def mm_rel(mm_pos, start, take, bucket):
            """Chunk-relative injection positions (pads -> OOB sentinel)."""
            rel = np.where((mm_pos >= start) & (mm_pos < start + take),
                           mm_pos - start, 1 << 30)
            return rel.astype(np.int32)[None]

        t0 = time.monotonic()
        if not final:
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :take] = s.pending[:take]
            args = (self.params, tokens, np.array([take], np.int32), self.ck,
                    self.cv, np.array([slot], np.int32),
                    np.array([s.written], np.int32))
            if s.mm_pos is not None:
                fn = self._get_mm_chunk_fn(bucket, len(s.mm_pos))
                args = args + (mm_rel(s.mm_pos, s.written, take, bucket),
                               s.mm_vec[None])
            else:
                fn = self._get_chunk_fn(bucket)
            self.ck, self.cv = fn(*args)
            if self.dck is not None and s.spec_ok:
                # mirror the prompt into the draft cache (speculative
                # rounds need the same context; see engine/speculative.py)
                self.dck, self.dcv = self._get_draft_chunk_fn(bucket)(
                    self.draft_params, tokens, np.array([take], np.int32),
                    self.dck, self.dcv, np.array([slot], np.int32),
                    np.array([s.written], np.int32))
            s.pending = s.pending[take:]
            s.written += take
            s.committed = s.written
            s.t_prefill_ms += (time.monotonic() - t0) * 1e3
            return True

        # collect a batch of fresh finals with the same bucket (queue order);
        # multimodal finals go singly (their injection shapes are per-request)
        group = [(slot, take)]
        if not continued and s.mm_pos is None:
            for other in self._prefill_queue[1:]:
                if len(group) >= self._final_pad:
                    break
                so = self.slots[other]
                if so is None or so.phase != "prefill" or so.mm_pos is not None:
                    continue
                of, ot, ob, oc = self._prefill_plan(other)
                if of and not oc and ob == bucket:
                    group.append((other, ot))
        B = 1 if len(group) == 1 else self._final_pad

        tokens = np.zeros((B, bucket), np.int32)
        seq_len = np.ones((B,), np.int32)
        slots_v = np.zeros((B,), np.int32)
        start_v = np.zeros((B,), np.int32)
        for b in range(B):
            gslot, gtake = group[min(b, len(group) - 1)]  # pad = repeat last
            gs = self.slots[gslot]
            tokens[b, :gtake] = gs.pending[:gtake]
            seq_len[b] = gtake
            slots_v[b] = gslot
            start_v[b] = gs.written

        # ring/ring_pos/slot_params copied: see the aliasing note in
        # _decode_once (in-flight dispatches must not see host mutations)
        args = (self.params, tokens, seq_len, self.ck, self.cv, slots_v, start_v,
                self.ring.copy(), self.ring_pos.copy(), self.bias, self.rng_keys,
                jax.tree.map(np.array, self.slot_params), self.mu.copy())
        if s.mm_pos is not None:
            fn = self._get_mm_final_fn(bucket, len(s.mm_pos), continued)
            args = args + (mm_rel(s.mm_pos, start_v[0], take, bucket),
                           s.mm_vec[None])
        else:
            fn = self._get_final_fn(bucket, B, continued)
        out_ids, logprobs, self.ck, self.cv, self.rng_keys, mu_out = fn(*args)
        if self.dck is not None and any(
                self.slots[g].spec_ok for g, _ in group):
            # draft ingests the same prompt rows (no sampling needed);
            # padded/ineligible rows are harmless duplicates
            self.dck, self.dcv = self._get_draft_chunk_fn(bucket)(
                self.draft_params, tokens, seq_len, self.dck, self.dcv,
                slots_v, start_v)
        # ASYNC: don't sync here — the result would be serialized behind any
        # in-flight decode burst, idling the device. The group's slots stay
        # in "prefill" phase (and out of decode bursts) until the sampled
        # first tokens arrive; _maybe_finalize_prefill polls readiness each
        # loop iteration. Bookkeeping (pending/written) is advanced NOW so a
        # second dispatch can't double-prefill the same slots.
        for gslot, gtake in group:
            gs = self.slots[gslot]
            gs.pending = []
            gs.written += gtake
            if gslot in self._prefill_queue:
                self._prefill_queue.remove(gslot)
        self._pending_prefill.append((
            [(gslot, self.slots[gslot]) for gslot, _ in group],
            out_ids, logprobs, mu_out, t0))
        return True

    def _maybe_finalize_prefill(self, block: bool = False) -> bool:
        """Activate the oldest dispatched final-prefill group once its first
        tokens are available (or immediately when ``block``)."""
        if not self._pending_prefill:
            return False
        group, out_ids, logprobs, mu_out, t0 = self._pending_prefill[0]
        tr = time.monotonic()
        ready = out_ids.is_ready()
        self._tmark("finalize_poll", tr)
        if not block and not ready:
            return False
        self._pending_prefill.pop(0)
        tr = time.monotonic()
        ids_np = np.asarray(out_ids)
        lps_np = np.asarray(logprobs)
        mu_np = np.asarray(mu_out)
        self._tmark("finalize_sync", tr)
        # scatter ONLY the group's mu entries — and only where the slot
        # still belongs to the dispatched request: a cancel + re-admit while
        # the prefill was in flight must not inherit the stale mu
        for gslot, snap in group:
            if self.slots[gslot] is snap:
                self.mu[gslot] = mu_np[gslot]
        t1 = time.monotonic()

        for b, (gslot, snap) in enumerate(group):
            gs = self.slots[gslot]
            if gs is not snap:
                continue  # cancelled while the prefill was in flight
            first_id = int(ids_np[b])
            gs.cache_len = gs.written
            gs.committed = gs.written
            gs.phase = "decode"

            self.lengths[gslot] = gs.written
            self.cur_tokens[gslot] = first_id
            self.active_dev[gslot] = True
            self._chain_dirty = True
            # mirror the sampled token into the penalty ring
            self.ring[gslot, self.ring_pos[gslot] % sampling.RING_N] = first_id
            self.ring_pos[gslot] += 1

            gs.t_prefill_ms += (t1 - t0) * 1e3
            if gs.t_first_token == 0.0:
                gs.t_first_token = t1
            self._emit_token(gslot, first_id, float(lps_np[b]))
        # leaders just committed: fork their rows to any waiting siblings
        # (vanished leaders downgrade the siblings to full prefills)
        for gslot, _snap in group:
            self._process_fork_waiters(gslot)
        self._flush_grammar_bias()
        return True

    def _pick_burst(self) -> int:
        """Burst length for this dispatch: a power of two <= decode_burst,
        clamped so no slot crosses its context-shift threshold mid-burst
        (tokens past the threshold would be silently position-less).
        Grammar-constrained slots ride FULL bursts speculatively: tokens
        are verified against the automaton at processing time and the slot
        rolls back (free — recompute semantics) on the first invalid one
        (r3; replaces the r2 design that forced burst=1 fleet-wide).
        Slots that finish mid-burst (EOS/stop/budget) simply ride out the
        burst; their tail tokens are discarded host-side — cheaper than
        clamping every slot to the smallest remaining budget. Host mirrors
        lag by any in-flight (pipelined) burst, so its steps count against
        the capacity clamp too."""
        cap = self.ecfg.decode_burst
        budget = 1
        infl = self._inflight
        inflight_k = infl.n_steps if infl is not None else 0
        inflight_slots = {i for i, _ in infl.slots} if infl is not None else ()
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode":
                continue
            used = s.cache_len + (inflight_k if i in inflight_slots else 0)
            cap = min(cap, max(1, self.ecfg.max_context - 2 - used))
            budget = max(budget, s.req.max_new_tokens - s.n_decoded)
        cap = min(cap, budget)
        k = 1
        while k * 2 <= cap:
            k *= 2
        return k

    def _ensure_draft_cache(self):
        if self.dck is None and self.draft_cfg is not None:
            self.dck, self.dcv = llama.init_cache(
                self.draft_cfg, self.ecfg.num_slots, self.ecfg.max_context,
                self.ecfg.cache_dtype)

    def _get_spec_fn(self):
        if self._spec_fn is None:
            from localai_tpu.engine import speculative

            D = self.ecfg.n_draft
            self._spec_fn = jax.jit(
                lambda *a: speculative.spec_round(
                    *a[:2], self.cfg, self.draft_cfg, *a[2:], n_draft=D),
                donate_argnums=(4, 5, 6, 7))
        return self._spec_fn

    def _spec_eligible(self) -> "np.ndarray":
        """Per-SLOT speculation mask (r3; the r2 design was all-or-nothing
        across the fleet): a slot joins spec rounds iff it admitted as
        spec_ok (greedy, ungrammared, draft-mirrored prompt) and has D+1
        rows of headroom."""
        S = self.ecfg.num_slots
        mask = np.zeros((S,), np.bool_)
        if self.dck is None or self.ecfg.n_draft <= 0:
            return mask
        D = self.ecfg.n_draft
        for i, s in enumerate(self.slots):
            if (s is not None and s.phase == "decode" and s.spec_ok
                    and self.ecfg.max_context - 2 - s.cache_len >= D + 1):
                mask[i] = True
        return mask

    def _spec_once(self, eligible: "np.ndarray"):
        """One speculative round for the ELIGIBLE slots only (no
        pipelining: rounds advance lengths per-slot, so the burst chain is
        not reusable)."""
        if self._inflight is not None:
            self._process_burst(self._inflight)
            self._inflight = None
        fn = self._get_spec_fn()
        burst_slots = [(i, s) for i, s in enumerate(self.slots)
                       if s is not None and s.phase == "decode"
                       and eligible[i]]
        out, out_lp, n_out, self.ck, self.cv, self.dck, self.dcv, _ = fn(
            self.params, self.draft_params, self.cur_tokens.copy(),
            self.lengths.copy(), self.ck, self.cv, self.dck, self.dcv,
            self.active_dev.copy() & eligible)
        out_np = np.asarray(out)
        lp_np = np.asarray(out_lp)
        n_np = np.asarray(n_out)
        self._chain = None
        self._chain_dirty = True
        for i, snap in burst_slots:
            if not self._live(i, snap):
                continue
            n = int(n_np[i])
            if n <= 0:
                continue
            self.cur_tokens[i] = out_np[i, n - 1]
            self.lengths[i] += n
            for j in range(n):
                tok = int(out_np[i, j])
                self.ring[i, self.ring_pos[i] % sampling.RING_N] = tok
                self.ring_pos[i] += 1
            for j in range(n):
                if not self._live(i, snap):
                    break
                snap.committed = min(snap.committed + 1, snap.cache_len)
                self._emit_token(i, int(out_np[i, j]), float(lp_np[i, j]))

    def _decode_once(self, exclude: Optional["np.ndarray"] = None):
        """Dispatch one decode burst, PIPELINED: the previous burst's host
        processing (sync, detok, stop-scan, queue puts) happens while this
        burst runs on the device. Burst-to-burst state (tokens/lengths/ring)
        chains device-side; whenever host events (admission, release,
        context shift) invalidate the chain, the burst is fed from the host
        mirrors instead — which requires the previous burst's results to be
        folded into the mirrors first. ``exclude`` masks out slots that are
        advancing through spec rounds instead (mixed-traffic alternation)."""
        active = self.active_dev.copy()
        if exclude is not None:
            active &= ~exclude
        key = active.tobytes()
        if key != getattr(self, "_last_active_key", None):
            self._chain_dirty = True
            self._last_active_key = key
        if self._inflight is not None and self._chain_dirty:
            # dispatching from mirrors requires the previous burst
            # folded in first — but only the FOLD (sync + mirror
            # arithmetic, ~1ms); the expensive emission still overlaps
            # the next burst below. (Grammar slots no longer force a sync
            # here: their tokens are VERIFIED at processing time and the
            # slot rolls back on the first invalid one, so a stale mask
            # costs throughput on that slot only, never correctness.)
            self._fold_burst(self._inflight)
        n_steps = self._pick_burst()
        f = sampling.feature_flags(self.slot_params, self.active_dev)
        flags = (f["use_penalties"], f["use_typical"], f["use_mirostat"])
        if any(flags) and flags != (True, True, True):
            # only the two precompiled variants exist; mixed feature sets
            # use the full sampler rather than compiling mid-request
            flags = (True, True, True)
        fn = self._get_burst_fn(n_steps, flags)
        t_d = time.monotonic()
        if self._chain_dirty or self._chain is None:
            # DEFENSIVE COPIES: jax may zero-copy alias numpy arguments
            # (observed on the CPU client) — an in-flight dispatch holding
            # the live mirror arrays would see later in-place host mutations
            # (admission/finalize/release) and e.g. decode an activating
            # slot with lengths still 0, clobbering its prefilled KV rows
            tokens, lengths, ring, rpos, mu = (self.cur_tokens.copy(),
                                               self.lengths.copy(),
                                               self.ring.copy(),
                                               self.ring_pos.copy(),
                                               self.mu.copy())
        else:
            tokens, lengths, ring, rpos, mu = self._chain
        # snapshot the PARTICIPATING SLOT OBJECTS: a slot index may be
        # released and re-admitted while this burst is in flight, and the
        # new occupant must never receive the stale burst's tokens
        burst_slots = [(i, s) for i, s in enumerate(self.slots)
                       if s is not None and s.phase == "decode"
                       and (exclude is None or not exclude[i])]
        ids_all, lps_all, self.ck, self.cv, self.rng_keys, self._chain = fn(
            self.params, tokens, self.ck, self.cv, lengths,
            ring, rpos, self.bias, self.rng_keys,
            jax.tree.map(np.array, self.slot_params),
            active, mu,
        )
        self._chain_dirty = False
        self._tmark("dispatch", t_d)
        if self._trace:
            s = self._tstats.setdefault("burst_steps", [0.0, 0])
            s[0] += n_steps
            s[1] += 1
        prev, self._inflight = self._inflight, _Burst(n_steps, burst_slots,
                                                      ids_all, lps_all,
                                                      self._chain[4])
        if prev is not None:
            t0 = time.monotonic()
            self._process_burst(prev)
            self._tmark("process_prev", t0)

    def _live(self, i, snap):
        return self.slots[i] is snap and snap.phase == "decode"

    def _fold_burst(self, b: "_Burst"):
        """Sync a burst's ids and fold the device-side state evolution into
        the host mirrors. Cheap (~1ms past the device sync) and idempotent;
        emission is separate so it can overlap the NEXT dispatch."""
        if b.folded:
            return
        t0 = time.monotonic()
        b.ids_np = np.asarray(b.ids_all)    # [K, S]
        self._tmark("burst_sync", t0)
        b.lps_np = np.asarray(b.lps_all)
        mu_np = np.asarray(b.mu_out)
        live_idx = [i for i, snap in b.slots
                    if self._live(i, snap) and i not in b.skip_slots]
        for i in live_idx:
            self.mu[i] = mu_np[i]
        for i in live_idx:
            self.cur_tokens[i] = b.ids_np[-1, i]
            self.lengths[i] += b.n_steps
        sampling.host_update_ring(self.ring, self.ring_pos, b.ids_np, live_idx)
        b.folded = True

    def _process_burst(self, b: "_Burst"):
        """Fold (if not already) then emit a burst's tokens (emission may
        release slots or trigger context shifts — both mark the device
        chain dirty). Per-slot events are COALESCED into one queue put per
        burst (see StreamEvent.token_ids)."""
        self._fold_burst(b)
        t0 = time.monotonic()
        self._sink_buf = {}
        rolled: set = set()   # grammar slots rolled back mid-burst
        try:
            for j in range(b.n_steps):
                for i, snap in b.slots:
                    if i in rolled or i in b.skip_slots \
                            or not self._live(i, snap):
                        continue  # finished/shifted/replaced/rolled-back
                    # the step just wrote this slot's previous token's KV row
                    snap.committed = min(snap.committed + 1, snap.cache_len)
                    if not self._emit_token(i, int(b.ids_np[j, i]),
                                            float(b.lps_np[j, i])):
                        rolled.add(i)
        finally:
            buf, self._sink_buf = self._sink_buf, None
            self._tmark("emit_loop", t0)
            self._flush_grammar_bias()
            t0 = time.monotonic()
            for (_slot, out), evs in buf.items():
                out.put(evs[0] if len(evs) == 1 else _merge_events(evs))
            self._tmark("emit_flush", t0)

    def _emit_token(self, slot: int, token_id: int, logprob: float) -> bool:
        """Emit one token for a slot. Returns False when the token was a
        grammar-invalid speculative sample and the slot rolled back (the
        slot's remaining tokens in the current burst must be skipped)."""
        s = self.slots[slot]
        s.generated.append(token_id)
        s.n_decoded += 1
        self._total_tokens += 1
        finish = None
        shifted = False

        if token_id in self.eos_ids and not (s.req.ignore_eos and s.grammar is None):
            if s.grammar is not None and s.cur_penalty is not None \
                    and s.cur_penalty[token_id] != 0.0:
                # speculative EOS sampled under a STALE mask while the
                # grammar cannot terminate yet — discard and resume
                return self._rollback_grammar(slot, s)
            finish = "stop"
            delta = s.held_text + s.detok.flush()
        elif s.grammar is not None and not self._advance_grammar(slot, s, token_id):
            # speculative token fell outside the grammar (stale mask mid-
            # burst) — roll back instead of emitting invalid output
            return self._rollback_grammar(slot, s)
        elif s.n_decoded >= s.req.max_new_tokens:
            finish = "length"
            delta = s.held_text + s.detok.push(token_id) + s.detok.flush()
        elif s.cache_len + 1 >= self.ecfg.max_context - 1:
            if self.ecfg.context_shift:
                delta = s.held_text + s.detok.push(token_id)
                s.held_text = ""
                # stop sequences still apply at the shift-trigger token —
                # a completing stop must finish, not leak past the shift
                if s.req.stop_sequences:
                    cut = self._check_stops(s, delta)
                    if cut is not None:
                        delta, finish = cut, "stop"
                    elif delta:
                        delta, s.held_text = self._holdback(s, delta)
                if finish is None:
                    self._context_shift(slot, s, token_id)
                    shifted = True
            else:
                finish = "length"
                delta = s.held_text + s.detok.push(token_id) + s.detok.flush()
        else:
            delta = s.held_text + s.detok.push(token_id)
            s.held_text = ""
            # stop-sequence handling with partial-match holdback
            if s.req.stop_sequences:
                cut = self._check_stops(s, delta)
                if cut is not None:
                    delta, finish = cut, "stop"
                elif delta:
                    delta, s.held_text = self._holdback(s, delta)

        if finish is None and not shifted:
            # this token's KV is written by the next decode step
            self._cache_tokens[slot].append(token_id)
            s.cache_len += 1

        ev = StreamEvent(
            token_id=token_id, text=delta, logprob=logprob,
            finish_reason=finish,
            prompt_tokens=s.prompt_len, completion_tokens=s.n_decoded,
        )
        buf = self._sink_buf
        if finish:
            dt = time.monotonic() - s.t_first_token
            ev.timings = {
                "prefill_ms": s.t_prefill_ms,
                "reused_prompt_tokens": s.reused,
                "decode_tokens_per_s": (s.n_decoded - 1) / dt if dt > 0 and s.n_decoded > 1 else 0.0,
            }
            self._save_prompt_cache(slot, s)
            self._release_slot(slot)
            if buf is not None:
                evs = buf.pop((slot, s.req.out), None)
                if evs:
                    s.req.out.put(evs[0] if len(evs) == 1 else _merge_events(evs))
            s.req.out.put(ev)
            s.req.out.put(None)
        elif buf is not None:
            buf.setdefault((slot, s.req.out), []).append(ev)
        else:
            s.req.out.put(ev)
        return True

    def _context_shift(self, slot: int, s: _Slot, token_id: int):
        """Cache full mid-generation: re-prefill the tail half of the logical
        context into the slot and keep generating (reference KV surgery:
        grpc-server.cpp:1832,1916-1927 — recomputed here; see module doc)."""
        history = self._cache_tokens[slot] + [token_id]
        keep = max(self.ecfg.max_context // 2, 1)
        new_ids = history[-keep:]
        s.phase = "prefill"
        s.pending = list(new_ids)
        s.written = 0
        s.cache_len = 0
        s.committed = 0
        self.active_dev[slot] = False
        self.lengths[slot] = 0
        self._chain_dirty = True
        # restart the penalty ring from the kept window
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, new_ids)
        self._prefill_queue.append(slot)
        # prefix matching against a mid-shift slot cannot happen (occupied)
        self._cache_tokens[slot] = list(new_ids)

    def _check_stops(self, s: _Slot, delta: str) -> Optional[str]:
        """If a stop sequence completes in emitted+delta text, return the
        delta truncated before the stop; else None."""
        total = s.detok.text  # includes delta already
        for stop in s.req.stop_sequences:
            idx = total.find(stop, max(0, len(total) - len(delta) - len(stop)))
            if idx != -1:
                emitted_before = len(total) - len(delta)
                return delta[: max(0, idx - emitted_before)]
        return None

    def _holdback(self, s: _Slot, delta: str) -> tuple[str, str]:
        """Withhold a suffix of delta that is a prefix of any stop sequence."""
        total = s.detok.text
        hold = 0
        for stop in s.req.stop_sequences:
            for k in range(min(len(stop) - 1, len(total)), 0, -1):
                if total.endswith(stop[:k]):
                    hold = max(hold, min(k, len(delta)))
                    break
        if hold:
            return delta[:-hold], delta[-hold:]
        return delta, ""

    def _release_slot(self, slot: int):
        # _cache_tokens is intentionally preserved (trimmed to rows whose KV
        # write actually executed) — the slot's rows stay valid and a future
        # request sharing a prefix reuses them
        s = self.slots[slot]
        if s is not None:
            self._cache_tokens[slot] = self._cache_tokens[slot][:s.committed]
        self.slots[slot] = None
        self.active_dev[slot] = False
        self.lengths[slot] = 0
        self._chain_dirty = True
