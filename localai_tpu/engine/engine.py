"""The TPU serving engine: continuous batching over a compiled decode step.

Re-design of the reference's slot-based continuous-batching server
(reference: backend/cpp/llama/grpc-server.cpp — llama_client_slot :162-301,
task queue utils.hpp:192,336, update_slots hot loop :1578-2013) for XLA's
compilation model:

  * The decode step is ONE jitted function over ALL slots, compiled once —
    inactive slots ride along masked (static shapes, no recompiles).
  * Prompts are ingested by a RAGGED PACKED PREFILL step between decode
    steps (reference packs prompt chunks and decode tokens into one
    llama_batch, :1671+): each tick packs the pending prompt tails of
    ALL queued slots — fresh finals, continued prefix-reuse tails, long
    prompts' chunks, context-shift re-prefills — into ONE
    [total_tokens] batch padded only to a small set of total-token
    buckets, and runs one compiled program that writes every segment's
    KV rows through its own slot's page table and samples first tokens
    for the final segments (models/llama.py ragged_prefill;
    ops/ragged_prefill.py + ops/pallas/ragged_prefill.py). A
    prefill_token_budget caps packed tokens per tick so decode ITL
    stays bounded, and admitting a long prompt never stalls decode for
    active slots by more than one budget's compute.
    ``prefill_packed=0`` restores the per-slot bucketed path (chunks +
    batched same-bucket finals + fused admission) bit-for-bit.
  * KV PREFIX REUSE: per-slot cache contents are tracked host-side; a new
    request is admitted into the free slot sharing the longest common
    token prefix and only the suffix is prefilled (reference:
    grpc-server.cpp:1721-1835 cache_tokens common-prefix reuse).
  * CONTEXT SHIFT: when a slot's cache fills mid-generation, the engine
    re-prefills the tail half of the context into the slot (chunked, so
    other slots keep decoding) and generation continues — the recompute
    equivalent of the reference's KV surgery (llama_kv_cache_seq_rm/add,
    grpc-server.cpp:1832,1916-1927), which XLA's immutable buffers and
    RoPE'd keys make the honest TPU design.
  * Sampling (full per-slot parameter suite) and the penalty-ring update
    are fused INTO the compiled steps — no per-token host round-trip for
    anything but the sampled ids themselves.
  * Admission/stop logic runs host-side on a dedicated engine thread,
    mirroring the reference's queue thread (grpc-server.cpp:2083-2096).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.engine import sampling
from localai_tpu.engine.detok import IncrementalDetokenizer
from localai_tpu.engine.scheduler import (
    PRIORITY_CLASSES, PRIORITY_RANK, ResumeEntry, Scheduler,
    normalize_priority, parse_priority_weights)
from localai_tpu.services import sysobs
from localai_tpu.services.eventlog import EVENTS
from localai_tpu.services.faults import FAULTS
from localai_tpu.models import llama
from localai_tpu.ops import kvcache

# Engine-owned latency histograms, re-exposed over /metrics as real
# Prometheus histograms (services/metrics.py set_histogram). Buckets in
# seconds, sized for serving latencies: sub-ms dispatch costs up to
# multi-second TTFTs.
_HIST_BUCKETS = {
    "ttft_seconds": (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5, 5.0, 10.0, 30.0),
    "itl_seconds": (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0),
    "decode_burst_seconds": (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                             0.1, 0.25, 0.5, 1.0, 2.5),
    "prefill_dispatch_seconds": (0.0005, 0.001, 0.0025, 0.005, 0.01,
                                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
}


class _NullCtx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


@dataclasses.dataclass
class EngineConfig:
    num_slots: int = 8
    max_context: int = 2048
    prefill_buckets: tuple = (32, 128, 512, 2048)
    prefill_chunk: int = 512   # max prompt tokens per slot per prefill tick
    # RAGGED PACKED PREFILL (module doc): pack every queued slot's
    # pending prompt tail into ONE ragged dispatch per tick instead of
    # per-slot bucket-padded chunks/finals. llama-family, non-lockstep,
    # ga_n == 1 only — ineligible slots (multimodal, self-extend,
    # draft-mirrored) transparently take the per-slot path. 0 restores
    # the per-slot scheduling bit-for-bit.
    prefill_packed: bool = True
    # max packed prompt tokens per tick — the decode-ITL bound of the
    # packed path (a tick's pack stalls decode for one pack's compute).
    # 0 = auto: 2 * prefill_chunk, clamped to max_context.
    prefill_token_budget: int = 0
    # fuse the packed prefill step WITH the decode burst so a tick
    # costs ONE dispatch chain. "split" (the "auto" default everywhere)
    # dispatches the fused tick as an early-emit PAIR: the prefill head
    # (ragged prefill + final-segment first tokens) and the burst body
    # chained off its device outputs, back-to-back with no host sync
    # between — the head's first tokens sync ahead of the burst, so the
    # fused path no longer pays the burst's compute in TTFT (the
    # tradeoff that used to keep "auto" real-chip-only: CPU measured
    # 1.5x worse loaded TTFT with the monolithic fuse). "1" forces the
    # monolithic single-program fuse (_fused_packed_body), "0" keeps
    # prefill and burst as independent ticks.
    prefill_packed_fuse: str = "auto"
    # TokenWeave-style compute/communication overlap (models/llama.py +
    # parallel/sharding.py): packed-prefill layers split the token axis
    # in two so the out-proj / down-proj all-reduce of half N overlaps
    # the matmul of half N+1 on the tp mesh. Bit-exact (greedy output
    # byte-identical on or off). "auto" = only when the engine runs on
    # a mesh (single-chip programs have no collectives to hide);
    # "1"/"0" force.
    comm_overlap: str = "auto"
    context_shift: bool = True  # re-prefill tail window when a slot's cache fills
    cache_dtype: Any = jnp.bfloat16
    # KV layout (llama family): "auto" -> the PAGED page-pool layout
    # (ops/kvcache.py; ragged paged decode kernel on TPU) except in
    # multi-host lockstep mode, where the page table is leader-local
    # host state the followers can't replay -> contiguous. "paged" /
    # "contiguous" force it. Paged admission allocates pages lazily per
    # prefill chunk, shares prompt-prefix pages copy-on-write between
    # slots (ref-counted; the first divergent page is cloned) and
    # returns pages to a free list on finish.
    kv_layout: str = "auto"
    kv_page_size: int = 64
    # physical pages in the pool; 0 = num_slots * max_context/page_size
    # (exactly the contiguous reservation — never more HBM). Shrink to
    # oversubscribe against actual usage; retained prefixes of free
    # slots are reclaimed under pressure.
    kv_pool_pages: int = 0
    # cross-release prefix cache (engine/prefix_cache.py): on slot
    # release/context-shift, committed full pages are RETAINED in a
    # token-hash-keyed store instead of freed, and admission splices
    # matching chains into the new slot's table (zero KV row copies,
    # works after the source slot is long gone). Retained pages are
    # evicted LRU-first under pool pressure, so the knob costs no
    # correctness — only free-list headroom. Paged layout only; off
    # restores PR-1 behavior exactly.
    kv_prefix_cache: bool = True
    # minimum reusable rows for a prefix-cache hit (and the live-slot
    # share scan) to beat a clean prefill — a 1-page BOS match must
    # never force the slow continued-prefill path
    kv_prefix_cache_min_rows: int = 16
    # two-tier KV page store (engine/kv_offload.py): when _reclaim_pages
    # would evict a retained chain, its page rows are OFFLOADED to a
    # host-RAM store (same chained block hash keys, int8 pages kept
    # quantized) via a non-blocking device gather, and a prefix-cache
    # hit against an offloaded chain RESTORES the pages into freshly
    # allocated device rows with the upload overlapped against in-flight
    # decode work — the LRU cascades device -> host -> gone. Requires
    # the prefix cache; off restores the PR-2 lifecycle exactly.
    kv_offload: bool = True
    # host-tier byte budget (the host->gone edge of the LRU cascade)
    kv_host_pool_mb: int = 256
    # persist the host store here on graceful shutdown and reload it at
    # init (version/scope-checked; a mismatched or corrupt file is
    # ignored). "" = no persistence.
    kv_host_store_path: str = ""
    # --- long-context serving tier (ISSUE 16) ---
    # snap-back sliding window (SnapStream, arXiv:2511.03092): bound the
    # on-device KV working set to kv_sink_pages attention-sink head
    # pages + this many tail pages; the cold middle demotes to the host
    # tier page by page as decode advances (or drops, see
    # kv_window_policy), so context length is limited by host RAM, not
    # HBM. Paged layout + prefix cache only; 0 = off (bit-for-bit the
    # unwindowed path). Positions stay ABSOLUTE via pos_offset — the
    # window compacts cache rows, never RoPE positions.
    kv_window_pages: int = 0
    # attention-sink head pages pinned on device while a window is
    # active (StreamingLLM-style: the first tokens anchor attention)
    kv_sink_pages: int = 1
    # what happens to the demoted cold middle: "demote" offloads it to
    # the host tier (restorable — the default), "drop" discards it
    # under an explicit compression policy, recorded as a first-class
    # "compress" ledger op so kv_audit=strict stays clean
    kv_window_policy: str = "demote"
    # decode-time prefetch-ahead pipeline (PRESERVE, arXiv:2501.08192):
    # the scheduler scans queued requests each tick and issues
    # double-buffered host->device restores for the chain links their
    # admission will need, AHEAD of the admission — at most this many
    # restore batches in flight. 0 disables (restores happen
    # synchronously at admission, the pre-PR behavior).
    kv_prefetch_ahead: int = 2
    # speculative decoding: draft proposals per round (0 disables even
    # when a draft model is loaded); greedy slots only
    n_draft: int = 4
    # drafting mode (ISSUE 13): "auto" uses the loaded draft model when
    # one exists and falls back to model-free n-gram self-speculation
    # (prompt-lookup over the slot's own token ring) for llama-family
    # greedy slots; "model" / "ngram" force a drafter; "0" disables
    # speculation entirely. Greedy speculation is LOSSLESS whatever the
    # drafter proposes (see engine/speculative.py).
    draft: str = "auto"
    # n-gram length the prompt-lookup drafter matches against the token
    # ring (draft=ngram); longer grams propose less often but more
    # accurately on repetitive continuations
    spec_ngram: int = 3
    # decode BURST: run up to this many decode steps per device dispatch
    # (lax.scan), amortizing per-dispatch overhead (measured ~3-12 ms on the
    # serving chip — larger than one step's compute). Grammar-constrained
    # slots ride bursts speculatively (verify + free rollback at processing
    # time); bursts clamp to cache-capacity conditions, see _pick_burst.
    decode_burst: int = 16
    # decode bursts kept in flight on the device (r4): with depth 2 the
    # host's sync of burst N overlaps burst N+1's compute, so host-side
    # processing never idles the device. Deeper than 2 buys nothing (the
    # host work fits easily inside one burst) and worsens admission lag.
    pipeline_depth: int = 2
    # self-extend / group attention (reference: ga_n/ga_w slot state,
    # grpc-server.cpp:209-213, KV surgery :1904-1927): with ga_n > 1,
    # every completed window of ga_w raw tokens has its RoPE positions
    # divided by ga_n (cached keys re-rotated in place — rotations
    # compose, so this is exact and recomputeless), letting a model
    # trained to max_position_embeddings attend usefully over ga_n x
    # longer raw contexts. Cache ROWS are unaffected (context shift still
    # governs capacity).
    ga_n: int = 1
    ga_w: int = 512
    # request-lifecycle tracing (services/tracing.py): per-request spans
    # (queue_wait / admission / prefill dispatch / decode burst / detok /
    # stream flush) in a fixed ring, host-vs-device decomposition in
    # metrics()["trace"], Chrome trace export via trace_events().
    # trace=0 makes every record() call a no-op on the hot path.
    trace: bool = True
    trace_ring_size: int = 4096
    # slow-request structured log: when a finished request's TTFT or
    # end-to-end wall exceeds this many ms, log one WARNING with the
    # span decomposition. 0 disables.
    slow_request_ms: int = 0
    # --- fault-tolerant request lifecycle (ISSUE 7) ---
    # admission control: submit() sheds (structured 429-mapped error,
    # never an unbounded queue) once this many requests are already
    # waiting for a slot. 0 = unbounded (pre-PR-7 behavior).
    max_queued_requests: int = 256
    # queued requests that waited longer than this are shed at the next
    # admission tick — bounds worst-case queue sojourn under sustained
    # overload. 0 disables.
    max_queue_wait_ms: int = 0
    # per-request deadline from submit(): expired requests get a
    # structured timeout error and are cancelled through the normal
    # engine.cancel path (slot + pages released). 0 disables.
    request_timeout_ms: int = 0
    # stall watchdog: if a dispatched prefill/decode item sees no
    # sync-worker ready-set transition for this long, the engine dumps
    # the span ring to disk, aborts ONLY the stalled requests with
    # structured errors, and keeps serving. 0 disables (pre-PR-7
    # behavior: wait forever).
    dispatch_stall_ms: int = 30000
    # where stall ring dumps land; "" = the system temp dir.
    stall_dump_dir: str = ""
    # --- system observability (ISSUE 8) ---
    # structured event-log sink: a file path, "stderr", or "off"/"" for
    # ring-only (events are ALWAYS retained in the bounded in-memory
    # ring surfaced at /debug/events; this knob adds write-through).
    event_log: str = ""
    # peak device TFLOP/s for MFU accounting; 0 = auto (TPU device-kind
    # table / LOCALAI_PEAK_TFLOPS env; unknown hardware reports MFU 0).
    peak_tflops: float = 0.0
    # --- event-driven hot path (ISSUE 9) ---
    # dedicated emitter worker: detok, stop-sequence scanning and stream
    # queue puts run on a background thread instead of the engine loop;
    # the loop hands over immutable token batches and keeps all id-level
    # control (EOS/grammar/length/context-shift). False restores the
    # in-loop emission path bit-for-bit.
    emitter: bool = True
    # event-log file-sink rotation bound (MB): at this size the file
    # rotates to <path>.1, one generation kept. 0 disables rotation.
    event_log_max_mb: int = 64
    # --- preemptive priority scheduler (ISSUE 10, engine/scheduler.py) ---
    # pause/offload/resume: a higher-priority request that cannot be
    # admitted PREEMPTS the lowest-class active slot — the victim pauses
    # at a burst boundary, its committed pages stay retained (offloading
    # host-side under pool pressure through the normal reclaim path),
    # and resume is plain re-admission through the prefix-splice /
    # host-restore tiers (a killed host entry degrades to a
    # byte-identical re-prefill). Also enables priority-ordered
    # admission, DRR prefill shares and shed fairness. 0 restores
    # strict-FIFO admission bit-for-bit.
    preempt: bool = True
    # deficit-round-robin weights for the high:normal:low classes'
    # shares of the packed-prefill token budget (colon-separated —
    # option values ride a comma-joined wire, so no commas)
    priority_weights: str = "4:2:1"
    # starvation guard: one request is never preempted more than this
    # many times; after that it is immune and runs to completion
    max_preemptions: int = 2
    # free pages held back from FRESH admissions while preempted
    # requests wait to resume (resumes themselves ignore the reserve,
    # so a resume can always make progress). 0 disables.
    resume_reserve_pages: int = 0
    # model-default priority class for requests that don't carry one
    # ("high" | "normal" | "low")
    priority: str = "normal"
    # starvation aging: queued/parked work older than this is treated
    # one class higher when ordering admissions. 0 disables.
    priority_aging_ms: int = 4000
    # --- per-class SLO engine (ISSUE 12, services/sysobs.py) ---
    # latency objectives per priority class, colon-separated
    # high:normal:low thresholds in ms (one value applies to every
    # class; named subsets like "high=250:low=5000" work too — option
    # values ride a comma-joined wire, so colon separates, as in
    # priority_weights). "" = no objective for that metric; all three
    # empty leaves the SLO engine unbuilt (zero per-request cost).
    slo_ttft_ms: str = ""
    slo_itl_ms: str = ""
    slo_queue_wait_ms: str = ""
    # error budget the burn rate is measured against: burn = (violation
    # fraction in window) / budget, so burn > 1 means the class misses
    # its SLO if the rate holds. 0.01 = a 99% objective.
    slo_error_budget: float = 0.01
    # --- KV lifecycle ledger + invariant auditor (ISSUE 15) ---
    # "off" = zero-cost no-op (no auditor object, no ledger, every hook
    # dissolves into one `is not None` check — like trace=0); "on" =
    # continuous report-only scans on the housekeeping cadence (default:
    # counters + kv_audit_violation events + flight dumps); "strict" =
    # violations raise KVAuditError, for tests and chaos rigs.
    kv_audit: str = "on"
    # --- prefill/decode disaggregation (ISSUE 17) ---
    # cluster role: "both" (the default — a normal engine, bit-for-bit
    # the single-host path), "prefill" (admission + packed prefill
    # only: once a slot's prefill completes and its first token is out,
    # the request is ejected via the PR-10 pause primitive, its chain
    # force-offloaded to the host tier, and the ResumeEntry handed to
    # the registered disagg_handoff — the cluster router streams the
    # chain to a decode host and re-admits it there), or "decode" (a
    # routing hint: the cluster router sends it no fresh prefill work;
    # the engine itself needs no restriction — a resumed admission's
    # splice prefill is part of decoding the handoff). With no handoff
    # registered a "prefill" engine serves requests to completion like
    # "both" — a request is never stranded on a role knob.
    disagg: str = "both"
    # --- SLO-driven replica autoscaling (ISSUE 19) ---
    # 0 (default) = bit-for-bit the static pool path: no policy object,
    # no policy thread, no prefetcher constructed. 1 = the pool's
    # housekeeping tick feeds live signals (SLO burn, queue fill, page
    # pressure, preemption EWMA) to engine/autoscale.AutoscalePolicy
    # and executes the returned EnginePool.resize(n) targets.
    autoscale: bool = False
    autoscale_min: int = 1
    autoscale_max: int = 0          # 0 = twice the configured engines=N
    # scale-out fires when the worst short-window SLO burn crosses
    # burn_out; scale-in needs sustained idle with burn under burn_in.
    autoscale_burn_out: float = 1.0
    autoscale_burn_in: float = 0.05
    # hysteresis brakes: same-direction dwell and opposite-direction
    # cool-down, both in ms (the bench rig shrinks them to seconds).
    autoscale_dwell_ms: int = 2000
    autoscale_cooldown_ms: int = 4000
    # --- predictive weight prefetch (ISSUE 19, PRESERVE-style) ---
    # 1 = model loads go through weights.stream_llama_params (leaf-at-
    # a-time, bounded host RAM) and the frontend warms the predicted-
    # next gallery model's parsed leaves into a host cache ahead of its
    # first request.
    weight_prefetch: bool = False
    # --- cluster control plane (ISSUE 20) ---
    # "inproc" (default) = every cluster host is an in-process handle:
    # no RPC server, no heartbeats — bit-for-bit the PR-17 path.
    # "process" = hosts run as separate OS processes behind
    # services/cluster_rpc.py, driven through RemoteHostHandle.
    cluster_mode: str = "inproc"
    # heartbeat probe cadence, and the failure-detector windows: a host
    # with no successful beat (or only slow beats) for suspect_ms is
    # SUSPECT (de-preferred in routing, no new KV-streaming work, its
    # streams stay alive); silent past dead_ms it is DEAD (byte-gated
    # stream recovery on siblings). suspect < dead, always.
    cluster_heartbeat_ms: int = 250
    cluster_suspect_ms: int = 1000
    cluster_dead_ms: int = 3000
    # control-plane per-op deadline + full-jitter retry schedule
    # (idempotent ops only: DIGEST/METRICS/HEARTBEAT/AUDIT; SUBMIT is
    # never auto-retried — recovery re-admits instead)
    cluster_rpc_timeout_ms: int = 2000
    cluster_rpc_retries: int = 3
    cluster_rpc_backoff_ms: int = 50
    # --- federated KV stream timing (ISSUE 20, was hardcoded) ---
    # a failed peer sits out cooldown_ms before being re-tried; negative
    # membership probes cache for negcache_ms; connect/IO timeout for
    # peer stream sockets. Tune together with the detector windows so
    # the KV tier and the control plane agree on peer health.
    kv_stream_cooldown_ms: int = 5000
    kv_stream_negcache_ms: int = 500
    kv_stream_connect_timeout_ms: int = 5000


@dataclasses.dataclass
class GenRequest:
    prompt_ids: list
    params: sampling.SamplingParamsHost = dataclasses.field(
        default_factory=sampling.SamplingParamsHost
    )
    max_new_tokens: int = 256
    stop_sequences: list = dataclasses.field(default_factory=list)
    ignore_eos: bool = False
    grammar: str = ""               # GBNF constrained decoding
    # prompt-cache persistence (reference: backend.proto:132-138,
    # options.go:182-191): committed KV rows + tokens saved to this path
    # on finish, restored on prefix match at admission
    prompt_cache_path: str = ""
    prompt_cache_ro: bool = False   # restore only, never write
    prompt_cache_all: bool = False  # persist generated rows too
    # multimodal (LLaVA-style): projected image embeddings to inject at
    # absolute prompt positions (prompt_ids holds pad tokens there)
    mm_positions: list = dataclasses.field(default_factory=list)  # [P] ints
    mm_vectors: Any = None          # np [P, hidden] float32
    request_id: str = ""
    # priority class ("high" | "normal" | "low"); "" = the model default
    # (EngineConfig.priority). Normalized by Engine.submit — unknown
    # values degrade to the default, never an error (ISSUE 10).
    priority: str = ""
    # filled by engine:
    out: "queue.Queue" = None  # receives StreamEvent, then None sentinel
    t_submit: float = 0.0      # stamped by Engine.submit (TTFT decomposition)
    deadline: float = 0.0      # monotonic; stamped by submit from request_timeout_ms

    def __post_init__(self):
        if not self.request_id:
            self.request_id = uuid.uuid4().hex[:16]
        if self.out is None:
            self.out = queue.Queue()


@dataclasses.dataclass
class StreamEvent:
    token_id: int
    text: str               # finalized delta (may be "")
    logprob: float
    finish_reason: Optional[str] = None  # "stop" | "length" | None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    timings: Optional[dict] = None
    error: Optional[str] = None
    # burst-coalesced events carry every member token (r3: emitting one
    # queue event per token cost ~0.35 ms/token of host time on the 1-core
    # serving host — GIL/wakeup churn — and serialized against the next
    # dispatch; the engine now emits ONE event per slot per processed
    # burst). token_id/logprob above are the LAST member's.
    token_ids: Optional[list] = None
    logprobs: Optional[list] = None
    # lifecycle failure taxonomy (ISSUE 7): set alongside `error` so the
    # gRPC runner can map the failure to the right status code instead
    # of a blanket INTERNAL. "shed" | "timeout" | "stall" | None.
    error_kind: Optional[str] = None
    # crude client back-off hint derived from live queue depth / slot
    # occupancy; surfaced as Retry-After at the HTTP layer.
    retry_after_s: float = 0.0


def event_ids(events) -> list:
    """Flatten a stream of (possibly coalesced) events to token ids."""
    out = []
    for e in events:
        if e.token_ids:
            out.extend(e.token_ids)
        elif e.token_id >= 0:
            out.append(e.token_id)
    return out


def _merge_events(evs: list) -> StreamEvent:
    last = evs[-1]
    return dataclasses.replace(
        last,
        text="".join(e.text for e in evs),
        token_ids=[e.token_id for e in evs],
        logprobs=[e.logprob for e in evs],
    )


class _DispatchStall(Exception):
    """Raised by _wait_ready when a dispatched item saw no sync-worker
    ready-set transition within dispatch_stall_ms. Carries the wedged
    item so _handle_stall can abort exactly its requests."""

    def __init__(self, item):
        super().__init__("device dispatch stalled")
        self.item = item


class _ReplicaDead(BaseException):
    """Chaos-only (ISSUE 14): the ``replica<N>_die`` fault kills this
    replica's loop thread the way a lost host would — BaseException so
    _run's ``except Exception`` recovery can NOT save it. Raised at the
    tick top, where the host mirrors (slots, token histories, emitted
    counts) are consistent with everything already flushed to the
    emitter, so the pool's crash recovery rebuilds resume state from an
    honest snapshot."""


class _Burst:
    """A dispatched decode burst awaiting host processing. Its packed
    results are synced by the engine's SYNC WORKER thread (one thread,
    device dispatch order — concurrent np.asarray calls from two threads
    convoy on the client's transfer path and can invert completion
    order, which metastably collapsed serving throughput ~7x)."""
    __slots__ = ("n_steps", "slots", "pack", "group", "t_dispatch",
                 "t_ready", "pack_np", "ids_np", "lps_np", "first_ids",
                 "first_lps", "folded", "skip_slots", "ready", "err",
                 "head", "spec_mask", "spec_width", "n_out_np",
                 "spec_greedy")

    def __init__(self, n_steps, slots, pack, group=(), t_dispatch=0.0,
                 head=None):
        self.n_steps = n_steps
        self.slots = slots          # [(index, _Slot snapshot), ...]
        self.pack = pack            # device [2K+1(+2), S] f32
        # fused spec tick (ISSUE 13): per-slot spec mask and tokens per
        # round (n_draft + 1); spec_width 0 marks a plain burst
        self.spec_mask = None
        self.spec_width = 0
        self.n_out_np = None        # [R, S] per-round emit counts
        self.spec_greedy = None     # [S] dispatch-time greedy snapshot
        self.group = list(group)    # fused-admission slots (subset of slots)
        # early-emit split: the _PendingPrefill head this burst is
        # chained off on-device. The sync worker readies the head FIRST
        # (dispatch order), so its first tokens emit before this burst
        # syncs; _fold_burst then reads first_ids from the head.
        self.head = head
        self.t_dispatch = t_dispatch
        self.t_ready = 0.0          # sync-worker completion stamp
        self.pack_np = None
        self.ids_np = None
        self.lps_np = None
        self.first_ids = None       # [S] np (fused groups only)
        self.first_lps = None
        self.folded = False
        self.ready = threading.Event()
        self.err = None
        # slots whose host state was rolled back AFTER this burst was
        # dispatched (grammar rollback): the burst's tokens for them are
        # conditioned on a discarded token and must be dropped wholesale
        self.skip_slots: set = set()


class _PendingPrefill:
    """A dispatched final-prefill group awaiting its device results.

    The sampled-first-token sync runs on the engine's SYNC WORKER thread
    (np.asarray releases the GIL during the device wait), so the serving
    loop never blocks on a prefill that is still queued behind in-flight
    decode bursts — r3 polled is_ready(), which lies on this platform."""
    __slots__ = ("group", "out_ids", "logprobs", "mu_out", "t0",
                 "t_ready", "ids_np", "lps_np", "mu_np", "ready", "err",
                 "split", "processed")

    def __init__(self, group, out_ids, logprobs, mu_out, t0, split=False):
        self.group = group
        self.out_ids = out_ids
        self.logprobs = logprobs
        self.mu_out = mu_out
        self.t0 = t0
        self.t_ready = 0.0          # sync-worker completion stamp
        self.ids_np = self.lps_np = self.mu_np = None
        self.ready = threading.Event()
        self.err = None
        # early-emit split head: device chain state was already updated
        # in-program, so processing only EMITS first tokens + stamps
        # timing — the chained burst carries the slots' mirror updates.
        # ``processed`` guards against double emission when _drain_fifo
        # block-syncs the burst past a not-yet-processed head.
        self.split = split
        self.processed = False


class _PendingOffload:
    """A dispatched device->host page gather awaiting its transfer.

    The gather itself is issued between decode dispatches (one jit call,
    no sync); the blocking np.asarray runs on the SYNC WORKER thread in
    dispatch order, so offloading never stalls the serving loop. Once
    materialized, the worker inserts the pages straight into the host
    store (HostPageStore locks internally)."""
    __slots__ = ("metas", "k_rows", "v_rows", "store", "d_rows", "err")

    def __init__(self, metas, k_rows, v_rows, store, d_rows=None):
        self.metas = metas        # [(key, parent, depth), ...] per page
        self.k_rows = k_rows      # device [L, B, pg, KV, hd] (+ scales)
        self.v_rows = v_rows
        self.store = store
        self.d_rows = d_rows      # (dk, dv) draft-cache rows or None
        self.err = None

    def run(self):
        """Sync the gather and hand each page to the host store."""
        import jax as _jax

        k_np = _jax.tree.map(np.asarray, self.k_rows)
        v_np = _jax.tree.map(np.asarray, self.v_rows)
        dk_np = dv_np = None
        if self.d_rows is not None:
            dk_np = _jax.tree.map(np.asarray, self.d_rows[0])
            dv_np = _jax.tree.map(np.asarray, self.d_rows[1])

        def page(rows, i):
            if isinstance(rows, dict):
                return {"q": np.ascontiguousarray(rows["q"][:, i]),
                        "s": np.ascontiguousarray(rows["s"][:, i])}
            return np.ascontiguousarray(rows[:, i])

        for i, (key, parent, depth) in enumerate(self.metas):
            self.store.put(
                key, parent, depth, page(k_np, i), page(v_np, i),
                dk=page(dk_np, i) if dk_np is not None else None,
                dv=page(dv_np, i) if dv_np is not None else None)


class _PendingPrefetch(_PendingOffload):
    """A prefetch-ahead restore batch in the sync worker (ISSUE 16).

    The scatter itself was already dispatched by the engine loop (device
    order protects the upload against later work); this item exists so
    the sync worker observes the upload's completion in dispatch order
    and retires the store's inflight gauge. It reuses the offload
    branch's terminal handling (run + continue, exempt from fault
    injection) — ``metas``/``store`` keep their slots, ``k_rows`` holds
    a tiny device handle dependent on the scatter to sync against."""

    def run(self):
        np.asarray(self.k_rows)      # blocks until the scatter executed
        self.store.note_prefetch_done()


class _Slot:
    __slots__ = (
        "req", "detok", "generated", "held_text", "prompt_len",
        "t_start", "t_first_token", "n_decoded", "t_prefill_ms",
        "grammar", "gstate", "bias_base", "cur_penalty",
        "phase", "pending", "written", "reused", "cache_len", "committed",
        "mm_pos", "mm_vec", "spec_ok", "ga_blocks", "prio", "preempts",
        "win_off", "chain_keys",
    )

    def __init__(self, req: GenRequest, detok, prompt_len: int):
        self.req = req
        self.detok = detok
        self.generated: list[int] = []
        self.held_text = ""   # text withheld due to partial stop-seq match
        self.prompt_len = prompt_len
        self.t_start = time.monotonic()
        self.t_first_token = 0.0
        self.n_decoded = 0
        self.t_prefill_ms = 0.0
        self.grammar = None     # functions.grammars.automaton.Grammar
        self.gstate = None      # current frozenset state
        self.bias_base = None   # np [V] logit_bias row under the grammar mask
        self.cur_penalty = None  # last uploaded penalty row (identity-compared)
        self.phase = "prefill"  # "prefill" -> "decode"
        self.mm_pos = None      # np [P] absolute prompt positions (P-bucketed)
        self.mm_vec = None      # np [P, hidden] injected embeddings
        self.spec_ok = False    # greedy+ungrammared: may join spec rounds
        self.pending: list[int] = []   # prompt tokens not yet prefilled
        self.written = 0        # cache rows already valid for this request
        self.reused = 0         # prefix tokens reused from a previous request
        self.cache_len = 0      # rows occupied in the slot's KV cache
        self.committed = 0      # rows whose KV write has actually executed
        self.ga_blocks = 0      # self-extend: position blocks compressed
        # snap-back window (ISSUE 16): absolute rows already demoted off
        # the device (a page multiple). All row coordinates above
        # (written/committed/cache_len + engine lengths) are COMPACT —
        # absolute position = compact + win_off, carried to the device
        # through pos_offset. 0 = unwindowed, every path bit-for-bit.
        self.win_off = 0
        # chain keys of the slot's absolute FULL pages, extended lazily
        # from _cache_tokens as pages fill — (key, parent, depth) per
        # page, so demotion can offload without rehashing from the root
        self.chain_keys: list = []
        # priority scheduling (ISSUE 10): class rank (0 = high) and how
        # many times this REQUEST has been preempted (survives resume)
        self.prio = PRIORITY_RANK.get(req.priority, 1)
        self.preempts = 0


class Engine:
    """Owns the model state and a background step-loop thread."""

    def __init__(
        self,
        model_cfg: llama.LlamaConfig,
        params,
        tokenizer,
        engine_cfg: EngineConfig = None,
        eos_token_ids: Optional[set] = None,
        mesh=None,
        param_shardings=None,
        draft: Optional[tuple] = None,   # (LlamaConfig, params) draft model
        bus=None,                        # parallel/lockstep.LeaderBus
        family=None,                     # model-family module (default llama)
        replica_id: int = 0,             # position in an EnginePool (ISSUE 14)
        shared_kv=None,                  # pool.SharedKV: one host tier + index
    ):
        self.cfg = model_cfg
        self.ecfg = engine_cfg or EngineConfig()
        # effective admission limit (ISSUE 20): identical to the
        # configured knob for a standalone engine; EnginePool.resize()
        # rescales it proportionally with live replica width
        self.maxq_effective = self.ecfg.max_queued_requests
        # replica-pool membership (ISSUE 14): standalone engines are
        # replica 0 of a pool of one and OWN their host tier (shutdown
        # persists it); pool members share ONE HostPageStore the pool
        # owns, and report device-tier membership to the pool index.
        self.replica_id = int(replica_id)
        self._shared_kv = shared_kv
        self._hstore_owned = shared_kv is None
        # model-family adapter (init_cache / engine_decode / prefill):
        # llama-family by default; models/mamba.py rides the same slot
        # model with a fixed-size (conv, ssm) state in the cache lanes.
        # Families without a positional KV-row cache get the llama-only
        # features gated off (prefix reuse, prompt-cache persistence,
        # fork-dedup, multimodal injection, speculative draft, ga).
        self.family = family if family is not None else llama
        self._fam_llama = self.family is llama
        self._fam_name = getattr(self.family, "__name__",
                                 "llama").rsplit(".", 1)[-1]
        if not self._fam_llama:
            assert draft is None, "draft speculation is llama-family only"
            assert self.ecfg.ga_n <= 1, "self-extend is llama-family only"
        # multi-host lockstep mode: every device dispatch is mirrored to
        # follower processes (see parallel/lockstep.py); features whose
        # dispatches are not in the descriptor set are rejected/disabled
        self._bus = bus
        if bus is not None:
            assert draft is None, "speculative draft unsupported in lockstep"
            assert self.ecfg.ga_n <= 1, "self-extend unsupported in lockstep"
        self.tokenizer = tokenizer
        self.mesh = mesh
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        V = model_cfg.vocab_size

        self.params = params
        # speculative decoding (greedy-lossless; see engine/speculative.py)
        self.draft_cfg, self.draft_params = draft if draft else (None, None)
        # drafting-mode resolution (ISSUE 13): llama-family only (the
        # spec tick composes llama.prefill), never in lockstep (spec
        # dispatches are not in the descriptor set) and never with
        # self-extend (rounds advance row=position). Everything outside
        # those engine modes keeps its pre-spec dispatch stream
        # bit-for-bit (the fused tick is only ever compiled or
        # dispatched when _spec_mode != "off").
        d = str(self.ecfg.draft or "auto").lower()
        if d in ("0", "off", "none", "false"):
            mode = "off"
        elif d == "model":
            mode = "model" if self.draft_params is not None else "off"
        elif d == "ngram":
            mode = "ngram"
        else:   # auto
            mode = "model" if self.draft_params is not None else "ngram"
        if (not self._fam_llama or bus is not None or self.ecfg.ga_n > 1
                or self.ecfg.n_draft <= 0):
            mode = "off"
        self._spec_mode = mode
        self._state_shardings = self._make_state_shardings()
        # paged KV layout resolution (EngineConfig.kv_layout doc):
        # llama-family only; lockstep followers can't replay the leader's
        # host-side page-table mutations, so "auto" degrades there
        if self.ecfg.kv_layout == "paged" and bus is not None:
            raise ValueError("kv_layout=paged is unsupported in multi-host "
                             "lockstep mode (host-local page tables)")
        # self-extend composes with the paged layout since ISSUE 16: the
        # in-place key re-rotation is confined to rows past the
        # compressed region (never the shared/retained pages, whose
        # delta-0 rewrite is value-identical), cross-slot sharing is
        # gated off under ga, and the prefix/host scopes fold ga_n/ga_w
        # in so compressed rows only ever match under the same mapping.
        # "auto" still degrades to contiguous under ga (the historical
        # default); opt in with an explicit kv_layout=paged.
        self._paged = self._fam_llama and (
            self.ecfg.kv_layout == "paged"
            or (self.ecfg.kv_layout == "auto" and bus is None
                and self.ecfg.ga_n <= 1))
        self._pool = None
        self._pcache = None
        self._hstore = None
        self._rstager = None
        self._pool_pages = 0     # resolved physical pool size (0 = full)
        pg = 0
        if self._paged:
            from localai_tpu.engine.paging import PagePool

            pg = max(1, min(self.ecfg.kv_page_size, C))
            while C % pg:     # page size must divide the context
                pg -= 1
            offload_on = self.ecfg.kv_prefix_cache and self.ecfg.kv_offload
            self._pool_pages = self.ecfg.kv_pool_pages
            full = S * (C // pg)
            if self._pool_pages == 0 and offload_on and full >= 64:
                # ROADMAP follow-up: with oversubscription telemetry AND
                # a host tier absorbing evictions, the default pool no
                # longer needs the worst-case contiguous reservation —
                # serving-sized pools shrink 25% (evicted chains offload
                # instead of re-prefilling). Tiny test/bench pools (< 64
                # pages) keep the full reservation: at that scale one
                # slot's context is a large pool fraction and shrinkage
                # would manufacture admission failures, not save HBM.
                self._pool_pages = max(full * 3 // 4, S + C // pg)
            self._pool = PagePool(S, C, pg, self._pool_pages)
            if self.ecfg.kv_prefix_cache:
                # cross-release page retention; NEVER built for the
                # contiguous fallbacks (lockstep / mamba / rwkv) — those
                # layouts have no pages to retain
                from localai_tpu.engine import prefix_cache

                scope = prefix_cache.build_scope(
                    self._fam_name, model_cfg, pg, self.ecfg.cache_dtype)
                if self.ecfg.ga_n > 1:
                    # self-extend rows are position-COMPRESSED: fold the
                    # grouping geometry into the scope so they can only
                    # ever match (device tier, host tier, persisted
                    # store) under the identical ga_n/ga_w mapping
                    scope = scope + b"|ga:%d:%d" % (self.ecfg.ga_n,
                                                    self.ecfg.ga_w)
                # pool mode: device-tier membership feeds the shared
                # cross-replica index (prefix-affinity routing) and the
                # shared store's mapping refcounts
                hooks = (shared_kv.prefix_hooks(self.replica_id)
                         if shared_kv is not None else {})
                self._pcache = prefix_cache.PrefixPageCache(
                    scope, pg, **hooks)
                if self.ecfg.kv_offload:
                    # the host-RAM tier under the pool (the scope doubles
                    # as the persisted file's model/geometry check)
                    from localai_tpu.engine.kv_offload import (
                        HostPageStore, RestoreStager)

                    if shared_kv is not None:
                        # ONE host tier for the whole pool; the pool owns
                        # persistence (saved once, not per replica)
                        self._hstore = shared_kv.host_store(
                            scope, pg, self.ecfg.kv_host_pool_mb,
                            self.ecfg.kv_host_store_path)
                    else:
                        self._hstore = HostPageStore(
                            scope, pg, self.ecfg.kv_host_pool_mb)
                    # double-buffered restore staging (ISSUE 9 satellite):
                    # consecutive restore uploads alternate buffer sets so
                    # an in-flight scatter never aliases a refill
                    self._rstager = RestoreStager()
                    if self._hstore_owned and self.ecfg.kv_host_store_path:
                        n = self._hstore.load(self.ecfg.kv_host_store_path)
                        if n:
                            import logging as _logging

                            _logging.getLogger(__name__).info(
                                "kv host store: reloaded %d offloaded "
                                "pages from %s", n,
                                self.ecfg.kv_host_store_path)
        # --- long-context tier (ISSUE 16): snap-back window + prefetch ---
        self._win_pages = 0
        self._win_sink = max(0, int(self.ecfg.kv_sink_pages))
        self._prefetch = None
        if self.ecfg.kv_window_pages > 0:
            W = int(self.ecfg.kv_window_pages)
            if not self._paged or self._pcache is None:
                raise ValueError(
                    "kv_window_pages requires the paged KV layout with the "
                    "prefix cache enabled (kv_prefix_cache=1)")
            if self.ecfg.kv_window_policy not in ("demote", "drop"):
                raise ValueError(
                    "kv_window_policy must be demote|drop, got "
                    f"{self.ecfg.kv_window_policy!r}")
            if (self.ecfg.kv_window_policy == "demote"
                    and self._hstore is None):
                raise ValueError(
                    "kv_window_policy=demote requires the host tier "
                    "(kv_offload=1); use kv_window_policy=drop to run a "
                    "window without host RAM")
            if (self._win_sink + W + 2) * pg > C:
                raise ValueError(
                    f"kv window does not fit: (sink {self._win_sink} + "
                    f"window {W} + 2) pages of {pg} rows exceeds "
                    f"max_context {C}")
            if self.ecfg.ga_n > 1:
                raise ValueError(
                    "kv_window_pages does not compose with self-extend "
                    "(ga_n > 1): both mechanisms own the slot's RoPE "
                    "position offset")
            self._win_pages = W
        if (self._paged and self._hstore is not None
                and self.ecfg.kv_prefetch_ahead > 0):
            from localai_tpu.engine.kv_offload import PrefetchPipeline

            self._prefetch = PrefetchPipeline()
        # device-resident state: big (KV cache), rarely-mutated (bias), or
        # not host-mirrorable (PRNG keys). Everything per-slot and small
        # lives as HOST numpy — admissions/releases are then free in-place
        # writes instead of ~3ms `.at[].set` dispatches, and the arrays ride
        # to the device as ordinary jit args each step.
        self.ck, self.cv = self.family.init_cache(
            model_cfg, S, C, self.ecfg.cache_dtype,
            **({"page_size": pg, "num_pages": self._pool_pages}
               if self._paged else {}))
        # draft cache is allocated LAZILY at the first spec-eligible
        # admission (r2 allocated it up front, doubling per-slot KV HBM
        # even when no request could ever speculate)
        self.dck = self.dcv = None
        self.bias = jnp.zeros((S, V), jnp.float32)
        self.rng_keys = jax.vmap(jax.random.key_data)(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(S, dtype=jnp.uint32))
        )
        self.slot_params = sampling.make_slot_params(S)
        self.ring, self.ring_pos = sampling.make_ring(S)
        self.mu = sampling.make_mu(S)
        self.lengths = np.zeros((S,), np.int32)
        self.cur_tokens = np.zeros((S,), np.int32)
        self.active_dev = np.zeros((S,), np.bool_)
        self.pos_offset = np.zeros((S,), np.int32)  # self-extend offsets
        # snap-back window (ISSUE 16): compact rows each slot demoted
        # since the last dispatch — subtracted from the device chain's
        # lengths via override-pack row 6, zeroed after every pack
        self._win_delta = np.zeros((S,), np.int32)
        self._adm_win_off = 0   # window offset chosen by _paged_admission
        self._bias_dirty = np.zeros((S,), np.bool_)
        self._shard_state()

        if eos_token_ids:
            self.eos_ids = set(eos_token_ids)
        else:
            self.eos_ids = set()
            eid = getattr(tokenizer, "eos_token_id", None)
            if eid is not None:
                self.eos_ids.add(int(eid))

        # host mirrors
        self.slots: list[Optional[_Slot]] = [None] * S
        self._cache_tokens: list[list[int]] = [[] for _ in range(S)]
        self._prefill_queue: list[int] = []   # slot ids awaiting prefill chunks
        self._cancelled: set = set()
        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._load_time = time.monotonic()
        self._total_tokens = 0
        self._reused_total = 0
        # (queue_wait_ms, admit_to_first_ms, prefill_ms) per finished
        # request — rolling window for the TTFT decomposition in metrics()
        from collections import deque
        self._ttft_decomp: "deque" = deque(maxlen=512)
        # at maxlen every append mutates the deque, and metrics() reads it
        # from gRPC handler threads — unsynchronized iteration raises
        self._decomp_lock = threading.Lock()
        self._rollbacks = 0     # grammar rollbacks (test observability)

        self._burst_fns: dict[int, Callable] = {}
        self._chunk_fns: dict[int, Callable] = {}
        self._final_fns: dict[tuple, Callable] = {}
        # fused spec-tick counters (ISSUE 13): dispatches = spec ticks
        # issued, mixed_dispatches = ticks that carried BOTH spec rounds
        # and plain-decode rows, rounds/proposed/accepted = per-slot
        # round totals, tokens = emitted spec tokens (accepted + bonus).
        # by_mode (ISSUE 18) splits the per-slot totals between greedy
        # (accept_greedy) and sampled (accept_sampled) slots; the flat
        # keys stay the cross-mode aggregates.
        self._spec_stats = {"dispatches": 0, "mixed_dispatches": 0,
                            "rounds": 0, "proposed": 0, "accepted": 0,
                            "tokens": 0,
                            "by_mode": {
                                m: {"rounds": 0, "proposed": 0,
                                    "accepted": 0, "tokens": 0}
                                for m in ("greedy", "sampled")}}

        # pipelined decode state (r4 redesign): bursts chain device-side
        # through (tokens, lengths, ring, ring_pos, mu) output handles, and
        # host events (admission, release, context shift, rollback) no
        # longer invalidate the whole chain — each dispatch composes the
        # chain with per-slot OVERRIDE rows taken from the host mirrors
        # (see _decode_burst_body), so dispatch NEVER waits on a device
        # sync. Dispatched work (decode bursts + final-prefill groups)
        # lives in one FIFO mirroring the device's execution order; the
        # loop keeps up to pipeline_depth bursts in flight and only
        # block-syncs the FIFO head, which by then is (nearly) computed —
        # this replaces r3's is_ready() polling, which lies on this
        # platform (a "ready" prefill result still blocked ~640 ms).
        import collections

        self._chain = None                    # device handles or None
        self._override: set = set()           # slots whose chain rows are stale
        self._fifo = collections.deque()      # _Burst | _PendingPrefill
        self._burst_ms_ema = 0.0   # plain-burst dispatch->processed latency
        self._sync_q: "queue.Queue" = queue.Queue()
        self._sync_thread = threading.Thread(
            target=self._sync_worker, name="engine-sync", daemon=True)
        self._sync_thread.start()

        # effective prefill buckets always include the chunk size; both are
        # clamped to the cache capacity (a bucket larger than max_context
        # could never be written and would crash the prefill KV update)
        self._chunk = min(self.ecfg.prefill_chunk, C)
        self._buckets = tuple(sorted(set(
            [b for b in self.ecfg.prefill_buckets if b <= min(self._chunk, C)]
            + [self._chunk])))
        # fresh final prefills batch up to this many prompts per dispatch
        # (padded by repeating the last entry, so only two compiled batch
        # sizes exist per bucket: 1 and _final_pad). Sized for the wave-
        # turnover case (r3 trace: all slots finishing together serialized
        # 4 groups of 8 through one pending slot, stalling the device ~1s
        # per wave): one group should swallow half the fleet.
        self._final_pad = max(8, min(16, self.ecfg.num_slots))
        # ragged packed prefill (module doc): one dispatch per tick for
        # ALL queued slots' prompt tails. Families without the ragged
        # forward and lockstep (the pack op is not in the descriptor
        # set) keep the per-slot path; ineligible SLOTS (multimodal,
        # position-compressed self-extend) fall back per-slot inside
        # _prefill_step. Spec slots pack too — their draft mirror rides
        # a packed ragged program (_get_draft_packed_fn); a ga engine's
        # UNcompressed slots pack normally (compressed ones need
        # explicit grouped positions and go singly, _prefill_ga_piece).
        self._packed = (self.ecfg.prefill_packed and self._fam_llama
                        and bus is None)
        fuse = str(self.ecfg.prefill_packed_fuse)
        # fused-tick mode: "off" | "mono" (prefill + first tokens +
        # burst as literally one program) | "split" (early-emit pair:
        # the same work as two back-to-back dispatches with no host
        # sync between, so the head's first tokens reach the stream
        # while the decode half is still computing). auto = split on
        # EVERY platform — the split recovers the first-token delay
        # that kept the monolithic body real-chip-only.
        self._pack_fuse = {"0": "off", "1": "mono",
                           "split": "split"}.get(fuse, "split")
        co = str(self.ecfg.comm_overlap)
        # TokenWeave halved-pack overlap (models/llama.py): only ever a
        # win when per-layer collectives exist, so auto arms it on a
        # mesh and keeps single-device serving on the one-chain path.
        # Bit-exact either way (parallel/sharding.py::overlap_halves).
        self._comm_overlap = co == "1" or (co == "auto"
                                           and self.mesh is not None)
        budget = self.ecfg.prefill_token_budget or 2 * self._chunk
        self._pack_budget = max(1, min(budget, C))
        # total-token pad buckets for the pack: the per-slot ladder
        # capped at the budget, plus the budget itself (the loaded
        # steady state) — a handful of compiled variants, warmed by
        # precompile()
        self._pack_buckets = tuple(sorted(
            {min(b, self._pack_budget) for b in self._buckets}
            | {self._pack_budget}))
        # packed-prefill telemetry (metrics(); exercised by tests):
        # dispatches, packed real tokens, segments, pad waste, and
        # dispatches whose shape left the Pallas kernel path
        # (models/llama.py::ragged_kernel_shape_fallback — the ~1k-token
        # cliff this counter keeps observable)
        self._pack_stats = {"dispatches": 0, "tokens": 0, "segments": 0,
                            "pad_tokens": 0, "kernel_fallback": 0}

        # grammar-constrained decoding (lazy: built on first grammar request)
        self._grammar_cache: dict[str, Any] = {}
        self._mask_builder = None
        self._token_strs: Optional[list] = None

        # loop-phase tracing (LOCALAI_ENGINE_TRACE=1): cumulative seconds
        # per phase + counts, dumped at shutdown — the tool that found the
        # r3 serving-vs-kernel gap
        import os as _os

        self._trace = _os.environ.get("LOCALAI_ENGINE_TRACE", "") == "1"
        self._tstats: dict = {}
        # request-lifecycle span tracer (services/tracing.py): always
        # constructed; trace=0 makes record() a no-op on the hot path
        from localai_tpu.services.tracing import RingTracer

        self.tracer = RingTracer(self.ecfg.trace_ring_size,
                                 enabled=bool(self.ecfg.trace))
        self._slow_ms = float(self.ecfg.slow_request_ms)
        # per-request latency histograms (re-exposed by /metrics as real
        # Prometheus histograms): name -> [bucket counts + +Inf, sum, n].
        # Single writer (engine thread); metrics() reads are snapshots.
        self._hists = {name: [[0] * (len(b) + 1), 0.0, 0]
                       for name, b in _HIST_BUCKETS.items()}
        self._t_last_burst = 0.0
        # lifecycle telemetry + watchdog state (ISSUE 7). _t_last_ready is
        # the last sync-worker ready-set stamp: the stall watchdog measures
        # from max(item.t_dispatch, _t_last_ready) so a busy-but-progressing
        # pipeline never false-triggers.
        self._t_last_ready = 0.0
        self._lc = {"requests_shed": 0, "requests_timed_out": 0,
                    "stalls": 0, "stall_dumps": 0}
        self._lc_lock = threading.Lock()
        # non-None while _process_burst coalesces per-slot events
        self._sink_buf: Optional[dict] = None
        # in-flight prefill dedup: leader slot -> [(sib_slot, snap, leader
        # snap, ids)]; KV rows fork when the leader's prefill commits
        self._fork_waiters: dict = {}
        self._fork_fns: dict = {}
        # grammar slots whose mask row changed since the last device flush
        self._gbias_flush: set = set()
        # --- system observability (ISSUE 8, services/sysobs.py) ---
        # structured event-log sink (per-process singleton; the engine's
        # knob arms it for this backend process)
        if self.ecfg.event_log:
            EVENTS.configure(self.ecfg.event_log,
                             max_mb=self.ecfg.event_log_max_mb)
        # XLA compile tracking: the jax.monitoring listener dispatches to
        # this tracker from whichever thread registered it (the engine
        # loop registers at startup; precompile() wraps itself)
        self._cobs = sysobs.CompileTracker(
            model=self._fam_name,
            on_storm=lambda rec: EVENTS.emit("compile_storm", **rec))
        # memory watermarks: peaks folded from engine-loop tick samples
        self._wm = sysobs.Watermarks()
        try:
            self._weight_bytes = int(sum(
                a.size * a.dtype.itemsize for a in jax.tree.leaves(params)
                if hasattr(a, "size") and hasattr(a, "dtype")))
        except Exception:
            self._weight_bytes = 0
        # goodput/MFU: completed-request tokens only (sheds and timeouts
        # burn FLOPs but never reach the clean-finish accounting)
        peak = (self.ecfg.peak_tflops * 1e12 if self.ecfg.peak_tflops > 0
                else sysobs.peak_device_flops())
        fpt = (sysobs.flops_per_token(self.cfg, ctx=C // 2)
               if self._fam_llama else 0.0)
        self._goodput = sysobs.GoodputMeter(flops_per_tok=fpt,
                                            peak_flops=peak)
        # exemplar tracking: worst observation per histogram since the
        # last metrics() pull, with its request correlation id
        self._hist_worst: dict = {}
        self._pool_pressure = False   # hysteresis for pool_pressure events
        # --- event-driven hot path (ISSUE 9) ---
        # idle arm: with the sync worker waking the loop on every ready-set
        # transition (_wake), the fixed 50 ms poll tick is dead weight —
        # park until woken, bounded only by the watchdog cadence.
        stall_s = self.ecfg.dispatch_stall_ms / 1e3
        self._idle_wait_s = min(1.0, stall_s / 4) if stall_s > 0 else 1.0
        # emitter handoff: per-tick token batch (slot -> entry, insertion
        # ordered) flushed as ONE queue item per processed burst/prefill,
        # plus the note channel for emitter-detected stop finishes.
        self._em_batch: dict = {}
        self._em_notes: list = []
        self._em_lock = threading.Lock()
        self._emitter = self._make_emitter() if self.ecfg.emitter else None
        # hot-path dispatch: bound once so _process_burst/_process_prefill
        # don't branch per token
        self._emit = (self._emit_token_ev if self._emitter is not None
                      else self._emit_token)
        # reusable host-side staging for per-dispatch overrides and packed
        # segment tables: round-robin pools deep enough that no buffer is
        # rewritten while its async device transfer may still be reading
        self._ov_pool = [np.empty((7 + sampling.RING_N, S), np.float32)
                         for _ in range(max(6, self.ecfg.pipeline_depth + 4))]
        self._ov_pool_idx = 0
        self._seg_pools: dict = {}   # bucket -> round-robin list of arrays
        self._seg_pool_idx: dict = {}
        # --- preemptive priority scheduler (ISSUE 10) ---
        # the scheduler owns the per-tick run decision: aged-rank
        # admission ordering, DRR prefill-budget shares, preemption
        # victim selection and the resume queue. preempt=0 leaves it
        # unbuilt and every path below falls back to strict FIFO.
        self._default_prio = normalize_priority(self.ecfg.priority)
        self._sched = None
        if self.ecfg.preempt:
            self._sched = Scheduler(
                parse_priority_weights(self.ecfg.priority_weights),
                max_preemptions=self.ecfg.max_preemptions,
                aging_ms=float(self.ecfg.priority_aging_ms))
        # --- live migration out of this replica (ISSUE 14) ---
        # request_id -> handoff callable, drained by the engine loop at
        # the next tick: the slot preempts (PR-10 pause), its retained
        # chain force-offloads to the (shared) host tier, and the
        # ResumeEntry is handed to the pool instead of parked here.
        self._migrate_req: dict = {}
        self._migrate_lock = threading.Lock()
        # replica_die fault name (chaos: pool crash recovery) — checked
        # at the tick top only while fault injection is armed
        self._die_fault = f"replica{self.replica_id}_die"
        # --- cluster serving (ISSUE 17) ---
        # prefill/decode disaggregation: the cluster router registers a
        # handoff here on "prefill"-role engines; _process_disagg ejects
        # finished-prefill slots into it at the tick top. None = no
        # cluster — the tick-top check is one attribute read.
        self.disagg_handoff = None
        self._disagg_prefill = (self.ecfg.disagg == "prefill")
        self.disagg_handoffs = 0
        # warm-chain checkpointing (DejaVu-style KV streaming for crash
        # recovery): when armed by a ClusterHost, active slots' committed
        # chains are retained + force-offloaded to the host tier on the
        # watermark cadence — so a host that dies mid-decode leaves its
        # warm chains fetchable by the sibling that re-adopts its work.
        self.kv_checkpoint = False
        # --- resume_reserve_pages autosize (ISSUE 14 satellite; the
        # open PR-10 follow-up): EWMA of preemptions/min x average pages
        # retained per preemption -> effective reserve when the explicit
        # knob is 0. Starts at 0, so engines that never preempt keep
        # bit-for-bit admission behavior.
        self._preempt_marks: "deque" = deque(maxlen=256)   # monotonic stamps
        self._preempt_rate_ewma = 0.0    # preemptions per minute
        self._preempt_pages_ewma = 0.0   # pages retained per preemption
        self._reserve_auto = 0
        self._t_reserve_sample = time.monotonic()
        # --- per-class SLO engine + violation flight recorder (ISSUE 12)
        # Built only when an objective is declared — the finish-path
        # observe() calls are then dict lookups; with no objectives the
        # whole layer is None-checked away.
        objectives = {}
        for metric, spec in (("ttft_ms", self.ecfg.slo_ttft_ms),
                             ("itl_ms", self.ecfg.slo_itl_ms),
                             ("queue_wait_ms", self.ecfg.slo_queue_wait_ms)):
            classes = sysobs.parse_slo_classes(spec)   # raises on typos
            if classes:
                objectives[metric] = classes
        self._slo = (sysobs.SLOEngine(
            objectives, error_budget=self.ecfg.slo_error_budget)
            if objectives else None)
        # the flight recorder dumps merged trace + state + events on SLO
        # violations AND watchdog/stall events, into the same directory
        # the stall ring dumps use
        self._flight = sysobs.FlightRecorder(self.ecfg.stall_dump_dir)
        # last device allocator sample (bytes_in_use/peak/limit); {} on
        # backends without memory_stats() (CPU) — see _sample_watermarks
        self._device_mem: dict = {}
        # --- KV lifecycle ledger + online invariant auditor (ISSUE 15)
        # kv_audit=off (or a non-paged layout) constructs NOTHING: every
        # hook in paging/prefix_cache/kv_offload gates on a single
        # `audit is not None`, so the off path is the pre-PR hot path.
        self._kv_audit = None
        if self._paged and self.ecfg.kv_audit != "off":
            from localai_tpu.services.kv_audit import KVAuditor

            aud = KVAuditor(mode=self.ecfg.kv_audit,
                            replica=self.replica_id,
                            seed=self.replica_id)
            aud.on_violation = self._on_kv_violation
            self._pool.audit = aud
            if self._pcache is not None:
                self._pcache.audit = aud
            if self._hstore is not None and (self._hstore_owned
                                             or self._hstore.audit is None):
                # owned store: this replica's ledger records its tier
                # transitions and its housekeeping scans it. Shared store
                # (pool mode): the first replica's ledger takes the
                # store-level records; the POOL housekeeping scans it so
                # shared violations are counted once, not per replica.
                self._hstore.audit = aud
            self._kv_audit = aud

    def _sync_worker(self):
        """ALL device->host syncs run here, one at a time, in dispatch
        (= device execution) order: each np.asarray then blocks only
        until its own item finishes computing. The serving loop never
        issues a transfer itself — it dispatches, and consumes results
        whose ``ready`` event has fired."""
        while True:
            item = self._sync_q.get()
            if item is None:
                return
            if FAULTS.active and not isinstance(item, _PendingOffload):
                d = FAULTS.take("sync_delay_ms")
                if d is not None:
                    # stall injection: the ready-set transition is late, so
                    # the dispatch watchdog should fire on the waiting item
                    time.sleep(int(d) / 1e3)
                if FAULTS.take("sync_fail") is not None:
                    item.err = RuntimeError("injected fault: sync_fail")
                    item.t_ready = self._t_last_ready = time.monotonic()
                    item.ready.set()
                    self._wake.set()
                    continue
            try:
                if isinstance(item, _Burst):
                    item.pack_np = np.asarray(item.pack)
                elif isinstance(item, _PendingOffload):
                    # terminal here: offloads produce no tokens, so they
                    # never enter the dispatch FIFO — sync + store insert
                    # both live on this thread, off the serving loop
                    item.run()
                    continue
                else:
                    item.ids_np = np.asarray(item.out_ids)
                    item.lps_np = np.asarray(item.logprobs)
                    item.mu_np = np.asarray(item.mu_out)
            except Exception as e:  # surfaced when the item is processed
                if isinstance(item, _PendingOffload):
                    # a failed offload only loses a reusable copy — log
                    # and keep serving (the chain just re-prefills later)
                    __import__("logging").getLogger(__name__).exception(
                        "kv page offload failed")
                    continue
                item.err = e
            # the ready-set stamp IS the device-completion observation
            # point (block_until_ready/is_ready lie on this platform):
            # span t_dispatch->t_ready is device time, t_ready->process
            # pickup is finish-detection latency
            item.t_ready = self._t_last_ready = time.monotonic()
            item.ready.set()
            self._wake.set()

    def _tmark(self, key: str, t0: float):
        if self._trace:
            t = time.monotonic()
            s = self._tstats.setdefault(key, [0.0, 0])
            s[0] += t - t0
            s[1] += 1

    def _hobserve(self, name: str, seconds: float, rid: str = ""):
        h = self._hists[name]
        for i, b in enumerate(_HIST_BUCKETS[name]):
            if seconds <= b:
                h[0][i] += 1
                break
        else:
            h[0][-1] += 1
        h[1] += seconds
        h[2] += 1
        # per-span exemplar (ISSUE 8 satellite): remember the WORST
        # observation since the last metrics() pull with its correlation
        # id, so /metrics can attach an OpenMetrics exemplar pointing at
        # the span a latency investigation should start from
        if rid:
            worst = self._hist_worst.get(name)
            if worst is None or seconds > worst[0]:
                self._hist_worst[name] = (seconds, rid, time.time())

    def _flight_dump(self, reason: str, tag: str = "slo", **extra):
        """Violation flight recorder (ISSUE 12): atomically persist the
        merged evidence for ONE bad moment — chrome trace, /debug/state
        snapshot and the last events — so a stall or SLO burn seen on a
        dashboard at 3am still has its context on disk at 9am. Rate
        limiting and disk bounds live in sysobs.FlightRecorder; this
        wrapper only assembles the payload and must never raise into the
        engine loop."""
        try:
            payload = {
                "trace": self.trace_events(),
                "state": self.state_snapshot(),
                "events": EVENTS.events(last=256),
            }
            payload.update(extra)
            path = self._flight.dump(reason, payload, tag=tag)
            if path:
                EVENTS.emit("flight_dump", reason=reason, tag=tag, path=path)
            return path
        except Exception:  # pragma: no cover - defensive
            __import__("logging").getLogger(__name__).exception(
                "flight dump failed")
            return ""

    def _on_kv_violation(self, v: dict):
        """KVAuditor callback (ISSUE 15): one structured event per
        violation + a flight dump with the ledger tail attached, so the
        last ~64 page transitions that led to the broken invariant are
        on disk next to the trace/state evidence. Rate limiting lives in
        the recorder; this must never raise into the audit pass."""
        try:
            EVENTS.emit("kv_audit_violation",
                        **{k: (x if isinstance(x, (str, int, float))
                               else str(x)) for k, x in v.items()})
            self._flight_dump("kv_audit:" + str(v.get("check", "?")),
                              tag="kv_audit", kv_violation=v,
                              kv_ledger_tail=(
                                  self._kv_audit.ledger.tail(64)
                                  if self._kv_audit is not None else []))
        except Exception:  # pragma: no cover - defensive
            pass

    def _kv_audit_tick(self, drained: bool = False) -> list:
        """One online audit pass (ISSUE 15), riding the engine-loop
        housekeeping cadence so the pool's host mirrors are never
        mid-mutation. The only detached pages that survive a tick
        boundary are the prefetch pipeline's (ISSUE 16) — declared as
        extras so the leak scan can tell them from orphans; every other
        alloc_detached/unref_detached pairs within single calls on this
        thread. Strict mode lets the KVAuditError propagate — in the
        live loop that lands in the generic step-failure recovery, in
        tests it fails the test."""
        aud = self._kv_audit
        if aud is None:
            return []
        extras = ([rec[0] for rec in self._prefetch.pages.values()]
                  if self._prefetch is not None else None)
        return aud.run(
            self._pool, pcache=self._pcache,
            hstore=self._hstore if self._hstore_owned else None,
            extra_pages=extras, drained=drained)

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        """On-demand full audit pass + snapshot (bench phase ends, CI
        gates, tests). The caller must be quiesced — nothing in flight —
        since the scan reads the host mirrors without the engine loop's
        serialization."""
        if self._kv_audit is None:
            return {"mode": "off", "checks": 0, "violations": 0,
                    "leaked_pages": 0, "ledger_events": 0}
        self._kv_audit_tick(drained=drained)
        return self._kv_audit.snapshot()

    def kv_debug(self) -> dict:
        """/debug/kv payload (ISSUE 15): tier map, per-chain genealogy,
        fragmentation layout, audit counters + last violations, and the
        ledger tail. ``{"mode": "off"}`` shape when auditing is off or
        the layout has no pages."""
        if self._kv_audit is None:
            return {"mode": "off", "replica": self.replica_id}
        pool = self._pool
        out = {
            "mode": self._kv_audit.mode,
            "replica": self.replica_id,
            "pool": {
                "pages_total": pool.num_pages,
                "page_size": pool.page_size,
                "free": pool.free_pages,
                "active": pool.active_pages,
                "retained": pool.retained_pages,
                "shared": int((pool.refs > 1).sum()),
                "oversubscription": round(pool.oversubscription, 4),
                "fragmentation": pool.fragmentation(),
                "pages_per_slot": [int(n) for n in pool.owned],
            },
            "chains": (self._pcache.genealogy(64)
                       if self._pcache is not None else []),
            "audit": self._kv_audit.snapshot(),
            "ledger_tail": self._kv_audit.ledger.tail(64),
        }
        if self._hstore is not None:
            out["host"] = self._hstore.stats()
            if self._hstore.federated is not None:
                # peer tier (ISSUE 17): wire fetch/push totals
                out["kv_stream"] = self._hstore.federated.stats()
        if self._win_pages:
            out["window"] = {
                "pages": self._win_pages,
                "sink_pages": self._win_sink,
                "policy": self.ecfg.kv_window_policy,
                "win_off_rows": [
                    (s.win_off if s is not None else 0) for s in self.slots],
            }
        if self._prefetch is not None:
            out["prefetch"] = {
                "staged_pages": len(self._prefetch),
                "seen_rids": len(self._prefetch.seen_rids),
            }
        return out

    def _slo_finish(self, s, ndec: int, t_done: float, ttft_ms: float,
                    queue_wait_ms: float):
        """Feed one finished request into the SLO engine (ISSUE 12).

        Called from BOTH finish paths (in-loop _emit_token branch and the
        event-driven _finish_accounting_ev) with the same timings the
        histograms see, so burn rates and latency buckets can never
        disagree about what happened. ITL is the per-request mean
        inter-token gap — (t_done - t_first)/(ndec-1) — which matches how
        a client experiences stream smoothness without keeping per-token
        stamps around."""
        if self._slo is None or not self._slo.enabled:
            return
        cls = s.req.priority or "normal"
        violations = []
        v = self._slo.observe("ttft_ms", cls, ttft_ms, rid=s.req.request_id)
        if v:
            violations.append(v)
        v = self._slo.observe("queue_wait_ms", cls, queue_wait_ms,
                              rid=s.req.request_id)
        if v:
            violations.append(v)
        if ndec > 1 and s.t_first_token:
            itl_ms = (t_done - s.t_first_token) * 1e3 / (ndec - 1)
            v = self._slo.observe("itl_ms", cls, itl_ms,
                                  rid=s.req.request_id)
            if v:
                violations.append(v)
        for v in violations:
            EVENTS.emit("slo_violation", rid=v["rid"], metric=v["metric"],
                        cls=v["class"], value_ms=round(v["value_ms"], 1),
                        objective_ms=v["objective_ms"])
        if violations:
            self._flight_dump(
                f"slo:{violations[0]['metric']}:{violations[0]['class']}",
                tag="slo", violations=violations)

    def _annot(self, name: str):
        """jax.profiler annotation around a dispatch, so device traces
        captured via /debug/profile line up with engine spans. No-op
        context when trace=0 or the profiler is unavailable."""
        if not self.tracer.enabled:
            return _NULL_CTX
        try:
            return jax.profiler.TraceAnnotation(name)
        except Exception:  # pragma: no cover - profiler unavailable
            return _NULL_CTX

    def _make_state_shardings(self) -> Optional[dict]:
        """NamedShardings for the engine's device state when serving on a
        mesh (parallel/sharding.py cache_spec: slots on dp, kv heads on tp).
        Falls back to replication per axis when sizes don't divide — a
        wrong-but-silent replicated cache is exactly the HBM waste this
        exists to avoid, so only shard what divides evenly."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        dp = self.mesh.shape.get("dp", 1)
        tp = self.mesh.shape.get("tp", 1)
        slot_ax = "dp" if dp > 1 and self.ecfg.num_slots % dp == 0 else None
        if self._fam_llama:
            # [L, S, C, KV, hd]: kv heads on tp
            kv_ax = "tp" if tp > 1 and self.cfg.num_kv_heads % tp == 0 \
                else None
            cache_spec = (None, slot_ax, None, kv_ax, None)
        elif self._fam_name == "mamba":
            # mamba conv/ssm state [L, S, Di, {K-1|N}]: d_inner on tp,
            # matching mamba_param_specs so the recurrence is shard-local
            di_ax = "tp" if tp > 1 and self.cfg.d_inner % tp == 0 else None
            cache_spec = (None, slot_ax, di_ax, None)
        else:
            # rwkv state [L, S, {4|1}, D]: D is the trailing axis; params
            # are replicated for this family, so keep D unsharded too
            cache_spec = (None, slot_ax, None, None)

        def ns(*spec):
            return NamedSharding(self.mesh, P(*spec))

        return {
            # raw spec tuple: the int8 llama cache is a pytree whose scale
            # leaf drops the hd axis (kvcache.device_put builds both)
            "cache_spec": cache_spec,
            "slot_vec": ns(slot_ax),                        # [S]
            "slot_mat": ns(slot_ax, None),                  # [S, V] / [S, 2]
        }

    def _shard_state(self):
        """Commit device-resident state to the mesh (ADVICE r1: without this
        the dp/tp cache sharding was never applied in the real serving path —
        every device held a full replica of the KV cache). Host-numpy slot
        state needs no commitment — it enters jitted steps as arguments and
        GSPMD places it."""
        sh = self._state_shardings
        if sh is None:
            return
        self.ck = kvcache.device_put(self.ck, self.mesh, sh["cache_spec"])
        self.cv = kvcache.device_put(self.cv, self.mesh, sh["cache_spec"])
        self.bias = jax.device_put(self.bias, sh["slot_mat"])
        self.rng_keys = jax.device_put(self.rng_keys, sh["slot_mat"])

    # ---------- paged KV plumbing ----------

    def _commit_ptab(self):
        """Commit the host page-table mirror into the cache pytrees (the
        table rides INSIDE ck/cv so every jitted body stays
        layout-agnostic). Called before any dispatch that touches the
        cache; a no-op unless the allocator dirtied the table."""
        if not self._paged or not self._pool.dirty:
            return
        # ck and cv are donated separately, so they need DISTINCT table
        # buffers — but one stacked host->device transfer plus two
        # device-side slices beats two independent uploads (ISSUE 9:
        # half the transfer dispatches on every allocator change). The
        # paged draft cache (ISSUE 13) rides the SAME table: draft rows
        # live at the same page ids as the target's, so spec slots share
        # the prefix cache and offload/restore machinery for free.
        n = 4 if self.dck is not None else 2
        stacked = np.stack((self._pool.ptab,) * n)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self.mesh, P(None, None, None))
            both = jax.device_put(stacked, sh)
        else:
            both = jnp.asarray(stacked)
        self.ck = kvcache.with_page_table(self.ck, both[0])
        self.cv = kvcache.with_page_table(self.cv, both[1])
        if self.dck is not None:
            self.dck = kvcache.with_page_table(self.dck, both[2])
            self.dcv = kvcache.with_page_table(self.dcv, both[3])
        self._pool.dirty = False

    def _reclaim_pages(self, slot, need_free: int):
        """Two-tier reclaim under pool pressure, cheapest truth first:
          1. free slots' retained TABLES are released (their
             _cache_tokens cleared so _pick_slot stops advertising the
             prefix) — with the prefix cache on, pages it holds survive
             this with refs dropping to the cache's hold alone;
          2. prefix-cache entries are EVICTED LRU-first until enough
             pages are free (engine/prefix_cache.py).
        Purely host-side and non-blocking — admission either gets its
        pages or sees PoolExhausted from the retried alloc, never a
        deadlock against work the scheduler still has to run."""
        # ``slot`` (int or tuple) names tables reclaim must NOT release:
        # mid-admission the destination slot is still unoccupied, and a
        # share/restore source may be a free slot — freeing either would
        # invalidate pages the caller is actively splicing
        protect = slot if isinstance(slot, tuple) else (slot,)
        for i, s in enumerate(self.slots):
            if self._pool.free_pages >= need_free:
                return
            if s is None and i not in protect and self._pool.owned[i]:
                self._pool.release(i, 0)
                self._cache_tokens[i] = []
        if (self._prefetch is not None and len(self._prefetch)
                and self._pool.free_pages < need_free):
            # pool pressure outranks speculation: raid the prefetch
            # pipeline's staged pages BEFORE evicting retained chains —
            # staged pages are merely predicted-useful (their content
            # still lives in the host tier), retained chains are
            # known-useful. Counted WASTED: the prediction lost to load.
            drained = self._prefetch.drain()
            for _key, rec in drained:
                self._pool.unref_detached(rec[0])
            if drained and self._hstore is not None:
                self._hstore.note_prefetch_wasted(len(drained))
        if self._pcache is not None:
            victims = []
            on_evict = None
            if self._hstore is not None:
                # device->host handoff: collect each evicted entry while
                # its page id still names valid rows; one batched gather
                # goes out below, BEFORE any dispatch that could reuse
                # the freed pages (device program order makes the copy
                # read the pre-eviction content)
                def on_evict(e, _v=victims):
                    if not self._hstore.contains(e.key):
                        _v.append((e.key, e.parent, e.depth, e.page))
            self._pcache.evict(self._pool, need_free, on_evict)
            if victims:
                self._dispatch_offload(victims)

    def _ensure_pages(self, slot: int, rows: int):
        """Lazy page allocation with reclaim-and-retry on pool pressure."""
        if not self._paged:
            return
        from localai_tpu.engine.paging import PoolExhausted

        if FAULTS.active and FAULTS.take("page_alloc_fail") is not None:
            raise PoolExhausted("injected fault: page_alloc_fail")
        try:
            self._pool.ensure(slot, rows)
            return
        except PoolExhausted:
            pass
        self._reclaim_pages(slot, self._pool.pages_for(rows))
        if self._sched is not None:
            try:
                self._pool.ensure(slot, rows)
                return
            except PoolExhausted:
                pass
            # pool-pressure preemption (closes the PR-3 "offload ACTIVE
            # slots under extreme pressure" follow-up): pause a
            # strictly-lower-priority DECODE slot — decode-only because
            # this runs mid-prefill-pack, where a prefill-phase victim
            # could be a seg of the pack being built — then reclaim
            # again so its now-retained pages evict/offload
            me = self.slots[slot]
            my_rank = me.prio if me is not None else PRIORITY_RANK["high"]
            victim = self._pick_victim(my_rank, decode_only=True)
            if victim is not None and victim != slot:
                self._preempt_slot(victim, why="pool_pressure")
                self._reclaim_pages(slot, self._pool.pages_for(rows))
        self._pool.ensure(slot, rows)   # raises PoolExhausted if truly full

    def _alloc_detached(self, slot=-1) -> int:
        """alloc_detached with the same reclaim-and-retry discipline as
        _ensure_pages: a COW boundary clone must not fail while retained
        pages are still evictable. ``slot`` is the table being built —
        reclaim must not release it (mid-admission the slot is still
        unoccupied, so without the exclusion reclaim would free the
        pages just spliced into it)."""
        from localai_tpu.engine.paging import PoolExhausted

        try:
            return self._pool.alloc_detached()
        except PoolExhausted:
            self._reclaim_pages(slot, 1)
            return self._pool.alloc_detached()

    def _get_page_clone_fn(self):
        fn = self._fork_fns.get("page_clone")
        if fn is None:
            self._cobs.note_program("page_clone")
            fn = jax.jit(
                lambda ck, cv, src, dst: (kvcache.clone_page(ck, src, dst),
                                          kvcache.clone_page(cv, src, dst)),
                donate_argnums=(0, 1))
            self._fork_fns["page_clone"] = fn
        return fn

    def _get_draft_clone_fn(self):
        fn = self._fork_fns.get("page_clone_draft")
        if fn is None:
            self._cobs.note_program("page_clone_draft")
            fn = jax.jit(
                lambda ck, cv, src, dst: (kvcache.clone_page(ck, src, dst),
                                          kvcache.clone_page(cv, src, dst)),
                donate_argnums=(0, 1))
            self._fork_fns["page_clone_draft"] = fn
        return fn

    def _cow_guard(self, slot: int, row: int):
        """Copy-on-write: if the page containing ``row`` (the slot's first
        write position) is shared, clone it into a fresh page before any
        scatter can touch it. Pages before it stay shared — zero copies
        for the common prefix; this one page is the 'first divergent
        page' clone. The paged draft cache clones the same page id: its
        rows diverge exactly when the target's do."""
        if not self._paged:
            return
        pi = self._pool.cow_page(slot, row)
        if pi < 0:
            return
        new = self._alloc_detached(slot)
        old = int(self._pool.ptab[slot, pi])
        self._commit_ptab()
        self.ck, self.cv = self._get_page_clone_fn()(
            self.ck, self.cv, np.int32(old), np.int32(new))
        if self.dck is not None:
            self.dck, self.dcv = self._get_draft_clone_fn()(
                self.dck, self.dcv, np.int32(old), np.int32(new))
        self._pool.replace(slot, pi, new)

    def _get_offload_gather_fn(self, batch: int):
        key = ("offload_gather", batch)
        fn = self._fork_fns.get(key)
        if fn is None:
            self._cobs.note_program("offload_gather", batch)
            fn = jax.jit(lambda ck, cv, idx: (kvcache.gather_pages(ck, idx),
                                              kvcache.gather_pages(cv, idx)))
            self._fork_fns[key] = fn
        return fn

    def _get_restore_scatter_fn(self, batch: int):
        key = ("restore_scatter", batch)
        fn = self._fork_fns.get(key)
        if fn is None:
            self._cobs.note_program("restore_scatter", batch)
            fn = jax.jit(
                lambda ck, cv, idx, kr, vr: (
                    kvcache.scatter_pages(ck, idx, kr),
                    kvcache.scatter_pages(cv, idx, vr)),
                donate_argnums=(0, 1))
            self._fork_fns[key] = fn
        return fn

    def _dispatch_offload(self, victims: list):
        """Issue ONE non-blocking device gather for a batch of evicted
        pages and queue the host transfer on the sync worker. The batch
        pads to a power of two (repeat-last — duplicate reads are free)
        so only log2 gather programs ever compile."""
        t0 = time.monotonic()
        n = len(victims)
        B = 1
        while B < n:
            B *= 2
        idx = np.full((B,), victims[-1][3], np.int32)
        for i, (_k, _p, _d, page) in enumerate(victims):
            idx[i] = page
        with self._annot("kv_offload_gather"):
            k_rows, v_rows = self._get_offload_gather_fn(B)(self.ck,
                                                            self.cv, idx)
        d_rows = None
        if self.dck is not None:
            # paged draft cache (ISSUE 13): offload the draft rows of the
            # same pages so a restored spec slot resumes drafting without
            # a cold draft cache (the gather fn re-specializes per cache
            # shape under jit, so the same callable serves both)
            with self._annot("kv_offload_gather_draft"):
                d_rows = self._get_offload_gather_fn(B)(self.dck,
                                                        self.dcv, idx)
        item = _PendingOffload([(k, p, d) for k, p, d, _pg in victims],
                               k_rows, v_rows, self._hstore, d_rows)
        self._sync_q.put(item)
        self._tmark("offload_dispatch", t0)
        if self.tracer.enabled:
            self.tracer.record("offload_dispatch", "engine", t0,
                               time.monotonic(), args={"pages": n})

    def _upload_pages(self, pages: list, host_hits: list):
        """Dispatch the async host->device scatter copying ``host_hits``
        (host-tier entries) into ``pages`` (allocated device page ids,
        same order/length), draft planes included — the shared upload
        half of _restore_offloaded, the windowed admission, and the
        prefetch tick. Pure dispatch: no table edits, no host syncs; by
        device program order the copy completes before any later
        dispatch reads the rows."""
        pool = self._pool
        n = len(host_hits)
        B = 1
        while B < n:
            B *= 2
        # sentinel-pad the scatter batch: out-of-pool page ids DROP
        idx = np.full((B,), pool.num_pages, np.int32)
        idx[:n] = pages[:n]

        # double-buffered staging (PR-3 follow-up): the async scatter
        # dispatched below may still be READING the previous parity's
        # buffers while this batch fills the other set — reuse without
        # aliasing, and no per-restore stack/concatenate allocations
        par = self._rstager.begin()
        ks = self._rstager.fill(par, "k", host_hits, lambda e: e.k, B)
        vs = self._rstager.fill(par, "v", host_hits, lambda e: e.v, B)

        with self._annot("kv_restore_scatter"):
            self.ck, self.cv = self._get_restore_scatter_fn(B)(
                self.ck, self.cv, idx, ks, vs)
        # paged draft cache (ISSUE 13): restore the draft rows of any hit
        # that carried them (entries offloaded pre-draft, loaded from an
        # old disk snapshot, or whose draft payload failed its CRC have
        # dk None — their draft rows stay cold, which is merely an
        # acceptance-rate hit, never a correctness one)
        dhits = [(j, e) for j, e in enumerate(host_hits)
                 if e.dk is not None] if self.dck is not None else []
        if dhits:
            B2 = 1
            while B2 < len(dhits):
                B2 *= 2
            didx = np.full((B2,), pool.num_pages, np.int32)
            for c, (j, _e) in enumerate(dhits):
                didx[c] = pages[j]
            dents = [e for _j, e in dhits]
            dks = self._rstager.fill(par, "dk", dents, lambda e: e.dk, B2)
            dvs = self._rstager.fill(par, "dv", dents, lambda e: e.dv, B2)
            with self._annot("kv_restore_scatter_draft"):
                self.dck, self.dcv = self._get_restore_scatter_fn(B2)(
                    self.dck, self.dcv, didx, dks, dvs)

    def _restore_offloaded(self, slot: int, host_hits: list) -> int:
        """Upload offloaded pages into freshly allocated device rows and
        splice them onto the slot's table — DISPATCH-THEN-SPLICE: the
        host->device copy is issued as one async jit call (it overlaps
        whatever decode bursts are already in flight; by device program
        order it completes before the slot's prefill reads the rows),
        the table edit is pure host work, and the serving loop never
        syncs. Partial allocation under pool pressure degrades to a
        shorter restored chain (still contiguous from the root).
        Returns the number of pages actually restored."""
        pool = self._pool
        pages = pool.alloc_many(len(host_hits))
        if len(pages) < len(host_hits):
            self._reclaim_pages(slot, len(host_hits) - len(pages))
            pages.extend(pool.alloc_many(len(host_hits) - len(pages)))
        host_hits = host_hits[:len(pages)]
        if not host_hits:
            for p in pages:
                pool.unref_detached(p)
            return 0
        t0 = time.monotonic()
        n = len(host_hits)
        self._upload_pages(pages, host_hits)
        for e, p in zip(host_hits, pages[:n]):
            pool.adopt(slot, p)
            # restored pages re-enter the device tier immediately: the
            # attach hold makes refs >= 2, so the admitting prefill's
            # boundary write COW-clones instead of corrupting the copy
            self._pcache.attach(pool, e.key, e.parent, p, e.depth)
        self._hstore.note_restore(n)
        self._tmark("restore_dispatch", t0)
        if self.tracer.enabled:
            self.tracer.record("restore_dispatch", "engine", t0,
                               time.monotonic(), args={"pages": n})
        return n

    def _share_prefix(self, src: int, dst: int, rows: int) -> int:
        """Zero-copy prefix transfer: full pages covering rows[0:rows] are
        ref-count-shared into dst's table; when the prefix ends mid-page,
        that FIRST DIVERGENT page is cloned (one page copy, never a row
        loop) so dst reuses exactly ``rows`` rows."""
        shared = self._pool.share(src, dst, rows)
        if shared < rows:
            pi = shared // self._pool.page_size
            new = self._alloc_detached((src, dst))
            src_page = int(self._pool.ptab[src, pi])
            self._commit_ptab()
            self.ck, self.cv = self._get_page_clone_fn()(
                self.ck, self.cv, np.int32(src_page), np.int32(new))
            if self.dck is not None:
                self.dck, self.dcv = self._get_draft_clone_fn()(
                    self.dck, self.dcv, np.int32(src_page), np.int32(new))
            self._pool.adopt(dst, new)
            shared = rows
        return shared

    def _prefetch_tick(self):
        """Decode-time prefetch-ahead (ISSUE 16, tentpole): scan the
        admission queue's head, predict which HOST-TIER chain links each
        request's admission will restore, and upload them into detached
        device pages NOW — overlapped with the decode bursts already in
        flight — so the admission finds the rows resident and the
        synchronous restore cost drops off TTFT (PRESERVE,
        arXiv:2501.08192). Window-aware: with the snap-back window armed
        only the sink + tail-window links are fetched, so speculation
        never pulls the cold middle a windowed admission would skip.
        Never evicts truth for speculation: fetches stop at the pool's
        free headroom and a failed alloc simply ends the pass."""
        pf = self._prefetch
        pf.tick += 1
        expired = pf.expire()
        if expired:
            for _key, rec in expired:
                self._pool.unref_detached(rec[0])
            self._hstore.note_prefetch_wasted(len(expired))
        ahead = max(1, int(self.ecfg.kv_prefetch_ahead))
        with self._queue.mutex:
            reqs = list(self._queue.queue)[:ahead]
        if not reqs:
            return
        pool = self._pool
        pg = pool.page_size
        C = self.ecfg.max_context
        budget = 4 * ahead * max(1, self._win_pages or 8)  # pages/tick
        for req in reqs:
            if budget <= 0:
                break
            rid = req.request_id
            if rid in pf.seen_rids or req.mm_vectors is not None:
                continue
            pf.seen_rids.add(rid)
            ids = list(req.prompt_ids)
            # mirror _start_request's head truncation — keys past it
            # would be fetched for a prompt that will never admit them
            max_prompt = C - 1 - min(req.max_new_tokens, C // 4)
            if len(ids) > max_prompt:
                ids = ids[-max_prompt:]
            n_links = (len(ids) - 1) // pg
            if n_links <= 0:
                continue
            keys = []
            for i, key in enumerate(self._pcache.chain_keys(ids)):
                if i >= n_links:
                    break
                keys.append(key)
            d = 0                      # device-resident chain depth
            while d < len(keys) and self._pcache.contains(keys[d]):
                d += 1
            n_avail = d
            while n_avail < len(keys) and (
                    keys[n_avail] in pf.pages
                    # contains_any (ISSUE 17): a chain link held only by
                    # a PEER host still counts as available — the get()
                    # below streams it through the federated tier, so
                    # prefetch-ahead rides the transport (PRESERVE
                    # across hosts)
                    or self._hstore.contains_any(keys[n_avail])):
                n_avail += 1
            if n_avail <= d:
                continue
            sink, W = self._win_sink, self._win_pages
            if W and n_avail > sink + W:
                wanted = list(range(sink)) + list(range(n_avail - W,
                                                        n_avail))
            else:
                wanted = list(range(n_avail))
            fetch = [i for i in wanted
                     if i >= d and keys[i] not in pf.pages][:budget]
            if not fetch:
                continue
            if pool.free_pages < len(fetch) + 4:
                break                  # headroom guard: truth first
            ents = []
            for i in fetch:
                e = self._hstore.get(keys[i])
                if e is None:
                    break              # hole opened since the probe
                ents.append(e)
            if not ents:
                continue
            pages = pool.alloc_many(len(ents))
            if len(pages) < len(ents):
                # speculation never reclaims: give back and stop
                for p in pages:
                    pool.unref_detached(p)
                break
            self._upload_pages(pages, ents)
            for e, p in zip(ents, pages):
                pf.register(e.key, e.parent, p, e.depth)
            self._hstore.note_prefetch_issued(len(ents))
            budget -= len(ents)
            # completion probe rides the sync worker in dispatch order:
            # a scalar slice of the post-scatter cache blocks exactly
            # until this batch's upload executed, then retires the
            # store's inflight gauge — the /debug/kv restore depth
            leaf = jax.tree.leaves(self.ck)[0]
            self._sync_q.put(_PendingPrefetch(
                [], leaf[(0,) * leaf.ndim], None, self._hstore))

    def _abs_chain_keys(self, slot: int, s, upto_page: int) -> list:
        """Absolute chain keys for the slot's first ``upto_page`` full
        pages, extended incrementally from its absolute token history
        and cached on the slot (ISSUE 16). A windowed slot's compact
        table no longer maps 1:1 onto its token stream, so window
        advance / release derive offload keys from the ABSOLUTE stream
        — O(new pages) per call, not O(context) per advance."""
        keys = s.chain_keys
        toks = self._cache_tokens[slot]
        pg = self._pool.page_size
        upto_page = min(upto_page, len(toks) // pg)
        if len(keys) < upto_page:
            parent = keys[-1] if keys else kvcache.PAGE_HASH_ROOT
            scope = self._pcache.scope
            for i in range(len(keys), upto_page):
                parent = kvcache.page_chain_hash(
                    parent, toks[i * pg:(i + 1) * pg], scope)
                keys.append(parent)
        return keys

    def _advance_window(self, i: int, upcoming: int):
        """Snap-back window advance (ISSUE 16): before dispatching work
        that would push slot i's compact rows past the bounded working
        set ((sink + window) pages), demote the oldest non-sink FULL
        committed pages out of the table. Policy "demote" first
        offloads their content to the host tier (the async gather is
        dispatched BEFORE pool.demote can recycle the pages — device
        program order protects the copy, same as _reclaim_pages);
        policy "drop" records an explicit ledger "compress" op instead,
        so the auditor sees the rows leave by policy, not by leak.
        Compact coordinates then re-base: lengths/committed/written
        shrink by the demoted rows while pos_offset/win_off grow by the
        same amount — RoPE positions stay ABSOLUTE — and _win_delta
        carries the length rebase into an in-flight decode chain
        without forcing an override."""
        s = self.slots[i]
        if (not self._win_pages or not self._paged or s is None
                or s.mm_pos is not None or self.ecfg.ga_n > 1):
            # ga rotation owns pos_offset; the window never composes
            # with it (windowed admission is already ga-gated too)
            return
        pool = self._pool
        pg = pool.page_size
        sink = self._win_sink
        rows = max(int(self.lengths[i]), s.written) + max(0, upcoming)
        budget = (sink + self._win_pages) * pg
        if rows <= budget:
            return
        k = pool.pages_for(rows - budget)
        # only fully COMMITTED pages may leave (uncommitted speculative
        # rows must stay rollback-able), and never the sinks
        k = min(k, s.committed // pg - sink)
        if k <= 0:
            return
        start_abs = s.win_off // pg + sink
        if self.ecfg.kv_window_policy == "demote":
            victims = []
            keys = self._abs_chain_keys(i, s, start_abs + k)
            for t in range(min(k, len(keys) - start_abs)):
                ap = start_abs + t
                if self._hstore.contains(keys[ap]):
                    continue
                parent = keys[ap - 1] if ap > 0 else kvcache.PAGE_HASH_ROOT
                victims.append((keys[ap], parent, ap,
                                int(pool.ptab[i, sink + t])))
            if victims:
                self._dispatch_offload(victims)
        elif pool.audit is not None:
            # drop policy: the middle rows are compressed away — a
            # first-class lifecycle op, not a leak
            pool.audit.ledger.record("compress", slot=i)
        pool.demote(i, sink, k)
        delta = k * pg
        self.lengths[i] -= delta
        self.pos_offset[i] += delta
        s.win_off += delta
        s.committed -= delta
        s.written -= delta
        s.cache_len = max(0, s.cache_len - delta)
        if self._chain is not None:
            self._win_delta[i] += delta

    def _windowed_admission(self, slot: int, ids: list, cap: int,
                            cached_pages: list, rid: str = ""):
        """Snap-back window at (re-)admission (ISSUE 16): when the
        two-tier chain covers more of the prompt than the bounded
        on-device working set (sink + window pages), splice/restore ONLY
        the attention-sink head and the tail window. The cold middle
        never touches the device — it stays retained device-side or in
        the host tier — and the slot's compact row coordinates re-base
        by ``win_off`` = the skipped middle rows (positions stay
        absolute via pos_offset). Returns the compact reused row count
        (stashing self._adm_win_off for _start_request), or None to fall
        through to the unwindowed admission path."""
        pool = self._pool
        pg = pool.page_size
        sink, W = self._win_sink, self._win_pages
        d = len(cached_pages)
        # phase 1: availability over the whole chain with cheap
        # membership probes only — no LRU touch, no CRC on the middle
        # links the selection will skip (a 128k chain must not pay a
        # full-store CRC walk per admission)
        keys = []
        for i, key in enumerate(self._pcache.chain_keys(ids)):
            if i >= cap // pg:
                break           # always leave >= 1 token to prefill
            keys.append(key)
        n_avail = d
        while n_avail < len(keys):
            key = keys[n_avail]
            if ((self._prefetch is not None
                 and key in self._prefetch.pages)
                    # contains_any (ISSUE 17): peer-held links count as
                    # available — the selected links' get() streams them
                    # in through the federated tier; a probe/get race
                    # (peer died in between) is the same handled hole as
                    # a local CRC drop
                    or self._hstore.contains_any(key)):
                n_avail += 1
            else:
                break
        n_avail = min(n_avail, len(keys))
        if n_avail <= sink + W:
            return None         # fits the working set: no window needed
        while True:
            sel = list(range(sink)) + list(range(n_avail - W, n_avail))
            # device-resident selected links are always a PREFIX of the
            # compact order (the device tier is prefix-closed, so the
            # links it holds are exactly [0, d))
            splice_pages = [cached_pages[i] for i in sel if i < d]
            rest = [i for i in sel if i >= d]
            fetched = []        # (abs link, key, prefetch rec | entry)
            failed_at = -1
            for i in rest:
                key = keys[i]
                rec = (self._prefetch.claim(key)
                       if self._prefetch is not None else None)
                if rec is not None:
                    fetched.append((i, key, rec))
                    continue
                e = self._hstore.get(key)
                if e is None:
                    failed_at = i
                    break
                fetched.append((i, key, e))
            if failed_at < 0:
                break
            # a link vanished between probe and get (budget eviction,
            # CRC drop): shrink availability to the hole and reselect;
            # claimed prefetch pages go back on the shelf first
            for i, key, rec in fetched:
                if isinstance(rec, list):
                    self._prefetch.register(key, rec[1], rec[0], rec[2])
            n_avail = failed_at
            if n_avail <= sink + W:
                return None
        ents = [r for _i, _k, r in fetched if not isinstance(r, list)]
        pages = pool.alloc_many(len(ents))
        if len(pages) < len(ents):
            self._reclaim_pages(slot, len(ents) - len(pages))
            pages.extend(pool.alloc_many(len(ents) - len(pages)))
        if len(pages) < len(ents):
            # a partial window would leave holes mid-table — give the
            # pages back and let the unwindowed path degrade gracefully
            for p in pages:
                pool.unref_detached(p)
            for i, key, rec in fetched:
                if isinstance(rec, list):
                    self._prefetch.register(key, rec[1], rec[0], rec[2])
            return None
        pool.release(slot, 0)
        pool.splice(slot, splice_pages)
        if ents:
            self._upload_pages(pages, ents)
        pi = 0
        n_pre = 0
        for i, key, rec in fetched:
            if isinstance(rec, list):
                page = rec[0]       # prefetched: rows already on device
                n_pre += 1
            else:
                page = pages[pi]
                pi += 1
            pool.adopt(slot, page)
            if i < sink:
                # device-tier re-entry only for links that keep the tier
                # prefix-closed (the contiguous sink continuation of the
                # device chain); tail-window pages ride the table alone
                # and free with it
                self._pcache.attach(
                    pool, key,
                    rec[1] if isinstance(rec, list) else rec.parent,
                    page, i)
        if n_pre:
            self._hstore.note_prefetch_hit(n_pre)
        if ents:
            self._hstore.note_restore(len(ents))
            if (self._prefetch is not None
                    and rid in self._prefetch.seen_rids):
                # the pipeline scanned this request but the admission
                # still restored synchronously: the prefetch was LATE
                self._hstore.note_prefetch_late(len(ents))
        middle = n_avail - sink - W
        self._adm_win_off = middle * pg
        if pool.audit is not None:
            # first-class ledger op: the middle of the chain was
            # window-compressed out of the on-device working set
            pool.audit.ledger.record("compress", slot=slot)
        compact = (sink + W) * pg
        self._cow_guard(slot, compact)
        self._pcache.note_hit(compact)
        return compact

    def _paged_admission(self, slot: int, ids: list, common: int,
                         rid: str = "") -> int:
        """Paged prefix reuse at admission. Returns the reusable row
        count. Four tiers, best (longest usable prefix) wins:
          1. the slot's OWN retained rows (common — free, pages already
             owned);
          2. another slot's prefix, shared COPY-ON-WRITE (_share_prefix):
             zero KV row copies for the full pages, at most one page
             clone at the divergence boundary; only rows that are
             read-only for the source (committed prompt rows of an
             active slot / retained rows of a free one) are eligible;
          3. the CROSS-RELEASE prefix cache (engine/prefix_cache.py):
             the prompt's chained page hashes are matched against
             retained pages and the chain is spliced into the slot's
             table — zero copies, works after the source slot is gone;
          4. none — pages released for reuse by the pool.
        Tiers 2 and 3 share the min-rows guard (kv_prefix_cache_min_rows)
        so a 1-page BOS match never forces the slow continued-prefill
        path, and either way the first page this request will write is
        COW-guarded. With the snap-back window armed (ISSUE 16) a chain
        longer than the working set takes _windowed_admission instead,
        which sets self._adm_win_off; this method always resets it."""
        pool = self._pool
        self._adm_win_off = 0
        min_rows = max(1, self.ecfg.kv_prefix_cache_min_rows)
        cap = len(ids) - 1              # always leave >= 1 token to prefill
        best_src, best_rows = -1, 0
        if self.ecfg.ga_n <= 1:
            # cross-slot scan (self-extend rewrites cached keys in place,
            # so sharing is gated off under ga — rotation would corrupt
            # the other referents' view)
            for j, sj in enumerate(self.slots):
                if j == slot:
                    continue
                toks = self._cache_tokens[j]
                limit = len(toks) if sj is None else min(sj.committed,
                                                         sj.prompt_len)
                if sj is not None and sj.win_off > 0:
                    # a windowed live source only retains its sink pages
                    # as a contiguous absolute prefix — everything past
                    # them sits at compact (shifted) rows share() must
                    # never alias
                    limit = min(limit, self._win_sink * pool.page_size)
                limit = min(limit, cap, pool.slot_rows_capacity(j))
                n = 0
                for a, b in zip(toks[:limit], ids):
                    if a != b:
                        break
                    n += 1
                if n > best_rows:
                    best_src, best_rows = j, n
        if self._pcache is not None:
            cached_pages = self._pcache.match(ids, pool.max_pages)
            if (self._win_pages and self._hstore is not None
                    and self.ecfg.ga_n <= 1):
                win = self._windowed_admission(slot, ids, cap,
                                               cached_pages, rid=rid)
                if win is not None:
                    return win
            if self.ecfg.ga_n > 1:
                # self-extend composition (ISSUE 16 satellite): only rows
                # inside the COMPRESSED region of the new request are
                # byte-reusable — a compressed row's grouped position
                # depends solely on its absolute index, never on the
                # block count, so rows both sides have compressed agree
                # exactly while the raw tail does not. The scope already
                # pins ga_n/ga_w; the release path inserts only
                # fully-compressed pages under the same rule.
                cap = min(cap, self._ga_c(len(ids)) * self.ecfg.ga_w)
            host_hits = []
            pre_keys = []
            if self._hstore is not None:
                # TWO-TIER chain walk: the device tier is prefix-closed
                # (eviction cascades subtrees), so the host tier can only
                # CONTINUE the chain past the device pages — same key
                # sequence, links [d, h) served from offloaded copies.
                # Prefetched links (ISSUE 16) are claimed first while
                # they are the CONTIGUOUS continuation — their rows are
                # already on device, so they cost an adopt, not a
                # restore.
                want = min(pool.max_pages, cap // pool.page_size + 1)
                for i, key in enumerate(self._pcache.chain_keys(ids)):
                    if i < len(cached_pages):
                        continue
                    if (len(cached_pages) + len(pre_keys)
                            + len(host_hits) >= want):
                        break
                    if (self._prefetch is not None and not host_hits
                            and key in self._prefetch.pages):
                        pre_keys.append(key)
                        continue
                    e = self._hstore.get(key)
                    if e is None:
                        break
                    host_hits.append(e)
            cached_rows = min(
                (len(cached_pages) + len(pre_keys) + len(host_hits))
                * pool.page_size, cap)
            if cached_rows >= min_rows and cached_rows > max(common,
                                                            best_rows):
                pool.release(slot, 0)
                pool.splice(slot, cached_pages)
                n_pre = 0
                for key in pre_keys:
                    rec = self._prefetch.claim(key)
                    if rec is None:     # claimed away mid-admission
                        break
                    # the pipeline's detached reference transfers to the
                    # table; attach re-enters the device tier (the chain
                    # stays prefix-closed — these links continue it)
                    pool.adopt(slot, rec[0])
                    self._pcache.attach(pool, key, rec[1], rec[0], rec[2])
                    n_pre += 1
                if n_pre:
                    self._hstore.note_prefetch_hit(n_pre)
                if n_pre < len(pre_keys):
                    host_hits = []      # chain has a hole past the claim
                restored = 0
                if host_hits:
                    # dispatch-then-splice (see _restore_offloaded): the
                    # upload overlaps in-flight decode work; a partial
                    # restore under pool pressure shortens the reuse,
                    # never fails the admission
                    restored = self._restore_offloaded(slot, host_hits)
                    if (self._prefetch is not None
                            and rid in self._prefetch.seen_rids):
                        # scanned by the pipeline, restored sync anyway:
                        # the prefetch lost the race — LATE
                        self._hstore.note_prefetch_late(restored)
                cached_rows = min(
                    (len(cached_pages) + n_pre + restored)
                    * pool.page_size, cap)
                if cached_rows == 0:
                    # pathological: nothing spliced and nothing restored
                    self._pcache.note_miss()
                    return 0
                # a retained page re-entering a table carries refs >= 2
                # (table + cache hold), so the existing COW guard clones
                # the boundary page before the first prefill write —
                # cached rows are immutable by construction
                self._cow_guard(slot, cached_rows)
                self._pcache.note_hit(cached_rows)
                return cached_rows
            if self._hstore is not None and not host_hits and not pre_keys \
                    and len(ids) // pool.page_size > len(cached_pages):
                # the host tier was consulted past the device chain and
                # had nothing usable — the restore-miss path: plain
                # prefill, byte-identical to PR-2 behavior
                self._hstore.note_miss()
            self._pcache.note_miss()
        if best_rows > common and best_rows >= min_rows:
            pool.release(slot, 0)
            return self._share_prefix(best_src, slot, best_rows)
        pool.release(slot, common)
        if common:
            self._cow_guard(slot, common)
        return common

    # ---------- jitted step bodies ----------

    def _compose_overrides(self, tokens, lengths, ring, ring_pos, mu, ov_pack):
        """Merge host override rows (ONE packed [7+RING_N, S] f32 upload:
        mask, tokens, lengths, ring_pos, mu, pos_offset, win_delta,
        ring.T) into the chain state. pos_offset (self-extend / snap-back
        window) is NOT override-gated — it is current host truth every
        dispatch. win_delta (ISSUE 16) is an unconditional SUBTRACT from
        the chained device lengths: a window advance re-bases a slot's
        compact rows mid-chain without forcing an override (and therefore
        without a host sync); overridden slots carry already-rebased host
        lengths, so _pack_ov zeroes their delta to avoid double-counting."""
        ov_mask = ov_pack[0] > 0
        tokens = jnp.where(ov_mask, ov_pack[1].astype(jnp.int32), tokens)
        lengths = jnp.where(ov_mask, ov_pack[2].astype(jnp.int32), lengths) \
            - ov_pack[6].astype(jnp.int32)
        ring_pos = jnp.where(ov_mask, ov_pack[3].astype(jnp.int32),
                             jnp.asarray(ring_pos))
        mu = jnp.where(ov_mask, ov_pack[4], jnp.asarray(mu))
        pos_offset = ov_pack[5].astype(jnp.int32)
        ring = jnp.where(ov_mask[:, None], ov_pack[7:].T.astype(jnp.int32),
                         jnp.asarray(ring))
        return tokens, lengths, ring, ring_pos, mu, pos_offset

    def _decode_burst_body(self, params, tokens, ck, cv, lengths, ring, ring_pos,
                           bias, keys, slot_params, active, mu,
                           ov_pack, n_steps: int,
                           flags: tuple = (True, True, True)):
        """n_steps decode+sample steps in ONE dispatch (lax.scan).

        Per-dispatch overhead on the serving chip is comparable to one step's
        compute, so bursts are the single biggest serving-throughput lever.
        bias/slot_params/active are constant across the burst.

        tokens/lengths/ring/ring_pos/mu arrive as the previous burst's
        DEVICE output handles (the chain); ov_pack carries host rows
        composed in for newly activated / rolled-back / re-admitted slots —
        so host events never force a chain rebuild (and therefore never
        force the host to wait on an in-flight burst before it can
        dispatch the next one)."""
        slot_params = sampling.unpack_slot_params(slot_params)
        tokens, lengths, ring, ring_pos, mu, pos_offset = \
            self._compose_overrides(tokens, lengths, ring, ring_pos, mu,
                                    ov_pack)

        step = self._make_scan_step(params, slot_params, bias, active, flags,
                                    pos_offset)
        carry = (tokens, ck, cv, lengths, ring, ring_pos, keys, mu)
        carry, (ids_all, lps_all) = jax.lax.scan(step, carry, None, length=n_steps)
        tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
        # tokens/lengths/ring/mu are returned as DEVICE handles so the next
        # burst can chain off them without a host round-trip (pipelined
        # decode). Everything the host needs (ids, logprobs, post-burst mu)
        # is PACKED into one [2K+1, S] float32 array: on the serving tunnel
        # each device->host transfer costs ~60-100 ms of pure latency, so
        # three separate tiny syncs per burst were the loop bottleneck.
        # float32 holds token ids exactly (vocab << 2^24).
        pack = jnp.concatenate(
            [ids_all.astype(jnp.float32), lps_all, mu[None, :]], axis=0)
        return pack, ck, cv, keys, (tokens, lengths, ring, ring_pos, mu)

    def _make_scan_step(self, params, slot_params, bias, active, flags,
                        pos_offset=None):
        """The shared decode+sample scan step for plain and fused bursts.

        Inactive slots (free / mid-prefill) must NOT advance their cache
        state (the family adapter masks KV writes / state updates), and
        only active slots consume RNG/mirostat/ring state: a prefilling
        slot's seeded state must not advance with others' decode steps."""

        def step(carry, _):
            tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
            logits, ck, cv = self.family.engine_decode(
                params, self.cfg, tokens, lengths, active, ck, cv,
                pos_offset=pos_offset)
            ids, logprobs, new_keys, new_mu = sampling.sample(
                logits, slot_params, ring, ring_pos, bias, keys, mu,
                use_penalties=flags[0], use_typical=flags[1],
                use_mirostat=flags[2])
            keys = jnp.where(active[:, None], new_keys, keys)
            mu = jnp.where(active, new_mu, mu)
            ring, ring_pos = sampling.update_ring(ring, ring_pos, ids, active)
            lengths = lengths + active.astype(jnp.int32)
            tokens = jnp.where(active, ids, tokens)
            return (tokens, ck, cv, lengths, ring, ring_pos, keys, mu), (ids, logprobs)

        return step

    def _prefill_chunk_body(self, params, tokens, seq_len, ck, cv, slot, start_pos,
                            mm_pos=None, mm_vec=None):
        """Non-final chunk: write KV only, no sampling. (The penalty ring is
        seeded host-side at admission from the full prompt tail.)"""
        _, ck, cv = self.family.prefill(params, self.cfg, tokens, seq_len, ck,
                                        cv, slot, start_pos, continued=True,
                                        mm_pos=mm_pos, mm_vec=mm_vec)
        return ck, cv

    def _fused_body(self, params, tokens, ck, cv, lengths, ring, ring_pos,
                    bias, keys, slot_params, active, mu,
                    ov_pack, p_tokens, p_seq, p_slots, p_start,
                    n_steps: int):
        """FUSED admission: final-prefill a batch of B fresh prompts,
        sample their first tokens, and run the decode burst with those
        slots already active — all in ONE dispatch.

        r4 measurement: separate dispatches cost ~30 ms of device overhead
        each on the serving tunnel, and the prefill->host->activate
        round-trip idled the admitted slots for 100-300 ms more. Fusing
        collapses both, and makes singleton admissions as cheap as batched
        ones, so admission never holds requests back to form groups.
        (The reference packs prompt chunks and decode tokens into one
        llama_batch for the same reason — grpc-server.cpp:1671+.)

        Duplicate p_slots entries (pow2 batch padding repeats the last
        prompt) stay idempotent: every per-slot update is a .set() of
        identical values (same inputs -> same sampled id).

        Admission cost note (r5 measurement, 8B-int8 + int8 KV, 32 slots
        on the serving chip): this sequential prefill-then-burst body adds
        only ~14 ms over a plain burst dispatch. A concatenated
        prefill+decode forward sharing weight reads
        (models/llama.py:fused_prefill_decode) was built and measured at
        ~68 ms extra — the concat/slice layout copies cost far more than
        the shared reads save on this stack — so the sequential form is
        the keeper."""
        slot_params = sampling.unpack_slot_params(slot_params)
        tokens, lengths, ring, ring_pos, mu, pos_offset = \
            self._compose_overrides(tokens, lengths, ring, ring_pos, mu,
                                    ov_pack)

        logits, ck, cv = self.family.prefill(params, self.cfg, p_tokens,
                                             p_seq, ck, cv, p_slots, p_start,
                                             continued=False)
        sp_rows = jax.tree.map(lambda a: jnp.take(jnp.asarray(a), p_slots,
                                                  axis=0), slot_params)
        rpos_rows = jnp.take(ring_pos, p_slots, axis=0)
        ids_f, lps_f, new_keys, new_mu = sampling.sample(
            logits, sp_rows,
            jnp.take(ring, p_slots, axis=0), rpos_rows,
            jnp.take(bias, p_slots, axis=0),
            jnp.take(keys, p_slots, axis=0),
            jnp.take(mu, p_slots, axis=0))
        keys = keys.at[p_slots].set(new_keys)
        mu = mu.at[p_slots].set(new_mu)
        lengths = lengths.at[p_slots].set(p_start + p_seq)
        tokens = tokens.at[p_slots].set(ids_f)
        # the sampled first token enters the penalty ring (idempotent form)
        ring = ring.at[p_slots, rpos_rows % sampling.RING_N].set(ids_f)
        ring_pos = ring_pos.at[p_slots].set(rpos_rows + 1)
        active = jnp.asarray(active).at[p_slots].set(True)

        # fused bursts always run the full sampler (one compiled variant
        # per (bucket, B); a flags dimension would double the precompile
        # set for a small sampler saving)
        step = self._make_scan_step(params, slot_params, bias, active,
                                    (True, True, True), pos_offset)
        carry = (tokens, ck, cv, lengths, ring, ring_pos, keys, mu)
        carry, (ids_all, lps_all) = jax.lax.scan(step, carry, None,
                                                 length=n_steps)
        tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
        S = self.ecfg.num_slots
        first_ids = jnp.zeros((S,), jnp.float32).at[p_slots].set(
            ids_f.astype(jnp.float32))
        first_lps = jnp.zeros((S,), jnp.float32).at[p_slots].set(lps_f)
        pack = jnp.concatenate(
            [ids_all.astype(jnp.float32), lps_all, mu[None, :],
             first_ids[None, :], first_lps[None, :]], axis=0)
        return pack, ck, cv, keys, (tokens, lengths, ring, ring_pos, mu)

    def _get_fused_fn(self, bucket: int, batch: int):
        key = ("fused", bucket, batch)
        fn = self._burst_fns.get(key)
        if fn is None:
            self._cobs.note_program("prefill_fused", (bucket, batch))
            fn = jax.jit(
                lambda *a: self._fused_body(*a, n_steps=self.ecfg.decode_burst),
                donate_argnums=(2, 3, 8))
            self._burst_fns[key] = fn
        return fn

    def _prefill_final_body(self, params, tokens, seq_len, ck, cv, slot, start_pos,
                            ring, ring_pos, bias, keys, slot_params, mu,
                            continued: bool, mm_pos=None, mm_vec=None,
                            positions=None):
        """Final chunk for a BATCH of B prompts: write KV, sample each one's
        first output token. slot may contain duplicate entries (batch
        padding repeats the last prompt; duplicate KV writes and key
        scatters are idempotent — same inputs, last write wins)."""
        logits, ck, cv = self.family.prefill(
            params, self.cfg, tokens, seq_len, ck, cv, slot, start_pos,
            continued=continued, mm_pos=mm_pos, mm_vec=mm_vec,
            positions=positions)
        slot_params = sampling.unpack_slot_params(slot_params)
        sp_rows = jax.tree.map(lambda a: jnp.take(jnp.asarray(a), slot, axis=0),
                               slot_params)
        bias_rows = jnp.take(bias, slot, axis=0)
        key_rows = jnp.take(keys, slot, axis=0)
        ring_rows = jnp.take(jnp.asarray(ring), slot, axis=0)
        rpos_rows = jnp.take(jnp.asarray(ring_pos), slot, axis=0)
        mu_rows = jnp.take(jnp.asarray(mu), slot, axis=0)
        ids, logprobs, new_keys, new_mu = sampling.sample(
            logits, sp_rows, ring_rows, rpos_rows, bias_rows, key_rows, mu_rows)
        keys = keys.at[slot].set(new_keys)
        mu = jnp.asarray(mu).at[slot].set(new_mu)
        return ids, logprobs, ck, cv, keys, mu

    def _packed_prefill_body(self, params, tokens, positions, seg_of,
                             seg_slots, seg_start, seg_off, seg_len,
                             final_mask, ck, cv, ring, ring_pos, bias, keys,
                             slot_params, mu, continued: bool):
        """RAGGED PACKED PREFILL step (one compiled program per
        (total-token bucket, continued?)): every segment's KV rows are
        written through its own slot's page table, FINAL segments (their
        slot's whole remaining prompt fits this pack) sample their first
        output token, non-final segments only write KV — the
        generalization of the fused final-prefill groups to arbitrary
        fresh/continued mixes. Pad segments carry the slot sentinel S,
        so their state writes DROP and their RNG is never consumed; a
        real non-final segment's gated write puts its OWN old value
        back (slots are unique per pack, so the scatter stays
        well-defined)."""
        logits, ck, cv = self.family.ragged_prefill(
            params, self.cfg, tokens, positions, seg_of, seg_slots,
            seg_start, seg_off, seg_len, ck, cv, continued=continued,
            comm_overlap=self._comm_overlap)
        slot_params = sampling.unpack_slot_params(slot_params)
        sp_rows = jax.tree.map(
            lambda a: jnp.take(jnp.asarray(a), seg_slots, axis=0),
            slot_params)
        ring_rows = jnp.take(jnp.asarray(ring), seg_slots, axis=0)
        rpos_rows = jnp.take(jnp.asarray(ring_pos), seg_slots, axis=0)
        bias_rows = jnp.take(bias, seg_slots, axis=0)
        key_rows = jnp.take(keys, seg_slots, axis=0)
        mu_rows = jnp.take(jnp.asarray(mu), seg_slots, axis=0)
        ids, logprobs, new_keys, new_mu = sampling.sample(
            logits, sp_rows, ring_rows, rpos_rows, bias_rows, key_rows,
            mu_rows)
        keys = keys.at[seg_slots].set(
            jnp.where(final_mask[:, None], new_keys, key_rows),
            mode="drop")
        mu = jnp.asarray(mu).at[seg_slots].set(
            jnp.where(final_mask, new_mu, mu_rows), mode="drop")
        return ids, logprobs, ck, cv, keys, mu

    def _get_packed_fn(self, bucket: int, continued: bool):
        key = ("packed", bucket, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            self._cobs.note_program("prefill_pack", (bucket, continued))
            fn = jax.jit(
                lambda *a: self._packed_prefill_body(*a,
                                                     continued=continued),
                donate_argnums=(9, 10, 14))
            self._final_fns[key] = fn
        return fn

    def _fused_packed_body(self, params, tokens, ck, cv, lengths, ring,
                           ring_pos, bias, keys, slot_params, active, mu,
                           ov_pack, p_tokens, p_positions, seg_of, seg_slots,
                           seg_start, seg_off, seg_len, final_mask,
                           n_steps: int, continued: bool):
        """FUSED packed admission — the packed generalization of
        _fused_body: ragged-prefill EVERY queued segment (fresh or
        continued), sample first tokens for the FINAL segments, and run
        the decode burst with those slots already active — all in ONE
        dispatch. This is the full llama_batch analogue (module doc):
        under load one tick costs one dispatch for prompt ingestion AND
        decode, so admission latency stops scaling with the number of
        pending prompts. Pad / non-final segments are gated exactly as
        in _packed_prefill_body (sentinel slots drop, finals-only state
        writes)."""
        sp = sampling.unpack_slot_params(slot_params)
        tokens, lengths, ring, ring_pos, mu, pos_offset = \
            self._compose_overrides(tokens, lengths, ring, ring_pos, mu,
                                    ov_pack)

        logits, ck, cv = self.family.ragged_prefill(
            params, self.cfg, p_tokens, p_positions, seg_of, seg_slots,
            seg_start, seg_off, seg_len, ck, cv, continued=continued,
            comm_overlap=self._comm_overlap)
        sp_rows = jax.tree.map(
            lambda a: jnp.take(jnp.asarray(a), seg_slots, axis=0), sp)
        ring_rows = jnp.take(ring, seg_slots, axis=0)
        rpos_rows = jnp.take(ring_pos, seg_slots, axis=0)
        ids_f, lps_f, new_keys, new_mu = sampling.sample(
            logits, sp_rows, ring_rows, rpos_rows,
            jnp.take(bias, seg_slots, axis=0),
            jnp.take(keys, seg_slots, axis=0),
            jnp.take(mu, seg_slots, axis=0))
        gate = final_mask
        keys = keys.at[seg_slots].set(
            jnp.where(gate[:, None], new_keys,
                      jnp.take(keys, seg_slots, axis=0)), mode="drop")
        mu = mu.at[seg_slots].set(
            jnp.where(gate, new_mu, jnp.take(mu, seg_slots, axis=0)),
            mode="drop")
        lengths = lengths.at[seg_slots].set(
            jnp.where(gate, seg_start + seg_len,
                      jnp.take(lengths, seg_slots, axis=0)), mode="drop")
        tokens = tokens.at[seg_slots].set(
            jnp.where(gate, ids_f, jnp.take(tokens, seg_slots, axis=0)),
            mode="drop")
        # the sampled first token enters the penalty ring (finals only)
        rcol = rpos_rows % sampling.RING_N
        ring = ring.at[seg_slots, rcol].set(
            jnp.where(gate, ids_f, ring[seg_slots, rcol]), mode="drop")
        ring_pos = ring_pos.at[seg_slots].set(
            jnp.where(gate, rpos_rows + 1, rpos_rows), mode="drop")
        active = jnp.asarray(active).at[seg_slots].set(
            jnp.where(gate, True,
                      jnp.take(jnp.asarray(active), seg_slots, axis=0)),
            mode="drop")

        step = self._make_scan_step(params, sp, bias, active,
                                    (True, True, True), pos_offset)
        carry = (tokens, ck, cv, lengths, ring, ring_pos, keys, mu)
        carry, (ids_all, lps_all) = jax.lax.scan(step, carry, None,
                                                 length=n_steps)
        tokens, ck, cv, lengths, ring, ring_pos, keys, mu = carry
        S = self.ecfg.num_slots
        first_ids = jnp.zeros((S,), jnp.float32).at[seg_slots].set(
            jnp.where(gate, ids_f.astype(jnp.float32), 0.0), mode="drop")
        first_lps = jnp.zeros((S,), jnp.float32).at[seg_slots].set(
            jnp.where(gate, lps_f, 0.0), mode="drop")
        pack = jnp.concatenate(
            [ids_all.astype(jnp.float32), lps_all, mu[None, :],
             first_ids[None, :], first_lps[None, :]], axis=0)
        return pack, ck, cv, keys, (tokens, lengths, ring, ring_pos, mu)

    def _get_fused_packed_fn(self, bucket: int, continued: bool):
        key = ("fused_packed", bucket, continued)
        fn = self._burst_fns.get(key)
        if fn is None:
            self._cobs.note_program("prefill_pack_fused", (bucket, continued))
            fn = jax.jit(
                lambda *a: self._fused_packed_body(
                    *a, n_steps=self.ecfg.decode_burst,
                    continued=continued),
                donate_argnums=(2, 3, 8))
            self._burst_fns[key] = fn
        return fn

    def _split_head_body(self, params, tokens, ck, cv, lengths, ring,
                         ring_pos, bias, keys, slot_params, active, mu,
                         ov_pack, p_tokens, p_positions, seg_of, seg_slots,
                         seg_start, seg_off, seg_len, final_mask,
                         continued: bool):
        """EARLY-EMIT split, prefill half: exactly the state evolution of
        _fused_packed_body up to (not including) the decode scan —
        compose overrides, ragged-prefill every segment, sample the
        FINAL segments' first tokens, fold them into the chain state —
        and return the per-segment first tokens as their own device
        outputs. The engine dispatches a plain decode burst chained off
        the returned handles back-to-back (no host sync between), so the
        device still sees one uninterrupted tick of work; but the sync
        worker materializes THIS half first, so first tokens reach the
        stream a whole decode burst earlier than the monolithic fused
        body could deliver them — that delay is what kept fused auto
        real-chip-only."""
        sp = sampling.unpack_slot_params(slot_params)
        tokens, lengths, ring, ring_pos, mu, _pos_offset = \
            self._compose_overrides(tokens, lengths, ring, ring_pos, mu,
                                    ov_pack)

        logits, ck, cv = self.family.ragged_prefill(
            params, self.cfg, p_tokens, p_positions, seg_of, seg_slots,
            seg_start, seg_off, seg_len, ck, cv, continued=continued,
            comm_overlap=self._comm_overlap)
        ring_rows = jnp.take(ring, seg_slots, axis=0)
        rpos_rows = jnp.take(ring_pos, seg_slots, axis=0)
        ids_f, lps_f, new_keys, new_mu = sampling.sample(
            logits,
            jax.tree.map(lambda a: jnp.take(jnp.asarray(a), seg_slots,
                                            axis=0), sp),
            ring_rows, rpos_rows,
            jnp.take(bias, seg_slots, axis=0),
            jnp.take(keys, seg_slots, axis=0),
            jnp.take(mu, seg_slots, axis=0))
        gate = final_mask
        keys = keys.at[seg_slots].set(
            jnp.where(gate[:, None], new_keys,
                      jnp.take(keys, seg_slots, axis=0)), mode="drop")
        mu = mu.at[seg_slots].set(
            jnp.where(gate, new_mu, jnp.take(mu, seg_slots, axis=0)),
            mode="drop")
        lengths = lengths.at[seg_slots].set(
            jnp.where(gate, seg_start + seg_len,
                      jnp.take(lengths, seg_slots, axis=0)), mode="drop")
        tokens = tokens.at[seg_slots].set(
            jnp.where(gate, ids_f, jnp.take(tokens, seg_slots, axis=0)),
            mode="drop")
        rcol = rpos_rows % sampling.RING_N
        ring = ring.at[seg_slots, rcol].set(
            jnp.where(gate, ids_f, ring[seg_slots, rcol]), mode="drop")
        ring_pos = ring_pos.at[seg_slots].set(
            jnp.where(gate, rpos_rows + 1, rpos_rows), mode="drop")
        return (ids_f, lps_f, ck, cv, keys,
                (tokens, lengths, ring, ring_pos, mu))

    def _get_split_head_fn(self, bucket: int, continued: bool):
        key = ("packed_head", bucket, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            self._cobs.note_program("prefill_pack_head", (bucket, continued))
            fn = jax.jit(
                lambda *a: self._split_head_body(*a, continued=continued),
                donate_argnums=(2, 3, 8))
            self._final_fns[key] = fn
        return fn

    def _get_draft_packed_fn(self, bucket: int):
        """Draft-model ragged prompt ingestion (open PR-4 follow-up:
        spec slots are packed citizens now). Same ragged program as the
        target's, minus sampling — the draft cache embeds its own layout
        (paged since ISSUE 13, riding the main page table; contiguous on
        the fallbacks), so scatter_ragged branches to the right path by
        itself."""
        key = ("draft_packed", bucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda p, t, pos, so, ss, st, off, ln, ck, cv:
                    llama.ragged_prefill(
                        p, self.draft_cfg, t, pos, so, ss, st, off, ln,
                        ck, cv, continued=True)[1:],
                donate_argnums=(8, 9))
            self._chunk_fns[key] = fn
        return fn

    def _get_burst_fn(self, n_steps: int, flags: tuple = (True, True, True)):
        key = (n_steps, flags)
        fn = self._burst_fns.get(key)
        if fn is None:
            self._cobs.note_program("decode_burst", key)
            # donate the cache + keys; chain inputs stay undonated (they are
            # tiny, and mirror-fed dispatches pass host numpy for them)
            fn = jax.jit(
                lambda *a: self._decode_burst_body(*a, n_steps=n_steps,
                                                   flags=flags),
                donate_argnums=(2, 3, 8))
            self._burst_fns[key] = fn
        return fn

    def _get_chunk_fn(self, bucket: int):
        fn = self._chunk_fns.get(bucket)
        if fn is None:
            self._cobs.note_program("prefill_chunk", bucket)
            fn = jax.jit(self._prefill_chunk_body, donate_argnums=(3, 4))
            self._chunk_fns[bucket] = fn
        return fn

    def _get_draft_chunk_fn(self, bucket: int):
        """Draft-model prompt ingestion (the draft has its OWN config —
        the target-cfg chunk body would mis-shape or mis-parameterize
        it). The draft cache embeds its layout, so the same body serves
        the paged draft cache (ISSUE 13) and the contiguous fallbacks."""
        key = ("draft", bucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda p, t, s, ck, cv, sl, st: llama.prefill(
                    p, self.draft_cfg, t, s, ck, cv, sl, st,
                    continued=True)[1:],
                donate_argnums=(3, 4))
            self._chunk_fns[key] = fn
        return fn

    def _get_final_fn(self, bucket: int, batch: int, continued: bool):
        key = (bucket, batch, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            self._cobs.note_program("prefill_final", key)
            fn = jax.jit(
                lambda *a: self._prefill_final_body(*a, continued=continued),
                donate_argnums=(3, 4, 10))
            self._final_fns[key] = fn
        return fn

    # self-extend prefill variants (B=1, explicit grouped positions;
    # lazily compiled — ga is off by default)

    def _get_ga_chunk_fn(self, bucket: int):
        key = ("ga", bucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda p, t, sl, ck, cv, slo, st, pos: llama.prefill(
                    p, self.cfg, t, sl, ck, cv, slo, st, continued=True,
                    positions=pos)[1:],
                donate_argnums=(3, 4))
            self._chunk_fns[key] = fn
        return fn

    def _get_ga_final_fn(self, bucket: int, continued: bool):
        key = ("ga_final", bucket, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: self._prefill_final_body(
                    *a[:13], continued=continued, positions=a[13]),
                donate_argnums=(3, 4, 10))
            self._final_fns[key] = fn
        return fn

    def _get_ga_rotate_fn(self):
        fn = self._fork_fns.get("ga_rotate")
        if fn is None:
            fn = jax.jit(
                lambda ck, slot, deltas: llama.shift_cache_positions(
                    ck, self.cfg, slot, deltas),
                donate_argnums=(0,))
            self._fork_fns["ga_rotate"] = fn
        return fn

    # multimodal prefill variants (B=1, lazily compiled on first vision
    # request; keyed additionally on the image-embedding bucket P)

    def _get_mm_chunk_fn(self, bucket: int, pbucket: int):
        key = ("mm", bucket, pbucket)
        fn = self._chunk_fns.get(key)
        if fn is None:
            fn = jax.jit(self._prefill_chunk_body, donate_argnums=(3, 4))
            self._chunk_fns[key] = fn
        return fn

    def _get_mm_final_fn(self, bucket: int, pbucket: int, continued: bool):
        key = ("mm", bucket, pbucket, continued)
        fn = self._final_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda *a: self._prefill_final_body(*a[:13], continued=continued,
                                                    mm_pos=a[13], mm_vec=a[14]),
                donate_argnums=(3, 4, 10))
            self._final_fns[key] = fn
        return fn

    # ---------- public API ----------

    def precompile(self):
        """Compile + execute every jitted variant the serving loop can hit
        (burst sizes, prefill buckets x fresh/continued) BEFORE taking
        traffic. A cold XLA compile costs 20-40s on the serving chip;
        hitting one mid-wave stalls every active request (measured: one
        stray burst-size compile turned a 7s bench wave into 40s).

        Bursts run with all slots inactive — a state-preserving no-op.
        Prefill warmups write one garbage row into (free) slot 0's cache;
        admission reseeds all per-slot state, so this is invisible to
        traffic. Mirrors the reference's LoadToMemory warmup
        (core/startup/startup.go:148-176); pairs with the persistent
        compilation cache (utils/jaxtools.py) so restarts compile fast.

        ISSUE 8: the body runs with this engine's CompileTracker bound
        to the calling thread (precompile runs on the loader/caller
        thread, not the engine loop), and the END of precompile marks
        the warm boundary — incidental warmup compiles (helper fills,
        first-touch jnp ops) land before the mark, and any compile
        observed after it is a compile storm."""
        with sysobs.activated(self._cobs):
            self._precompile_impl()
        self._cobs.mark_warm()

    def _precompile_impl(self):
        k = 1
        ks = []
        while k <= self.ecfg.decode_burst:
            ks.append(k)
            k *= 2
        S = self.ecfg.num_slots
        no_ov = self._pack_ov(np.zeros((S,), np.bool_))
        spp = sampling.pack_slot_params(self.slot_params)
        for k in ks:
            for flags in ((False, False, False), (True, True, True)):
                fn = self._get_burst_fn(k, flags)
                _, self.ck, self.cv, self.rng_keys, _ = fn(
                    self.params, self.cur_tokens, self.ck, self.cv, self.lengths,
                    self.ring, self.ring_pos, self.bias, self.rng_keys,
                    spp, self.active_dev, self.mu, no_ov)
        if self._spec_mode != "off" and self.ecfg.ga_n <= 1:
            # fused spec-tick ladder (ISSUE 13): same pow2 discipline as
            # the burst ladder, capped exactly like _plan_spec so no spec
            # round-count ever compiles mid-serving. The warmup mask is
            # all-inactive: every KV write drops.
            if self._spec_mode == "model":
                self._ensure_draft_cache()
                self._commit_ptab()
            no_spec = np.zeros((S,), np.bool_)
            r = 1
            rs = []
            while r <= max(1, self.ecfg.decode_burst
                           // (self.ecfg.n_draft + 1)):
                rs.append(r)
                r *= 2
            for r in rs:
                for flags in ((False, False, False), (True, True, True)):
                    fn = self._get_spec_tick_fn(r, flags)
                    if self._spec_mode == "model":
                        (_, self.ck, self.cv, self.rng_keys, _,
                         self.dck, self.dcv) = fn(
                            self.params, self.cur_tokens, self.ck,
                            self.cv, self.lengths, self.ring,
                            self.ring_pos, self.bias, self.rng_keys,
                            spp, self.active_dev, self.mu, no_ov,
                            no_spec, self.draft_params, self.dck,
                            self.dcv)
                    else:
                        _, self.ck, self.cv, self.rng_keys, _ = fn(
                            self.params, self.cur_tokens, self.ck,
                            self.cv, self.lengths, self.ring,
                            self.ring_pos, self.bias, self.rng_keys,
                            spp, self.active_dev, self.mu, no_ov,
                            no_spec)
        for bucket in self._buckets:
            one = np.ones((1,), np.int32)
            zero = np.zeros((1,), np.int32)
            tokens = np.zeros((1, bucket), np.int32)
            if bucket == self._chunk:
                # non-final chunks always use the full chunk bucket
                self.ck, self.cv = self._get_chunk_fn(bucket)(
                    self.params, tokens, one, self.ck, self.cv, zero, zero)
            finals = [(1, False), (1, True)]
            fb = 2
            while fb <= self._final_pad:
                finals.append((fb, False))
                fb *= 2
            for batch, continued in finals:
                if batch == 1:
                    tb, sb = tokens, one
                    slotb = startb = zero
                else:
                    tb = np.zeros((batch, bucket), np.int32)
                    sb = np.ones((batch,), np.int32)
                    slotb = startb = np.zeros((batch,), np.int32)
                fn = self._get_final_fn(bucket, batch, continued)
                _, _, self.ck, self.cv, self.rng_keys, _ = fn(
                    self.params, tb, sb, self.ck, self.cv, slotb, startb,
                    self.ring, self.ring_pos, self.bias, self.rng_keys,
                    spp, self.mu)
            # fused admission variants (prefill+first-token+burst)
            Bs = [1]
            fb = 2
            while fb <= self._final_pad:
                Bs.append(fb)
                fb *= 2
            for B in Bs:
                fn = self._get_fused_fn(bucket, B)
                _, self.ck, self.cv, self.rng_keys, _ = fn(
                    self.params, self.cur_tokens, self.ck, self.cv,
                    self.lengths, self.ring, self.ring_pos, self.bias,
                    self.rng_keys, spp, self.active_dev,
                    self.mu, no_ov,
                    np.zeros((B, bucket), np.int32), np.ones((B,), np.int32),
                    np.zeros((B,), np.int32), np.zeros((B,), np.int32))
        if self._packed:
            # ragged packed prefill variants: one program per
            # (total-token bucket, continued?). The warmup pack is ALL
            # PADS (sentinel segments/positions/slots), so it writes no
            # KV rows and consumes no slot state — invisible to traffic.
            S_ = self.ecfg.num_slots
            C_ = self.ecfg.max_context
            sent = np.full((S_,), S_, np.int32)
            zs = np.zeros((S_,), np.int32)
            nofinal = np.zeros((S_,), np.bool_)
            for bucket in self._pack_buckets:
                for continued in (False, True):
                    pack_args = (np.zeros((bucket,), np.int32),
                                 np.full((bucket,), C_, np.int32),
                                 np.full((bucket,), S_, np.int32),
                                 sent, zs, zs, zs, nofinal)
                    fn = self._get_packed_fn(bucket, continued)
                    _, _, self.ck, self.cv, self.rng_keys, _ = fn(
                        self.params, *pack_args,
                        self.ck, self.cv, self.ring, self.ring_pos,
                        self.bias, self.rng_keys, spp, self.mu)
                    if self._pack_fuse == "mono":
                        ffn = self._get_fused_packed_fn(bucket, continued)
                        _, self.ck, self.cv, self.rng_keys, _ = ffn(
                            self.params, self.cur_tokens, self.ck, self.cv,
                            self.lengths, self.ring, self.ring_pos, self.bias,
                            self.rng_keys, spp, self.active_dev, self.mu,
                            no_ov, *pack_args)
                    elif self._pack_fuse == "split":
                        # chain outputs are DISCARDED: the head donates
                        # only ck/cv/keys, and the engine's host-side
                        # tokens/lengths/ring/mu arrays must stay numpy
                        hfn = self._get_split_head_fn(bucket, continued)
                        _, _, self.ck, self.cv, self.rng_keys, _ = hfn(
                            self.params, self.cur_tokens, self.ck, self.cv,
                            self.lengths, self.ring, self.ring_pos, self.bias,
                            self.rng_keys, spp, self.active_dev, self.mu,
                            no_ov, *pack_args)
        if self._hstore is not None:
            # host-tier transfer programs: the first eviction/restore
            # must not pay a cold compile mid-serving. Gather reads page
            # 0 (harmless); the scatter warm-up writes nothing (all
            # sentinel ids drop).
            B = 1
            while B <= 16:
                idx_g = np.zeros((B,), np.int32)
                idx_s = np.full((B,), self._pool.num_pages, np.int32)
                rows = self._get_offload_gather_fn(B)(self.ck, self.cv,
                                                      idx_g)
                zeros = jax.tree.map(
                    lambda a: np.zeros(a.shape, a.dtype),
                    jax.tree.map(np.asarray, rows[0]))
                self.ck, self.cv = self._get_restore_scatter_fn(B)(
                    self.ck, self.cv, idx_s, zeros, zeros)
                if self.dck is not None and self._paged:
                    # draft-cache shapes re-specialize the same jitted
                    # gather/scatter callables (ISSUE 13): warm them too
                    drows = self._get_offload_gather_fn(B)(
                        self.dck, self.dcv, idx_g)
                    dzeros = jax.tree.map(
                        lambda a: np.zeros(a.shape, a.dtype),
                        jax.tree.map(np.asarray, drows[0]))
                    self.dck, self.dcv = self._get_restore_scatter_fn(B)(
                        self.dck, self.dcv, idx_s, dzeros, dzeros)
                B *= 2
        # admission-path op-level helpers: seed_slot_key builds a PRNGKey
        # (broadcast + squeeze) and scatters it into the key matrix —
        # three tiny implicit jits that would otherwise land on the FIRST
        # real admission and read as false compile storms (ISSUE 8)
        self.rng_keys = sampling.seed_slot_key(
            self.rng_keys, 0, sampling.SamplingParamsHost(),
            fallback_seed=0)
        jax.block_until_ready(self.ck)

    def start(self, precompile: bool = False):
        if self._paged and self._pool.oversubscription > 1.5:
            # sizing hint (ROADMAP follow-up): an operator who shrank
            # kv_pool_pages past 1.5x logical demand should know what
            # admission now leans on — one line, at start, not per event
            import logging as _logging

            _logging.getLogger(__name__).info(
                "kv pool oversubscription %.2fx (%d pages for %d logical):"
                " admission relies on %s under full load; watch "
                "localai_kv_pool_pages{state=\"free\"} and grow "
                "kv_pool_pages if admissions fail",
                self._pool.oversubscription, self._pool.num_pages,
                self.ecfg.num_slots * self._pool.max_pages,
                "prefix-cache eviction + host-RAM offload"
                if self._hstore is not None else "prefix-cache eviction")
        if precompile:
            self.precompile()
        self._thread = threading.Thread(target=self._run, name="engine-loop", daemon=True)
        self._thread.start()

    def shutdown(self):
        self._stop = True
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=10)
        self._sync_q.put(None)
        if (self._hstore is not None and self.ecfg.kv_host_store_path
                and self._hstore_owned):
            # graceful-shutdown persistence: let the worker drain any
            # in-flight offload gathers into the store first, then
            # serialize it for the next engine of this model. Pool
            # replicas never save — the POOL persists the shared store
            # once (ISSUE 14), not once per replica.
            self._sync_thread.join(timeout=30)
            self._hstore.save(self.ecfg.kv_host_store_path)
        if (self._kv_audit is not None and self.num_active == 0
                and self._queue.qsize() == 0):
            # post-drain leak freedom (ISSUE 15): a drained engine must
            # balance to zero — evict the retention tier (dropping its
            # holds), then prove all pages free, all holds gone, and the
            # ledger agreeing. Only meaningful when nothing was cut off
            # mid-flight; strict mode raises out of shutdown by design.
            from localai_tpu.services.kv_audit import KVAuditError

            try:
                for i, s in enumerate(self.slots):
                    if s is None and self._pool.owned[i]:
                        # freed-slot prefix retention is legal live state;
                        # drop it so the drained pool balances to zero
                        self._pool.release(i, 0)
                        self._cache_tokens[i] = []
                if self._pcache is not None:
                    self._pcache.evict(self._pool, self._pool.num_pages)
                self._kv_audit_tick(drained=True)
            except KVAuditError:
                raise
            except Exception:
                __import__("logging").getLogger(__name__).exception(
                    "post-drain kv audit failed")
        if self._bus is not None:
            self._bus.close()
        if self._trace and self._tstats:
            import sys

            total = sum(v[0] for k, v in self._tstats.items()
                        if k != "burst_steps")
            for k, (sec, n) in sorted(self._tstats.items(),
                                      key=lambda kv: -kv[1][0]):
                print(f"[engine-trace] {k:14s} {sec:8.2f}s n={n:<7d} "
                      f"avg={sec/max(n,1)*1e3:7.2f}ms", file=sys.stderr)
            print(f"[engine-trace] traced total {total:.2f}s", file=sys.stderr)
        # close every consumer: queued requests and still-active slots
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.out.put(StreamEvent(token_id=-1, text="", logprob=0.0,
                                    finish_reason="stop", error="engine shut down"))
            req.out.put(None)
        for i, s in enumerate(self.slots):
            if s is not None:
                self.slots[i] = None
                ev = StreamEvent(token_id=-1, text="", logprob=0.0,
                                 finish_reason="stop", error="engine shut down")
                if self._emitter is not None:
                    # lands after any still-queued tokens for the stream
                    self._emitter.push_final(i, s, [ev, None])
                else:
                    s.req.out.put(ev)
                    s.req.out.put(None)
        if self._emitter is not None:
            self._emitter.stop(timeout=5.0)

    def _reset_device_state(self):
        if self._bus is not None:
            self._bus.send("reset")
        S = self.ecfg.num_slots
        V = self.cfg.vocab_size
        if self._paged:
            from localai_tpu.engine.paging import PagePool

            if self._prefetch is not None:
                # staged prefetch pages die with the pool below — drop
                # the bookkeeping (no unref: the fresh pool has no
                # record of them) and count the batch WASTED
                n = len(self._prefetch.drain())
                if n and self._hstore is not None:
                    self._hstore.note_prefetch_wasted(n)
            self._pool = PagePool(S, self.ecfg.max_context,
                                  self._pool.page_size,
                                  self._pool_pages)
            if self._pcache is not None:
                # the pool (and its holds) died with the device state;
                # forget the index, keep the telemetry counters. The
                # HOST tier survives — its numpy copies don't reference
                # the dead pool, so offloaded chains stay restorable.
                self._pcache.clear()
            if self._kv_audit is not None:
                # rebind the fresh pool and zero the ledger's running
                # balances — the reset is itself a ledger event (ISSUE
                # 15); totals and the ring survive for post-mortems
                self._pool.audit = self._kv_audit
                self._kv_audit.ledger.rebase()
        self.ck, self.cv = self.family.init_cache(
            self.cfg, S, self.ecfg.max_context, self.ecfg.cache_dtype,
            **({"page_size": self._pool.page_size,
                "num_pages": self._pool_pages}
               if self._paged else {}))
        self.dck = self.dcv = None   # re-ensured at the next spec admission
        self.ring, self.ring_pos = sampling.make_ring(S)
        self.bias = jnp.zeros((S, V), jnp.float32)
        self.rng_keys = jax.vmap(jax.random.key_data)(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(S, dtype=jnp.uint32))
        )
        self.lengths = np.zeros((S,), np.int32)
        self.cur_tokens = np.zeros((S,), np.int32)
        self.active_dev = np.zeros((S,), np.bool_)
        self.pos_offset = np.zeros((S,), np.int32)
        self._bias_dirty = np.zeros((S,), np.bool_)
        self.slot_params = sampling.make_slot_params(S)
        self.mu = sampling.make_mu(S)
        self._shard_state()
        self._cache_tokens = [[] for _ in range(S)]
        self._prefill_queue = []
        self._chain = None
        self._win_delta.fill(0)   # no chain left to rebase (ISSUE 16)
        self._override = set()
        self._fifo.clear()
        self._fork_waiters = {}
        self._gbias_flush = set()

    def submit(self, req: GenRequest) -> "queue.Queue":
        req.t_submit = time.monotonic()
        req.priority = normalize_priority(req.priority, self._default_prio)
        # admission control (ISSUE 7): shed at the door instead of queuing
        # unboundedly — the caller gets a structured "shed" event on the
        # normal output queue within microseconds, not a growing sojourn.
        # maxq_effective tracks the configured limit until the pool
        # rescales it with replica width (ISSUE 20): a scaled-in pool
        # sheds at the narrower width's limit instead of promising the
        # full fleet's queue depth.
        maxq = self.maxq_effective
        if maxq > 0 and self._queue.qsize() >= maxq:
            # queue-wait-aware shed fairness (ISSUE 10, closes the PR-7
            # follow-up): a full queue sheds the longest-queued request
            # of the lowest class STRICTLY below the newcomer's — a
            # flood of equals still refuses the arrival (the PR-7
            # contract), but background traffic can no longer crowd
            # interactive work out of the queue. The victim gets the
            # same structured shed event / 429 shape it always did.
            victim = None
            if self._sched is not None:
                with self._queue.mutex:
                    queued = [(r.priority, r.t_submit, r)
                              for r in self._queue.queue]
                victim = self._sched.pick_shed_victim(
                    PRIORITY_RANK[req.priority], queued)
                if victim is not None:
                    with self._queue.mutex:
                        try:
                            self._queue.queue.remove(victim)
                        except ValueError:
                            victim = None   # raced with admission
            if victim is None:
                self._shed(req, f"server overloaded: {maxq} requests "
                                f"already queued (max_queued_requests)")
                return req.out
            self._shed(victim,
                       f"displaced by a {req.priority}-priority arrival "
                       f"(queue full at {maxq}; longest-queued "
                       f"{victim.priority} request shed)")
        if self.ecfg.request_timeout_ms > 0:
            req.deadline = req.t_submit + self.ecfg.request_timeout_ms / 1e3
        self._queue.put(req)
        self._wake.set()
        return req.out

    def _retry_after_hint(self) -> float:
        """Crude client back-off from the live queue_depth / slot gauges:
        roughly 'queue drains one request per slot per second', floored
        at 1 s. Precision is not the point — a monotone signal is."""
        return max(1.0, round(
            self._queue.qsize() / max(1, self.ecfg.num_slots), 1))

    def _shed(self, req: GenRequest, reason: str, kind: str = "shed"):
        with self._lc_lock:
            self._lc["requests_shed"] += 1
        EVENTS.emit("shed", rid=req.request_id, reason=reason,
                    queued=self._queue.qsize())
        req.out.put(StreamEvent(
            token_id=-1, text="", logprob=0.0, finish_reason="stop",
            error=reason, error_kind=kind,
            retry_after_s=self._retry_after_hint()))
        req.out.put(None)

    def _timeout_event(self, req: GenRequest) -> StreamEvent:
        with self._lc_lock:
            self._lc["requests_timed_out"] += 1
        EVENTS.emit("timeout", rid=req.request_id,
                    timeout_ms=self.ecfg.request_timeout_ms)
        return StreamEvent(
            token_id=-1, text="", logprob=0.0, finish_reason="stop",
            error=(f"request deadline exceeded "
                   f"({self.ecfg.request_timeout_ms} ms)"),
            error_kind="timeout")

    def cancel(self, request_id: str):
        """Cancel a queued or running request (reference parity:
        TASK_TYPE_CANCEL, utils.hpp:53-56). The slot is released at the
        next step boundary; a None sentinel closes the output queue."""
        self._cancelled.add(request_id)
        self._wake.set()

    def generate(self, req: GenRequest) -> Iterator[StreamEvent]:
        """Synchronous streaming helper."""
        out = self.submit(req)
        while True:
            ev = out.get()
            if ev is None:
                return
            yield ev

    def generate_text(self, req: GenRequest) -> tuple[str, list[StreamEvent]]:
        events = list(self.generate(req))
        return "".join(e.text for e in events), events

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def metrics(self) -> dict:
        """Parity with the reference's GetMetrics RPC (grpc-server.cpp:2465)."""
        active = [s for s in self.slots if s is not None]
        tok_s = 0.0
        for s in active:
            dt = time.monotonic() - (s.t_first_token or s.t_start)
            if s.n_decoded and dt > 0:
                tok_s += s.n_decoded / dt
        out = {
            "slots_total": self.ecfg.num_slots,
            "slots_active": len(active),
            "queued": self._queue.qsize(),
            "total_tokens_generated": self._total_tokens,
            "tokens_per_second_active": tok_s,
            "prompt_tokens_reused": self._reused_total,
            "uptime_s": time.monotonic() - self._load_time,
            "replica_id": self.replica_id,
            "engine_replicas": 1,    # EnginePool.metrics() overrides
            # ragged packed prefill (module doc): scheduling mode +
            # per-dispatch packing efficiency (pad_tokens / tokens is
            # the bucket-pad waste the packing removed per-slot)
            "prefill_packed": self._packed,
            "prefill_packed_fuse": self._pack_fuse,
            "prefill_token_budget": self._pack_budget,
            "packed_prefill": dict(self._pack_stats),
        }
        # speculative decoding (ISSUE 13): per-round counters + the two
        # derived rates the bench/CI gate on — acceptance (accepted /
        # proposed) and accepted-tokens-per-dispatch (emitted spec
        # tokens, bonus included, per slot-round — the per-dispatch
        # verify unit; 1.0 means speculation is buying nothing, >1.0 is
        # the whole point)
        st = self._spec_stats
        out["spec"] = {
            "mode": self._spec_mode,
            "n_draft": self.ecfg.n_draft,
            **{k: v for k, v in st.items() if k != "by_mode"},
            "acceptance_rate": (st["accepted"] / st["proposed"]
                                if st["proposed"] else 0.0),
            "accept_per_dispatch": (st["tokens"] / st["rounds"]
                                    if st["rounds"] else 0.0),
            # ISSUE 18: the same counters + derived rates split by
            # acceptance mode (greedy accept_greedy vs sampled
            # rejection-sampling) — /metrics labels and /debug/state
            # carry this through verbatim
            "by_mode": {
                m: {**c,
                    "acceptance_rate": (c["accepted"] / c["proposed"]
                                        if c["proposed"] else 0.0),
                    "accept_per_dispatch": (c["tokens"] / c["rounds"]
                                            if c["rounds"] else 0.0)}
                for m, c in st["by_mode"].items()},
        }
        if self._paged:
            out["kv_layout"] = "paged"
            out["kv_page_size"] = self._pool.page_size
            out["kv_pages_total"] = self._pool.num_pages
            out["kv_pages_in_use"] = self._pool.pages_in_use
            out["kv_pages_shared"] = int((self._pool.refs > 1).sum())
            # pool occupancy gauges (ROADMAP: "shrink default
            # kv_pool_pages once oversubscription telemetry exists"):
            # free + retained + active == total; retained is reclaimable
            out["kv_pages_free"] = self._pool.free_pages
            out["kv_pages_retained"] = self._pool.retained_pages
            out["kv_pages_active"] = self._pool.active_pages
            out["kv_pool_oversubscription"] = round(
                self._pool.oversubscription, 4)
            if self._pcache is not None:
                out["prefix_cache"] = self._pcache.stats()
            if self._hstore is not None:
                # host tier: state=offloaded pool gauge + transfer totals
                out["kv_pages_offloaded"] = self._hstore.pages
                out["kv_offload"] = self._hstore.stats()
                fed = self._hstore.federated
                if fed is not None:
                    # peer tier (ISSUE 17) ->
                    # localai_kv_stream_{pages,bytes,fetches,hits,
                    # misses}_total
                    out["kv_stream"] = fed.stats()
            if self.ecfg.disagg != "both":
                out["disagg"] = {"role": self.ecfg.disagg,
                                 "handoffs": self.disagg_handoffs}
            if self._kv_audit is not None:
                # lifecycle auditor (ISSUE 15): checks/violations/leaked
                # pages/ledger events -> localai_kv_audit_*_total
                out["kv_audit"] = self._kv_audit.snapshot()
        else:
            out["kv_layout"] = "contiguous"
        with self._decomp_lock:
            d = list(self._ttft_decomp)
        if d:
            qw, af, pf = (sorted(x[i] for x in d) for i in range(3))
            mid = len(d) // 2
            out["ttft_decomp_p50_ms"] = {
                "queue_wait": round(qw[mid], 1),
                "admit_to_first": round(af[mid], 1),
                "prefill_dispatch": round(pf[mid], 1),
                "n": len(d),
            }
        # latency histograms (re-exposed by /metrics as Prometheus
        # histograms) + span-tracer aggregates incl. the host-vs-device
        # walltime decomposition
        out["histograms"] = {
            name: {"le": list(_HIST_BUCKETS[name]),
                   "counts": list(h[0]),
                   "sum": round(h[1], 6), "count": h[2]}
            for name, h in self._hists.items()}
        out["trace"] = self.tracer.summary()
        # fault-tolerant lifecycle telemetry (ISSUE 7): shed/timeout/stall
        # counters + the effective knobs, re-exposed per model on /metrics
        with self._lc_lock:
            lc = dict(self._lc)
        lc["max_queued_requests"] = self.ecfg.max_queued_requests
        lc["queue_limit_effective"] = self.maxq_effective
        lc["max_queue_wait_ms"] = self.ecfg.max_queue_wait_ms
        lc["request_timeout_ms"] = self.ecfg.request_timeout_ms
        lc["dispatch_stall_ms"] = self.ecfg.dispatch_stall_ms
        out["lifecycle"] = lc
        # effective admission limit -> localai_engine_queue_limit (the
        # pool overrides this with the co-scaled routable sum)
        out["queue_limit"] = self.maxq_effective
        # event-driven emission (ISSUE 9)
        if self._emitter is not None:
            out["emitter"] = {"enabled": True,
                              "alive": self._emitter.alive,
                              "queued": self._emitter.qsize(),
                              "emitted": self._emitter.emitted}
        else:
            out["emitter"] = {"enabled": False}
        # system observability (ISSUE 8): compile tracking + memory
        # watermarks + goodput/MFU, re-exposed per model on /metrics
        self._sample_watermarks()
        sys_obs = {"compiles": self._cobs.snapshot(),
                   "watermarks": self._wm.snapshot(),
                   "goodput": self._goodput.snapshot(),
                   "weight_bytes": self._weight_bytes}
        if self._paged:
            sys_obs["fragmentation"] = self._pool.fragmentation()
        if self._device_mem:
            sys_obs["device_mem"] = dict(self._device_mem)
        out["sysobs"] = sys_obs
        # SLO engine (ISSUE 12): per-class burn rates + violation totals,
        # re-exposed as localai_slo_* gauges; short-window burns > 1 also
        # become rate-limited slo_burn events so the log tells the same
        # story the dashboard does
        if self._slo is not None and self._slo.enabled:
            out["slo"] = self._slo.snapshot()
            for rec in self._slo.burn_events():
                EVENTS.emit("slo_burn", **rec)
        out["flight_recorder"] = self._flight.snapshot()
        # preemptive priority scheduler (ISSUE 10): DRR counters, resume
        # queue depth, per-class queue/active gauges + effective knobs
        if self._sched is not None:
            sch = self._sched.stats()
            sch["preempt"] = True
            sch["max_preemptions"] = self.ecfg.max_preemptions
            # the reserve actually applied (explicit knob, or the
            # preemption-rate autosized value — ISSUE 14 satellite)
            sch["resume_reserve_pages"] = self.resume_reserve_effective
            sch["resume_reserve_auto"] = self._reserve_auto
            sch["preempt_rate_per_min"] = round(self._preempt_rate_ewma, 3)
            queued_by = {c: 0 for c in PRIORITY_CLASSES}
            with self._queue.mutex:
                for req in self._queue.queue:
                    queued_by[normalize_priority(
                        req.priority, self._default_prio)] += 1
            active_by = {c: 0 for c in PRIORITY_CLASSES}
            for s in active:
                active_by[PRIORITY_CLASSES[s.prio]] += 1
            resume_by = {c: 0 for c in PRIORITY_CLASSES}
            for c in self._sched.resume_priorities():
                resume_by[c] += 1
            sch["queued_by_class"] = queued_by
            sch["active_by_class"] = active_by
            sch["resume_by_class"] = resume_by
            out["scheduler"] = sch
        else:
            out["scheduler"] = {"preempt": False}
        # per-histogram exemplars: worst observation since the last pull
        # (consumed — each scrape sees that interval's worst span)
        worst, self._hist_worst = self._hist_worst, {}
        if worst:
            out["hist_exemplars"] = {
                name: {"value": round(v, 6), "trace_id": rid, "ts": ts}
                for name, (v, rid, ts) in worst.items()}
        return out

    def _sample_watermarks(self):
        """Fold current gauges into the high-water marks (engine-loop
        tick + every metrics() pull) and fire a pool_pressure event on
        the free-fraction threshold crossing (hysteresis: one event per
        excursion, cleared when the pool recovers past 2x)."""
        wm = {"queued": self._queue.qsize(), "slots_active": self.num_active,
              "tokens_total": self._total_tokens}
        # device memory (ISSUE 12 satellite): real allocator stats when
        # the backend exposes them (TPU/GPU), cached for /debug/state and
        # folded into the high-water marks; {} on CPU — the analytic
        # weight/KV accounting above remains the fallback there
        dm = sysobs.device_memory_stats()
        if dm:
            self._device_mem = dm
            wm["device_bytes_in_use"] = dm.get("bytes_in_use", 0)
        if self._paged:
            wm["pool_active_pages"] = self._pool.active_pages
            wm["pool_retained_pages"] = self._pool.retained_pages
            wm["pool_pages_in_use"] = self._pool.pages_in_use
            if self._hstore is not None:
                wm["host_offloaded_pages"] = self._hstore.pages
                wm["host_bytes"] = self._hstore.bytes_used
            free_frac = self._pool.free_pages / max(1, self._pool.num_pages)
            if not self._pool_pressure and free_frac < 0.05:
                self._pool_pressure = True
                EVENTS.emit("pool_pressure",
                            free_pages=self._pool.free_pages,
                            total_pages=self._pool.num_pages,
                            retained=self._pool.retained_pages,
                            active=self._pool.active_pages)
            elif self._pool_pressure and free_frac > 0.10:
                self._pool_pressure = False
        self._wm.sample(**wm)
        self._autosize_reserve()

    def _autosize_reserve(self):
        """resume_reserve_pages autosize (ISSUE 14 satellite, the open
        PR-10 follow-up): when the explicit knob is 0, derive an
        effective reserve from observed preemption pressure —
        EWMA(preemptions/min) x EWMA(pages retained per preemption),
        clamped to a quarter of the pool. Rides the 0.5 s watermark
        cadence; engines that never preempt stay at 0 (bit-for-bit
        pre-PR admission)."""
        if not self._paged or self._sched is None:
            return
        now = time.monotonic()
        dt = now - self._t_reserve_sample
        if dt < 0.5:
            return
        self._t_reserve_sample = now
        # instantaneous rate over a sliding 60 s window of marks
        horizon = now - 60.0
        # marks inside a sliding 60 s window = preemptions per minute
        inst = float(sum(1 for t in self._preempt_marks if t >= horizon))
        # EWMA with a ~15 s time constant at the 0.5 s cadence
        a = min(1.0, dt / 15.0)
        self._preempt_rate_ewma = ((1 - a) * self._preempt_rate_ewma
                                   + a * inst)
        if self.ecfg.resume_reserve_pages > 0:
            return    # explicit knob wins; EWMA still tracked for metrics
        cap = max(1, self._pool.num_pages // 4)
        want = self._preempt_rate_ewma * max(1.0, self._preempt_pages_ewma)
        self._reserve_auto = min(cap, int(round(want)))

    @property
    def resume_reserve_effective(self) -> int:
        """The reserve _admit_sched actually applies: the explicit knob
        when set, else the preemption-rate autosized value."""
        if self.ecfg.resume_reserve_pages > 0:
            return self.ecfg.resume_reserve_pages
        return self._reserve_auto

    def note_pool_resize(self, n_old: int, n_new: int):
        """Re-anchor the preemption-EWMA reserve when the pool's replica
        count changes (ISSUE 19 satellite). The EWMA was learned under
        the OLD replica count: a scale-out spreads the same offered load
        over more replicas, roughly halving per-replica preemption
        pressure, but the ~15 s EWMA time constant would keep the stale
        reserve pinned for many seconds — pages held back from admission
        for preemptions that will no longer happen here. Rescale the
        rate by old/new and recompute the auto reserve immediately
        instead of waiting for the EWMA to drift there."""
        if n_old <= 0 or n_new <= 0 or n_old == n_new:
            return
        ratio = float(n_old) / float(n_new)
        self._preempt_rate_ewma *= ratio
        if not self._paged or self._sched is None:
            return
        if self.ecfg.resume_reserve_pages > 0:
            return    # explicit knob wins, nothing derived to fix
        cap = max(1, self._pool.num_pages // 4)
        want = self._preempt_rate_ewma * max(1.0, self._preempt_pages_ewma)
        self._reserve_auto = min(cap, int(round(want)))

    def state_snapshot(self) -> dict:
        """Live engine-state JSON for /debug/state (ISSUE 8): slots,
        queues, pool map summary, warmth, last N compiles — the
        at-a-glance answer to "what is this engine doing right now"."""
        slots = []
        for i, s in enumerate(self.slots):
            if s is None:
                slots.append(None)
                continue
            slots.append({
                "rid": s.req.request_id,
                "prompt_tokens": len(s.req.prompt_ids),
                "committed": int(s.committed),
                "n_decoded": int(s.n_decoded),
                "age_s": round(time.monotonic() - s.t_start, 3)})
        out = {
            "slots": slots,
            "slots_active": self.num_active,
            "queued": self._queue.qsize(),
            "warm": self._cobs.snapshot()["warm"],
            "compiles": self._cobs.snapshot(),
            "last_compiles": self._cobs.last_compiles(),
            "watermarks": self._wm.snapshot(),
            "goodput": self._goodput.snapshot(),
            "weight_bytes": self._weight_bytes,
        }
        if self._device_mem:
            out["device_mem"] = dict(self._device_mem)
        # speculative counters with the ISSUE-18 per-mode split (greedy
        # vs sampled rejection acceptance), mirroring metrics()["spec"]
        st = self._spec_stats
        out["spec"] = {
            "mode": self._spec_mode,
            **{k: v for k, v in st.items() if k != "by_mode"},
            "by_mode": {m: dict(c) for m, c in st["by_mode"].items()},
        }
        if self._slo is not None and self._slo.enabled:
            out["slo"] = self._slo.snapshot()
        out["flight_recorder"] = self._flight.snapshot()
        with self._lc_lock:
            out["lifecycle"] = dict(self._lc)
        if self._paged:
            out["pool"] = {
                "pages_total": self._pool.num_pages,
                "page_size": self._pool.page_size,
                "free": self._pool.free_pages,
                "active": self._pool.active_pages,
                "retained": self._pool.retained_pages,
                "shared": int((self._pool.refs > 1).sum()),
                "oversubscription": round(self._pool.oversubscription, 4),
                "fragmentation": self._pool.fragmentation(),
                "pages_per_slot": [int(n) for n in self._pool.owned],
            }
            if self._hstore is not None:
                out["host_store"] = self._hstore.stats()
        return out

    def trace_events(self) -> dict:
        """The span ring as Chrome trace-event JSON (perfetto-loadable):
        one track per slot + scheduler + engine dispatch tracks."""
        from localai_tpu.services import tracing

        return tracing.chrome_trace(self.tracer)

    # ---------- grammar-constrained decoding ----------

    def _grammar_for(self, text: str):
        """Compile (cached) + lazily build the vocab mask builder.

        Prefers the native C++ runtime (runtime/grammar.cc via
        functions/grammars/native.py) — a cold mask walk over a 32k vocab
        costs hundreds of ms in the python automaton vs ~ms native; the
        python path remains the fallback (and the semantic reference)."""
        from localai_tpu.functions.grammars import native
        from localai_tpu.functions.grammars.automaton import (
            Grammar, TokenMaskBuilder, token_strings)

        use_native = native.get_lib() is not None
        if self._mask_builder is None:
            self._token_strs = token_strings(self.tokenizer)
            builder_cls = (native.NativeMaskBuilder if use_native
                           else TokenMaskBuilder)
            self._mask_builder = builder_cls(
                self._token_strs, self.eos_ids, self.cfg.vocab_size)
        g = self._grammar_cache.get(text)
        if g is None:
            if len(self._grammar_cache) > 64:
                self._grammar_cache.clear()
            cls = native.NativeGrammar if use_native else Grammar
            g = cls.from_text(text)
            self._grammar_cache[text] = g
        return g

    def _advance_grammar(self, slot: int, s: _Slot, token_id: int) -> bool:
        """Advance the slot's grammar by the emitted token. Returns False if
        the token is outside the grammar (the caller rolls the slot back).
        The device bias row is NOT written here — burst processing advances
        several states per slot and only the LAST one's mask matters for
        the next dispatch, so rows are flushed once per processed burst
        (_flush_grammar_bias)."""
        piece = (self._token_strs[token_id]
                 if 0 <= token_id < len(self._token_strs) else None)
        if piece is None:
            return False
        nxt = s.grammar.advance_string(s.gstate, piece)
        if nxt is None:
            return False
        s.gstate = nxt
        penalty = self._mask_builder.penalty_row(s.grammar, nxt)
        if penalty is not s.cur_penalty:  # memoized per state: identity == equality
            s.cur_penalty = penalty
            self._gbias_flush.add(slot)
        return True

    def _flush_grammar_bias(self):
        """Write the pending grammar-mask rows to the device bias — ONE
        batched scatter per processed burst, not one dispatch per slot
        (32 grammared slots × ~1-2 ms per .at[].set halved constrained
        throughput when flushed individually)."""
        slots = [i for i in self._gbias_flush
                 if self.slots[i] is not None
                 and self.slots[i].grammar is not None]
        self._gbias_flush.clear()
        if not slots:
            return
        # pad the batch to a power of two by REPEATING the first slot
        # (duplicate scatter writes are idempotent): each distinct batch
        # size is its own XLA executable, and 20-40s compiles for 30
        # different sizes would stall serving for minutes
        k = 1
        while k < len(slots):
            k *= 2
        padded = slots + [slots[0]] * (k - len(slots))
        rows = np.stack([self.slots[i].bias_base + self.slots[i].cur_penalty
                         for i in padded])
        self.bias = self.bias.at[np.asarray(padded, np.int32)].set(
            jnp.asarray(rows))
        if self._bus is not None:
            from localai_tpu.parallel.lockstep import encode_bias_row

            self._bus.send("bias_rows", slots=list(padded),
                           rows=[encode_bias_row(r) for r in rows])
        for i in slots:
            self._bias_dirty[i] = True

    def _rollback_grammar(self, slot: int, s: _Slot) -> bool:
        """Discard an invalid speculative token: grammar slots ride full
        bursts masked by their LAST-FLUSHED state (one burst stale under
        pipelining), so a mid-burst token can fall outside the grammar.
        Recompute semantics make the rollback free — reset the slot's
        device length to the last valid row; stale rows are rewritten.
        Returns False (the _process_burst signal to skip the slot's
        remaining burst tokens)."""
        s.generated.pop()
        s.n_decoded -= 1
        self._total_tokens -= 1
        self._rollbacks += 1
        # quiescent invariant (r4, verified against a fresh-prefill KV
        # oracle): lengths == cache_len - 1 — the pending token toks[-1]
        # has row cache_len-1, to be (re)written by the next step. r3 set
        # lengths = cache_len here, which re-wrote the pending token's KV
        # one row too far and silently position-shifted every row after a
        # rollback.
        s.committed = min(s.committed, max(s.cache_len - 1, 0))
        self.lengths[slot] = max(s.cache_len - 1, 0)
        toks = self._cache_tokens[slot]
        self.cur_tokens[slot] = toks[-1] if toks else 0
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, toks)
        # ensure the next dispatch carries this state's mask + the
        # corrected mirrors (chain override)
        self._gbias_flush.add(slot)
        self._override.add(slot)
        # every PIPELINED in-flight burst (dispatched before this rollback
        # was known) sampled its tokens conditioned on the discarded one —
        # drop this slot from them wholesale: neither their folds nor
        # their emissions may touch the corrected mirrors
        for b in self._fifo:
            if isinstance(b, _Burst):
                b.skip_slots.add(slot)
        return False

    # ---------- engine loop ----------

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _pick_slot(self, ids: list) -> tuple:
        """Free slot with the longest cached common prefix (reference:
        grpc-server.cpp:1721-1835). Returns (slot, reusable_len) or (None, 0)."""
        best, best_key = None, None
        for i, s in enumerate(self.slots):
            if s is not None:
                continue
            common = 0
            for a, b in zip(self._cache_tokens[i], ids):
                if a != b:
                    break
                common += 1
            # prefer the longest common prefix; on ties (esp. common == 0)
            # evict the slot with the LEAST cached content so unrelated
            # requests don't destroy another conversation's reusable prefix
            key = (common, -len(self._cache_tokens[i]))
            if best_key is None or key > best_key:
                best, best_key = i, key
        if best is None:
            return None, 0
        # always leave >= 1 token to prefill so we have last-position logits
        return best, min(best_key[0], len(ids) - 1)

    def _run(self):
        """The engine loop (r4): every iteration dispatches first (prefill
        chunks/finals, then up to pipeline_depth decode bursts — all
        async), and only then block-syncs the OLDEST dispatched item,
        which by FIFO execution order is already (nearly) computed. The
        device therefore always has at least one dispatch queued behind
        the one it is executing; host-side syncs, detok, stop-scans and
        queue puts all overlap device compute."""
        import logging

        log = logging.getLogger(__name__)
        # bind this engine's compile tracker to the loop thread: every
        # jit dispatch (and therefore every XLA compile) the serving
        # path triggers happens right here (ISSUE 8)
        sysobs.register_thread(self._cobs)
        t_wm = 0.0
        try:
            self._run_ticks(t_wm)
        except _ReplicaDead:
            # chaos: die like a lost host — the thread just ends, with
            # _stop still False (that asymmetry IS the pool's death
            # signal) and the host mirrors intact for recovery harvest
            log.warning("replica %d: loop killed by replica_die fault",
                        self.replica_id)

    def _run_ticks(self, t_wm: float):
        while not self._stop:
            try:
                t0 = time.monotonic()
                t_tick = t0
                if FAULTS.active and FAULTS.take(self._die_fault) is not None:
                    raise _ReplicaDead()
                # live migration out (ISSUE 14): eject requested streams
                # at the tick top — previous tick fully processed, so
                # the pause point is a burst boundary like any preempt
                if self._migrate_req:
                    self._process_migrations()
                # prefill/decode disaggregation (ISSUE 17): on a
                # prefill-role engine, slots whose prefill completed
                # (first token out) retire to the cluster transport at
                # the same burst boundary migration uses
                if self._disagg_prefill and self.disagg_handoff is not None:
                    self._process_disagg()
                if t0 - t_wm > 0.5:
                    # watermark fold (ISSUE 8): cheap max() samples so
                    # pool peaks between /metrics scrapes are not lost
                    t_wm = t0
                    self._sample_watermarks()
                    if self.kv_checkpoint:
                        # cluster mode (ISSUE 17): stream active slots'
                        # warm chains to the host tier so a host crash
                        # leaves them fetchable by re-adopting siblings
                        self._checkpoint_active_chains()
                    if self._kv_audit is not None:
                        # online KV invariant audit (ISSUE 15): same
                        # cadence, same thread — the mirrors are between
                        # ticks, so the O(num_pages) scans see a
                        # consistent pool
                        self._kv_audit_tick()
                # emitter-detected stop finishes land as notes (ISSUE 9);
                # apply before admission so the freed slots are admittable
                # this very tick
                self._apply_emitter_notes()
                # pick up whatever completed while the previous tick was
                # packing/dispatching BEFORE spending this tick's host
                # time — ready bursts otherwise pay a full tick of
                # finish-detect each (ISSUE 9); never blocks. Only with
                # the emitter on: in-loop emission makes burst pickup
                # expensive enough that extra drain points would starve
                # dispatch, so emitter=0 keeps the seed cadence.
                ev_mode = self._emitter is not None
                drained0 = self._drain_fifo(block=False) if ev_mode \
                    else False
                admitted = self._admit()
                self._tmark("admit", t0)
                if self._prefetch is not None:
                    # prefetch-ahead for the requests STILL queued after
                    # this tick's admissions (ISSUE 16): their host-tier
                    # restores overlap the decode work dispatched below
                    self._prefetch_tick()
                t0 = time.monotonic()
                prefilled = self._prefill_step()
                self._tmark("prefill", t0)
                # prompt packing is the longest host stretch of the tick;
                # collect anything that completed under it (no-op when
                # nothing is ready)
                if ev_mode:
                    drained0 |= self._drain_fifo(block=False)
                dispatched = self._dispatch_decode()
                drained = self._drain_fifo(
                    can_feed=dispatched or prefilled) or drained0
                if self.tracer.enabled and (admitted or prefilled
                                            or dispatched or drained):
                    self.tracer.record(
                        "tick", "sched", t_tick, time.monotonic(),
                        args={"admitted": int(admitted),
                              "prefilled": int(prefilled),
                              "dispatched": int(dispatched),
                              "drained": int(drained)})
                if not (admitted or prefilled or dispatched or drained):
                    # a dispatched item the loop is NOT blocked on (e.g. a
                    # prefill whose worker-side sync wedged) parks in the
                    # FIFO while the loop idles here — the watchdog must
                    # cover that wedge too, not just _wait_ready callers
                    self._check_parked_stall()
                    self._check_emitter_wedge()
                    # event-driven idle (ISSUE 9): the sync worker and the
                    # emitter note channel both set _wake, so the fixed
                    # 50 ms poll tick is gone — park until woken, waking
                    # on a watchdog-scaled timeout only to re-run the
                    # stall/wedge checks above
                    self._wake.wait(timeout=self._idle_wait_s)
                    self._wake.clear()
            except _DispatchStall as st:
                # stall watchdog (ISSUE 7): a narrower failure than the
                # generic handler below — abort ONLY the stalled item's
                # requests, dump the span ring for post-mortem, keep the
                # device state (survivors keep serving).
                self._handle_stall(st.item)
            except Exception as e:  # never let the loop die: fail active requests
                self._recover_step_failure(e)

    def _recover_step_failure(self, e: Exception):
        """Generic step-failure recovery: fail every active request with a
        structured error and reinitialize device state so the engine
        survives instead of erroring forever. Factored out of _run so the
        chaos suite can drive the exact production recovery path against
        a manually-ticked engine."""
        import logging

        log = logging.getLogger(__name__)
        log.exception("engine step failed")
        for i, s in enumerate(self.slots):
            if s is not None:
                ev = StreamEvent(
                    token_id=-1, text="", logprob=0.0,
                    finish_reason="stop", error=f"{type(e).__name__}: {e}",
                )
                if self._emitter is not None:
                    # FIFO with any still-queued tokens (ISSUE 9)
                    self._emitter.push_final(i, s, [ev, None])
                else:
                    s.req.out.put(ev)
                    s.req.out.put(None)
                self._release_slot(i)
        # a failure inside a donated jitted call leaves ck/cv/ring/
        # keys pointing at deleted buffers — reinitialize device state
        # so the engine survives instead of erroring forever
        try:
            self._reset_device_state()
        except Exception:
            log.exception("device state reset failed; engine unusable")
            self._stop = True

    def _admission_ready(self) -> bool:
        """Admit the moment a slot is free: fused admission (prefill +
        first token + burst in one dispatch) makes singleton admissions as
        cheap as batched ones, so holding requests back to form groups
        (r2/r3 did, up to 0.35 s) only idles freed slots. The prefill
        queue itself still batches whatever has accumulated per dispatch."""
        return not self._queue.empty() and self._free_count() > 0

    def _admit(self) -> bool:
        self._reap_expired()
        self._reap_cancelled()
        if self._sched is not None:
            return self._admit_sched()
        if not self._admission_ready():
            return False
        admitted = False
        batch: list[GenRequest] = []
        while not self._queue.empty() and self._free_count() > len(batch):
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        # identical prompts admitted together prefill ONCE: the first
        # becomes the leader; the rest fork its KV rows on commit
        # (VERDICT r2 #5 — true shared-prefix for n>1)
        leaders: dict = {}
        for req in batch:
            if self._admit_one(req, leaders):
                admitted = True
        return admitted

    def _admit_one(self, req: GenRequest, leaders: dict) -> bool:
        """Admit one popped request (shared by the FIFO and scheduler
        paths): cancellation check, fork-dedup leader/sibling logic, and
        failure containment. Returns True when a slot was started."""
        if req.request_id in self._cancelled:
            self._cancelled.discard(req.request_id)
            req.out.put(None)
            return False
        key = None
        # fork-dedup shares KV rows verbatim; under self-extend those
        # rows are position-compressed state the sibling's own ga
        # bookkeeping would re-compress, and in lockstep mode the fork
        # op is not in the descriptor set — mutually exclusive
        if not req.grammar and req.mm_vectors is None \
                and self.ecfg.ga_n <= 1 and self._bus is None \
                and self._fam_llama:
            # truncation depends on max_new_tokens; bucket it into the key
            key = (tuple(req.prompt_ids),
                   min(req.max_new_tokens, self.ecfg.max_context // 4))
        try:
            if key is not None and key in leaders:
                lslot, lsnap, lids = leaders[key]
                self._start_fork_sibling(req, lslot, lsnap, lids)
            else:
                slot, ids, snap = self._start_request(req)
                if key is not None and snap.mm_pos is None:
                    leaders[key] = (slot, snap, ids)
            return True
        except Exception as e:
            import logging

            logging.getLogger(__name__).exception("admission failed")
            req.out.put(StreamEvent(
                token_id=-1, text="", logprob=0.0, finish_reason="stop",
                error=f"{type(e).__name__}: {e}",
            ))
            req.out.put(None)
            return False

    def _pop_queued(self, req: GenRequest) -> bool:
        """Remove a specific request from the admission queue (scheduler
        path: ordered pops instead of FIFO gets). False when a reaper or
        shed-displacement raced us to it."""
        with self._queue.mutex:
            try:
                self._queue.queue.remove(req)
                return True
            except ValueError:
                return False

    def _admit_sched(self) -> bool:
        """Priority admission (ISSUE 10): pop queued work in aged-rank
        order (stable FIFO within a class, so single-class traffic
        admits exactly like the FIFO path), merge the resume queue in by
        effective class, hold ``resume_reserve_pages`` back from fresh
        admissions while preempted work waits, and — when the best
        waiting request strictly outranks an active slot and no slot is
        free — preempt the victim and admit into its slot."""
        sched = self._sched
        if self._queue.empty() and sched.resume_depth == 0:
            return False
        admitted = False
        leaders: dict = {}
        reserve = self.resume_reserve_effective
        # hard bound on the work loop: every iteration either admits,
        # preempts (at most num_slots times), or breaks
        guard = 2 * self.ecfg.num_slots + 8
        while guard > 0:
            guard -= 1
            now = time.monotonic()
            with self._queue.mutex:
                entries = [(r.priority, r.t_submit, r)
                           for r in self._queue.queue]
            cand = sched.order_queued(entries) if entries else []
            head = None
            while cand:
                r = cand[0]
                if r.request_id not in self._cancelled:
                    head = r
                    break
                # cancelled while queued: close the stream and move on
                cand.pop(0)
                if self._pop_queued(r):
                    self._cancelled.discard(r.request_id)
                    r.out.put(None)
            res = sched.peek_resume()
            if head is None and res is None:
                break
            head_rank = sched.effective_rank(
                head.priority, now - head.t_submit) if head is not None \
                else len(PRIORITY_CLASSES)
            res_rank = sched.effective_rank(
                res.priority, now - res.t_parked) if res is not None \
                else len(PRIORITY_CLASSES)
            # parked work already paid its queue wait once — on rank
            # ties it resumes before a fresh admission
            use_resume = res is not None and res_rank <= head_rank
            rank = res_rank if use_resume else head_rank
            if self._free_count() == 0:
                victim = self._pick_victim(rank)
                if victim is None:
                    break
                self._preempt_slot(victim, why="priority")
                continue   # the freed slot admits on the next pass
            if use_resume:
                entry = sched.pop_resume()
                try:
                    self._start_resume(entry)
                    admitted = True
                except Exception:
                    import logging

                    logging.getLogger(__name__).exception(
                        "resume admission failed; request re-parked")
                    sched.requeue_front(entry)
                    break
            else:
                if reserve > 0 and self._paged and sched.resume_depth > 0 \
                        and self._pool.free_pages <= reserve:
                    # fresh work would eat the pages a parked resume
                    # needs — only resumes may pass until pressure lifts
                    break
                if not self._pop_queued(head):
                    continue   # raced with a reaper / shed displacement
                if self._admit_one(head, leaders):
                    admitted = True
        return admitted

    def _preempt_eligible(self, slot: int, s: "_Slot") -> bool:
        """Pausable slots only: pause/resume round-trips through token
        re-admission, so anything whose slot state is NOT reconstructible
        from tokens is excluded — grammar automata (mid-generation state),
        multimodal rows (image embeddings, not tokens), prompt-cache
        requests (their save path assumes one continuous tenancy), and
        fork leaders with waiters still attached. Spec slots are
        pausable since ISSUE 13: the paged draft cache offloads/restores
        with the main pages, the n-gram drafter has no slot state, and a
        contiguous-draft slot simply resumes without speculation."""
        return (s.grammar is None and s.mm_pos is None
                and not s.req.prompt_cache_path
                and s.phase in ("prefill", "decode")
                and slot not in self._fork_waiters
                and s.req.request_id not in self._cancelled)

    def _pick_victim(self, incoming_rank: int,
                     decode_only: bool = False) -> Optional[int]:
        """Engine-side victim scan feeding Scheduler.pick_victim: only
        paged layouts can pause (committed pages retain/offload; the
        contiguous fallbacks would forfeit all progress), and only
        eligible slots are offered. ``decode_only`` restricts to
        decode-phase slots — required when called mid-prefill-pack, where
        a prefill-phase victim could be part of the pack being built."""
        if not self._paged or self._sched is None:
            return None
        cands = []
        for i, s in enumerate(self.slots):
            if s is None or not self._preempt_eligible(i, s):
                continue
            if decode_only and s.phase != "decode":
                continue
            cands.append((i, PRIORITY_CLASSES[s.prio], s.t_start,
                          s.preempts))
        return self._sched.pick_victim(incoming_rank, cands)

    def _preempt_slot(self, slot: int, why: str = "priority",
                      park: bool = True):
        """Pause an active slot at a burst boundary and park its request
        for resume (ISSUE 10). With ``park=False`` (live migration,
        ISSUE 14) the ResumeEntry is RETURNED instead of parked — the
        caller hands it to a sibling replica, and this engine's
        preemption counters stay untouched (migration is placement, not
        capacity pressure). Committed pages are RETAINED through the
        prefix cache exactly like a release/context-shift — under
        continued pool pressure they offload host-side through the
        normal reclaim path — so resume is plain re-admission: the
        chained-hash splice (device or host tier) restores the KV, and a
        killed host entry degrades to a full re-prefill of the identical
        token history.
        Invalidation mirrors _context_shift: tokens already emitted are
        kept; tokens still in flight for the slot are dropped from their
        bursts (the resume re-computes from the last kept token)."""
        s = self.slots[slot]
        if s is None:
            return False
        t0 = time.monotonic()
        hist = list(self._cache_tokens[slot])   # prompt + emitted tokens
        committed = min(s.committed, len(hist))
        if self._paged:
            # retention FIRST (slot references still pin the pages), then
            # the whole table returns to the pool for the displacing
            # request — the retained chain survives as cache holds
            if self._pcache is not None:
                self._pcache.insert(self._pool, slot, hist[:committed])
            self._pool.release(slot, 0)
        entry = ResumeEntry(
            req=s.req, ids=hist, priority=s.req.priority,
            generated=list(s.generated), n_decoded=s.n_decoded,
            prompt_len=s.prompt_len, detok=s.detok,
            held_text=s.held_text, t_start=s.t_start,
            t_first_token=s.t_first_token or None,
            t_prefill_ms=s.t_prefill_ms, mu=float(self.mu[slot]),
            preempt_count=s.preempts + (1 if park else 0))
        if park:
            self._sched.park(entry)
            # resume-reserve autosize input (ISSUE 14 satellite): stamp
            # the preemption and fold retained-pages into its EWMA —
            # migrations don't count, they are not capacity pressure
            pg = self._pool.page_size if self._paged else 1
            pages = committed // max(1, pg)
            self._preempt_marks.append(time.monotonic())
            if len(self._preempt_marks) == 1:
                self._preempt_pages_ewma = float(pages)
            else:
                self._preempt_pages_ewma = (
                    0.7 * self._preempt_pages_ewma + 0.3 * pages)
        self.slots[slot] = None
        self.active_dev[slot] = False
        self.lengths[slot] = 0
        # the table is empty now — advertising the old prefix to
        # _pick_slot would promise rows the pool no longer maps
        self._cache_tokens[slot] = []
        try:
            self._prefill_queue.remove(slot)
        except ValueError:
            pass
        # burst boundary: in-flight tokens for this slot are conditioned
        # on state the next tenant overwrites — drop them (same rule as
        # _context_shift / emitter-detected stops)
        for b in self._fifo:
            if isinstance(b, _Burst):
                b.skip_slots.add(slot)
        if park:
            with self._lc_lock:
                self._lc["preemptions"] = self._lc.get("preemptions", 0) + 1
        EVENTS.emit("preempt", rid=s.req.request_id, slot=slot, why=why,
                    priority=s.req.priority, n_decoded=s.n_decoded,
                    retained_rows=committed)
        if self.tracer.enabled:
            self.tracer.record("preempt", f"slot{slot}", t0,
                               time.monotonic(), rid=s.req.request_id,
                               args={"why": why,
                                     "retained_rows": committed})
        return True if park else entry

    def _start_resume(self, entry: "ResumeEntry"):
        """Re-admit a preempted request (ISSUE 10). Admission IS the
        resume path: the full token history (prompt + emitted tokens)
        goes back through _start_request, whose reuse tiers splice the
        retained device chain, restore offloaded pages, or — when the
        host entry was evicted or failed its CRC — fall back to a full
        re-prefill. Either way the continuation is conditioned on the
        identical token history, byte-for-byte what a fresh submission
        of (prompt + emitted tokens) would compute; streaming state
        (detokenizer, held text, counts, timings) carries over so the
        client sees one uninterrupted stream."""
        sched = self._sched
        req = entry.req
        req.prompt_ids = list(entry.ids)
        t0 = time.monotonic()
        slot, ids, s = self._start_request(req, resume=entry)
        sched.resumes += 1
        sched.resume_restore_rows += s.reused
        if s.reused == 0:
            sched.resume_reprefills += 1
        EVENTS.emit("resume", rid=req.request_id, slot=slot,
                    priority=req.priority, reused_rows=s.reused,
                    reprefill_rows=len(ids) - s.reused,
                    parked_ms=round((t0 - entry.t_parked) * 1e3, 1))
        if self.tracer.enabled:
            self.tracer.record("resume", f"slot{slot}", t0,
                               time.monotonic(), rid=req.request_id,
                               args={"reused_rows": s.reused,
                                     "reprefill_rows": len(ids) - s.reused})
        return slot

    # ---- replica-pool surface (ISSUE 14) -------------------------------

    @property
    def loop_alive(self) -> bool:
        """True while the engine loop thread is serving. False after
        shutdown() — or, with _stop still False, after a crash the
        generic recovery could not catch (the replica_die chaos fault):
        that asymmetry is the pool health check's death signal."""
        return self._thread is not None and self._thread.is_alive()

    def request_migration(self, request_id: str, handoff) -> None:
        """Ask the engine loop to eject ``request_id`` at the next tick
        top (a burst boundary, like any preemption). ``handoff(payload)``
        fires on the ENGINE LOOP thread with:
          ("resume", ResumeEntry, mapped_keys)  — was active or parked;
            retained pages force-offloaded to the (shared) host tier and
            mapped under ("migrate", rid) so budget eviction can't race
            the sibling's restore (the pool unmaps after adoption)
          ("fresh", GenRequest, [])             — still queued, nothing
            computed: plain re-submit on the target
          None                                   — unknown/finished, or
            the slot is migration-ineligible (grammar/multimodal/fork
            state does not ride a ResumeEntry)"""
        with self._migrate_lock:
            self._migrate_req[request_id] = handoff
        self._wake.set()

    def adopt_resume(self, entry: "ResumeEntry") -> bool:
        """Adopt a sibling replica's preempted request (migration-in).
        The entry parks in THIS engine's resume queue — without bumping
        its preemption counters — and the normal _admit_sched path
        re-admits it: the chain lookup splices the same pages back from
        the shared host tier, or re-prefills the identical history.
        Thread-safe (list append under the GIL); callable from the pool
        thread. False when this engine has no scheduler (preempt=0)."""
        if self._sched is None:
            return False
        self._sched.adopt(entry)
        self._wake.set()
        return True

    def _process_migrations(self):
        """Engine-loop half of request_migration (tick top)."""
        with self._migrate_lock:
            items = list(self._migrate_req.items())
            self._migrate_req.clear()
        import logging
        log = logging.getLogger(__name__)
        for rid, handoff in items:
            try:
                payload = self._eject_request(rid)
            except Exception:
                log.exception("migration eject failed for %s", rid)
                payload = None
            try:
                handoff(payload)
            except Exception:
                log.exception("migration handoff failed for %s", rid)

    def _eject_request(self, rid: str):
        """Remove ``rid`` from this replica wherever it lives (active
        slot -> pause; queued -> unqueue; parked -> unpark) and return
        the request_migration payload."""
        owner = ("migrate", rid)
        # active slot: PR-10 pause, but hand the entry out instead of
        # parking it (park=False keeps preemption counters honest)
        for i, s in enumerate(self.slots):
            if s is None or s.req.request_id != rid:
                continue
            if self._sched is None or not self._preempt_eligible(i, s):
                return None
            entry = self._preempt_slot(i, why="migrate", park=False)
            if entry is True or not entry:
                return None
            return ("resume", entry, self._offload_chain(entry.ids, owner))
        # still queued: nothing computed yet, plain re-route
        with self._queue.mutex:
            for r in self._queue.queue:
                if r.request_id == rid:
                    self._queue.queue.remove(r)
                    return ("fresh", r, [])
        # parked on this replica's resume queue
        if self._sched is not None:
            entry = self._sched.remove_parked(rid)
            if entry is not None:
                return ("resume", entry,
                        self._offload_chain(entry.ids, owner))
        return None

    def _offload_chain(self, ids, owner=None) -> list:
        """Force-copy the retained device chain for ``ids`` into the
        host tier WITHOUT dropping the device entries (unlike eviction:
        the local copy stays warm; the host copy is what a sibling
        replica restores from). Maps every covered key under ``owner``
        first, so the async put can never lose a budget-eviction race.
        Returns the mapped keys (engine-loop thread only: dispatches a
        device gather)."""
        if self._pcache is None or self._hstore is None:
            return []
        mapped: list = []
        victims: list = []
        for key in self._pcache.chain_keys(ids):
            e = self._pcache._entries.get(key)
            if e is None:
                break
            if owner is not None:
                self._hstore.map_key(key, owner)
                mapped.append(key)
            if not self._hstore.contains(key):
                victims.append((e.key, e.parent, e.depth, e.page))
        if victims:
            self._dispatch_offload(victims)
        return mapped

    # ---- prefill/decode disaggregation (ISSUE 17) ----------------------

    def _process_disagg(self):
        """Engine-loop tick-top on a "prefill"-role engine: retire every
        slot whose prefill has completed (>= 1 decoded token — the
        packed prefill and its first-token emit are done, so TTFT was
        paid HERE) to the cluster transport. The ejection IS the PR-10
        pause primitive with park=False, exactly like live migration:
        the chain force-offloads to the host tier mapped under
        ("disagg", rid) so budget eviction can't race the decode host's
        streamed restore, and the ResumeEntry goes to the registered
        handoff. A handoff that fails re-parks the entry locally — the
        request is never stranded, this engine just decodes it like
        role "both" would."""
        for i, s in enumerate(self.slots):
            if s is None or s.n_decoded < 1 or s.phase != "decode":
                continue
            if getattr(s.req, "_no_disagg", False):
                continue    # router had no decode host: serve locally
            if self._sched is None or not self._preempt_eligible(i, s):
                continue
            rid = s.req.request_id
            entry = self._preempt_slot(i, why="disagg", park=False)
            if entry is True or not entry:
                continue
            keys = self._offload_chain(entry.ids, ("disagg", rid))
            self.disagg_handoffs += 1
            if self._kv_audit is not None:
                self._kv_audit.ledger.record("disagg", rid=rid)
            try:
                self.disagg_handoff(entry, keys)
            except Exception:
                log.exception("disagg handoff failed for %s; decoding "
                              "locally", rid)
                self._sched.adopt(entry)

    def _checkpoint_active_chains(self):
        """Watermark-cadence warm-chain streaming (cluster mode,
        ISSUE 17): retain + force-offload every active slot's committed
        chain so the host tier — and through the wire server, every
        peer — always holds a near-current copy (DejaVu streams KV off
        the accelerator continuously; a crashed host's in-flight work
        then resumes on a sibling from streamed state instead of a full
        re-prefill). Steady-state cost is one pcache.insert dedup and
        one contains() walk per slot — pages already offloaded are
        skipped inside _offload_chain."""
        if self._pcache is None or self._hstore is None or not self._paged:
            return
        for i, s in enumerate(self.slots):
            if s is None or s.win_off > 0:
                continue        # windowed slots checkpoint via demote
            hist = self._cache_tokens[i]
            committed = min(s.committed, len(hist))
            pg = self._pool.page_size
            if committed < pg:
                continue
            self._pcache.insert(self._pool, i, hist[:committed])
            self._offload_chain(hist[:committed])

    def _free_count(self) -> int:
        return sum(1 for s in self.slots if s is None)

    def _reap_cancelled(self):
        if not self._cancelled:
            return
        for i, s in enumerate(self.slots):
            if s is not None and s.req.request_id in self._cancelled:
                self._cancelled.discard(s.req.request_id)
                self._release_slot(i)
                if self._emitter is not None:
                    # close the stream AFTER queued tokens drain (ISSUE 9)
                    self._emitter.push_final(i, s, [None])
                else:
                    s.req.out.put(None)
                # a cancelled LEADER must not strand fork-waiting siblings
                self._process_fork_waiters(i)

    def _reap_expired(self):
        """Per-request deadlines + queue-wait shedding (ISSUE 7), on the
        engine thread at admission ticks. Queued casualties are failed
        directly; active ones go through the normal cancel path so the
        slot, its pages, and any fork waiters are released exactly like a
        client disconnect."""
        timeout_on = self.ecfg.request_timeout_ms > 0
        qwait_s = self.ecfg.max_queue_wait_ms / 1e3
        if not timeout_on and qwait_s <= 0:
            return
        now = time.monotonic()
        # queued requests: scan the underlying deque under the queue's own
        # mutex (queue.Queue exposes it precisely for bulk maintenance)
        with self._queue.mutex:
            victims = [r for r in self._queue.queue
                       if (timeout_on and r.deadline and now > r.deadline)
                       or (qwait_s > 0 and now - r.t_submit > qwait_s)]
            for r in victims:
                self._queue.queue.remove(r)
        for r in victims:
            if timeout_on and r.deadline and now > r.deadline:
                r.out.put(self._timeout_event(r))
                r.out.put(None)
            else:
                self._shed(r, f"queued longer than max_queue_wait_ms "
                              f"({self.ecfg.max_queue_wait_ms} ms)")
        if not timeout_on:
            return
        for i, s in enumerate(self.slots):
            if s is not None and s.req.deadline and now > s.req.deadline \
                    and s.req.request_id not in self._cancelled:
                # decoding for a dead client: error event now, then the
                # cancel path releases the slot and closes the stream
                if self._emitter is not None:
                    # no trailing None here — the cancel path routes the
                    # stream close through the emitter queue itself
                    self._emitter.push_final(i, s, [self._timeout_event(s.req)])
                else:
                    s.req.out.put(self._timeout_event(s.req))
                self.cancel(s.req.request_id)

    def _check_parked_stall(self):
        """Stall detection for the idle branch of the loop: the oldest
        dispatched-but-unready FIFO item is the one the sync worker
        should be finishing right now; if nothing has gone ready within
        the stall budget of its dispatch, it is wedged."""
        stall_s = self.ecfg.dispatch_stall_ms / 1e3
        if stall_s <= 0 or not self._fifo:
            return
        head = self._fifo[0]
        if head.ready.is_set():
            return
        t_dispatch = getattr(head, "t_dispatch", 0.0) or getattr(
            head, "t0", 0.0)
        if time.monotonic() - max(t_dispatch, self._t_last_ready) > stall_s:
            raise _DispatchStall(head)

    def _wait_ready(self, item, t_dispatch: float):
        """Block until the sync worker marks ``item`` ready — with the
        stall watchdog armed (dispatch_stall_ms > 0), never forever.

        The reference point is max(this item's dispatch, the LAST ready
        transition of any item): a deep pipeline where the head is slow
        but the worker is visibly progressing is load, not a stall. jax
        compilation happens inside the dispatch call on this thread, so
        compile time never eats the stall budget."""
        stall_s = self.ecfg.dispatch_stall_ms / 1e3
        if stall_s <= 0:
            item.ready.wait()
            return
        step = min(stall_s / 2, 0.5)
        while not item.ready.wait(timeout=step):
            ref = max(t_dispatch, self._t_last_ready)
            if time.monotonic() - ref > stall_s:
                raise _DispatchStall(item)

    def _handle_stall(self, item):
        """Abort ONLY the stalled item's requests: structured error events,
        span-ring dump to disk (the PR-6 post-mortem follow-up), slots and
        FIFO entry released. Device state is kept — slots outside the
        wedged item keep serving; if the device is truly dead, their own
        dispatches will stall and be reaped the same way."""
        import json as _json
        import logging

        log = logging.getLogger(__name__)
        pairs = item.slots if isinstance(item, _Burst) else item.group
        stalled = [(i, snap) for i, snap in pairs if self.slots[i] is snap]
        with self._lc_lock:
            self._lc["stalls"] += 1
        dump_path = ""
        try:
            from localai_tpu.services.tracing import dump_ring

            dump_path = dump_ring(self.tracer, self.ecfg.stall_dump_dir)
            with self._lc_lock:
                self._lc["stall_dumps"] += 1
        except Exception:
            log.exception("stall ring dump failed")
        log.warning(_json.dumps({
            "event": "dispatch_stall",
            "dispatch_stall_ms": self.ecfg.dispatch_stall_ms,
            "item": type(item).__name__,
            "requests": [snap.req.request_id for _, snap in stalled],
            "slots": [i for i, _ in stalled],
            "ring_dump": dump_path,
        }))
        EVENTS.emit("stall_dump",
                    dispatch_stall_ms=self.ecfg.dispatch_stall_ms,
                    requests=[snap.req.request_id for _, snap in stalled],
                    ring_dump=dump_path)
        # flight recorder (ISSUE 12): the ring dump above is spans only;
        # this bundle adds state + recent events for the same moment
        self._flight_dump("stall", tag="stall",
                          requests=[snap.req.request_id
                                    for _, snap in stalled])
        try:
            self._fifo.remove(item)
        except ValueError:
            pass
        for i, snap in stalled:
            ev = StreamEvent(
                token_id=-1, text="", logprob=0.0, finish_reason="stop",
                error=(f"device dispatch stalled > "
                       f"{self.ecfg.dispatch_stall_ms} ms; request aborted"),
                error_kind="stall")
            if self._emitter is not None:
                # FIFO-ordered behind any tokens already handed over, so
                # the abort reaches queued-but-unemitted tokens too
                self._emitter.push_final(i, snap, [ev, None])
            else:
                snap.req.out.put(ev)
                snap.req.out.put(None)
            self._release_slot(i)
            self._process_fork_waiters(i)

    def _start_request(self, req: GenRequest, resume=None):
        """Admit a request: install sampling state and queue its prompt for
        chunked prefill. No model compute happens here.

        With ``resume`` (a ResumeEntry) this doubles as the preemption
        restore path: ``req.prompt_ids`` already holds the full processed
        history (original prompt + emitted tokens), so head truncation is
        skipped — the history was truncated at first admission and stays
        < C-1 by the context-shift invariant — and the streaming state
        (detokenizer, counts, timings) is grafted onto the fresh slot so
        the client sees one uninterrupted stream."""
        if self._bus is not None and req.mm_vectors is not None:
            raise ValueError(
                "multimodal injection is not supported in multi-host "
                "lockstep mode")
        t_adm = time.monotonic()
        if resume is None:
            EVENTS.emit("admit", rid=req.request_id,
                        prompt_tokens=len(req.prompt_ids),
                        queued=self._queue.qsize())
        C = self.ecfg.max_context
        ids = list(req.prompt_ids)
        shift = 0
        if resume is not None:
            # safety clamp only: keep the tail if the history somehow
            # reached the context edge (the shift path should prevent it)
            if len(ids) > C - 1:
                shift = len(ids) - (C - 1)
                ids = ids[-(C - 1):]
        else:
            # truncate the prompt head, keeping the tail (reference
            # semantics: grpc-server.cpp truncation keeps the prompt tail)
            max_prompt = C - 1 - min(req.max_new_tokens, C // 4)
            if len(ids) > max_prompt:
                shift = len(ids) - max_prompt
                ids = ids[-max_prompt:]
        if not ids:
            ids = [getattr(self.tokenizer, "eos_token_id", 0) or 0]

        mm_pos = mm_vec = None
        if req.mm_vectors is not None and not self._fam_llama:
            raise ValueError("multimodal injection is llama-family only")
        if req.mm_vectors is not None and len(req.mm_positions):
            pos = np.asarray(req.mm_positions, np.int64) - shift
            keep = (pos >= 0) & (pos < len(ids))
            pos = pos[keep]
            vec = np.asarray(req.mm_vectors, np.float32)[keep]
            pb = 16
            while pb < len(pos):
                pb *= 2
            # sentinel >= any bucket so the injection scatter DROPS pads
            # (negative sentinels would wrap to the last column)
            mm_pos = np.full((pb,), 1 << 30, np.int64)
            mm_pos[: len(pos)] = pos
            mm_vec = np.zeros((pb, self.cfg.hidden_size), np.float32)
            mm_vec[: len(pos)] = vec

        slot, common = self._pick_slot(ids)
        assert slot is not None, "_start_request called with no free slot"
        # a short accidental prefix match (e.g. two prompts sharing a BOS or
        # first word) is not worth the slow path it forces: continued
        # prefills run singly while fresh finals batch 8 per dispatch.
        # Reuse only prefixes long enough to beat that cost (real multi-turn
        # chats share hundreds of system/history tokens). Multimodal prompts
        # never reuse (their cache rows hold image embeddings, not tokens).
        if common < 16 or mm_pos is not None:
            common = 0
        if self.ecfg.ga_n > 1 or not self._fam_llama:
            # self-extend re-maps positions as the context grows, and
            # non-llama families have no positional KV rows to share —
            # prefix reuse and prompt-cache restore are llama-only
            common = 0
        win_off = 0
        if self._paged:
            if mm_pos is not None:
                # no reuse or sharing for image rows: recycle the slot's
                # retained pages into the pool
                self._pool.release(slot, 0)
            else:
                # paged reuse: own retained pages, or copy-on-write page
                # sharing from ANY slot's prefix (zero KV row copies).
                # Under self-extend only the tier-3 compressed-region
                # reuse applies (gated inside, ISSUE 16 satellite).
                common = self._paged_admission(slot, ids, common,
                                               rid=req.request_id)
                # snap-back admission (ISSUE 16): ``common`` is COMPACT
                # (sink + window rows); win_off is the skipped middle
                win_off = self._adm_win_off
        if self._fam_llama and self.ecfg.ga_n <= 1 and mm_pos is None \
                and win_off == 0:
            # (the disk prompt cache stores contiguous rows — a windowed
            # table has no contiguous image to overlay, skip it)
            common = self._restore_prompt_cache(slot, req, ids, common)

        # install sampling state for the slot
        self.slot_params = sampling.set_slot(self.slot_params, slot, req.params)
        # mirostat v2 initializes mu at 2*tau (llama.cpp semantics)
        tau = req.params.mirostat_tau if req.params.mirostat_tau > 0 else 5.0
        self.mu[slot] = 2.0 * tau
        if resume is not None and resume.mu is not None:
            self.mu[slot] = resume.mu   # mirostat state survives the pause
        fallback = hash(req.request_id) & 0x7FFFFFFF
        self.rng_keys = sampling.seed_slot_key(
            self.rng_keys, slot, req.params, fallback_seed=fallback
        )
        if self._bus is not None:
            sv = req.params.seed
            self._bus.send("seed", slot=slot,
                           seed=int(sv) if sv is not None and sv >= 0
                           else fallback)
        grammar = gstate = bias_base = penalty0 = None
        if req.grammar:
            grammar = self._grammar_for(req.grammar)
            gstate = grammar.initial_state()
            bias_base = np.zeros((self.cfg.vocab_size,), np.float32)
            for tok, b in (req.params.logit_bias or {}).items():
                t = int(tok)
                if 0 <= t < bias_base.shape[0]:
                    bias_base[t] = float(b)
            penalty0 = self._mask_builder.penalty_row(grammar, gstate)
            self.bias = self.bias.at[slot].set(jnp.asarray(bias_base + penalty0))
            if self._bus is not None:
                from localai_tpu.parallel.lockstep import encode_bias_row

                self._bus.send("bias_rows", slots=[slot],
                               rows=[encode_bias_row(bias_base + penalty0)])
            self._bias_dirty[slot] = True
        elif req.params.logit_bias:
            self.bias = sampling.set_slot_logit_bias(self.bias, slot, req.params)
            if self._bus is not None:
                self._bus.send("bias_sparse", slot=slot,
                               pairs={int(t): float(b) for t, b in
                                      req.params.logit_bias.items()})
            self._bias_dirty[slot] = True
        elif self._bias_dirty[slot]:
            # clear a previous request's grammar mask / bias row; skipping
            # the device write for never-biased slots keeps admission free of
            # dispatches in the common case
            self.bias = self.bias.at[slot].set(0.0)
            if self._bus is not None:
                self._bus.send("bias_clear", slot=slot)
            self._bias_dirty[slot] = False

        # penalty ring covers the prompt tail (llama.cpp last-n semantics
        # include prompt tokens); reused prefixes are part of the prompt
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, ids)
        if common:
            self._reused_total += common

        s = _Slot(req, IncrementalDetokenizer(self.tokenizer), len(ids))
        s.grammar, s.gstate, s.bias_base = grammar, gstate, bias_base
        s.cur_penalty = penalty0
        s.mm_pos, s.mm_vec = mm_pos, mm_vec
        self._init_ga(slot, s, len(ids))
        # per-SLOT speculation eligibility (ISSUE 13 greedy, ISSUE 18
        # sampled: per-request, any drafting mode — with draft=auto every
        # ungrammared llama-family request speculates via n-gram
        # self-drafting; greedy slots accept via accept_greedy
        # (byte-identical), sampled slots via rejection sampling against
        # the filtered verify distribution (distribution-identical)).
        # Gates: ungrammared, no logit_bias, no penalties, no mirostat —
        # the spec verify scores W positions against ONE frozen sampler
        # state, so per-token-evolving logit shaping (penalty ring,
        # mirostat mu) would silently diverge from the burst sampler.
        # The n-gram drafter has no draft state, so reused prefixes and
        # preemption resumes stay eligible; the model drafter on the
        # CONTIGUOUS fallback still requires a draft-mirrored prompt (no
        # reused prefix, no resume) — only the PAGED draft cache shares
        # and restores prefix rows (stale draft planes there cost
        # acceptance quality, never correctness).
        sp = req.params
        s.spec_ok = (self._spec_mode != "off"
                     and not req.grammar
                     and mm_pos is None
                     and not sp.logit_bias
                     and sp.repeat_penalty in (0.0, 1.0)
                     and sp.presence_penalty == 0.0
                     and sp.frequency_penalty == 0.0
                     and (sp.mirostat or 0) == 0)
        if self._spec_mode == "model" and not self._paged \
                and (common != 0 or resume is not None):
            s.spec_ok = False
        if s.spec_ok and self._spec_mode == "model":
            self._ensure_draft_cache()
        s.win_off = win_off
        if win_off:
            # compact coordinates: the reused prefix covers the absolute
            # rows [0, sink) ++ [win_off + sink_rows, win_off + common);
            # pending resumes past the absolute end of the window. RoPE
            # stays absolute via pos_offset (set after _init_ga below).
            s.pending = ids[win_off + common:]
        else:
            s.pending = ids[common:]
        s.written = common
        s.reused = common
        if win_off:
            self.pos_offset[slot] = win_off
        # multimodal rows are image embeddings, not token embeddings — a
        # later text request must never "reuse" them as a token prefix
        self._cache_tokens[slot] = [] if mm_pos is not None else list(ids)
        if resume is not None:
            # graft the paused stream back on: the emitter keys its state
            # on the (slot, snap) it is handed, and its FIFO queue makes
            # handing it the same detokenizer safe across the pause
            s.detok = resume.detok
            s.held_text = resume.held_text
            s.generated = list(resume.generated)
            s.n_decoded = resume.n_decoded
            s.prompt_len = resume.prompt_len
            s.t_start = resume.t_start
            s.t_first_token = resume.t_first_token or 0.0
            s.t_prefill_ms = resume.t_prefill_ms
            s.preempts = resume.preempt_count
        self.slots[slot] = s
        self._prefill_queue.append(slot)
        # fold a watermark sample at admission: a request shorter than the
        # loop's sampling throttle must still leave a high-water mark
        self._sample_watermarks()
        tr = self.tracer
        if tr.enabled and resume is None:
            t1 = time.monotonic()
            if req.t_submit:
                tr.record("queue_wait", f"slot{slot}", req.t_submit,
                          s.t_start, rid=req.request_id)
            # admission covers prefix-cache splice + host-tier restore
            # (_paged_admission / _restore_prompt_cache above)
            tr.record("admission", f"slot{slot}", t_adm, t1,
                      rid=req.request_id,
                      args={"prompt_tokens": len(ids), "reused_rows": common})
        return slot, ids, s

    def _start_fork_sibling(self, req: GenRequest, leader_slot: int,
                            leader_snap: "_Slot", ids: list):
        """Admit a request whose prompt is IDENTICAL to an in-flight
        leader's: install sampling state but prefill nothing — when the
        leader's prefill commits, its KV rows are forked to this slot and
        only the last prompt token is re-prefilled (for this slot's own
        first-token sampling). True shared-prefix for n>1 / simultaneous
        identical prompts (VERDICT r2 #5)."""
        slot, _ = self._pick_slot(ids)
        assert slot is not None
        self.slot_params = sampling.set_slot(self.slot_params, slot, req.params)
        tau = req.params.mirostat_tau if req.params.mirostat_tau > 0 else 5.0
        self.mu[slot] = 2.0 * tau
        self.rng_keys = sampling.seed_slot_key(
            self.rng_keys, slot, req.params,
            fallback_seed=hash(req.request_id) & 0x7FFFFFFF)
        if req.params.logit_bias:
            self.bias = sampling.set_slot_logit_bias(self.bias, slot, req.params)
            self._bias_dirty[slot] = True
        elif self._bias_dirty[slot]:
            self.bias = self.bias.at[slot].set(0.0)
            self._bias_dirty[slot] = False
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, ids)
        s = _Slot(req, IncrementalDetokenizer(self.tokenizer), len(ids))
        s.phase = "fork_wait"
        s.pending = []
        if self._paged:
            # drop the previous tenant's retained pages now: the fork
            # resolution either shares the leader's pages into an empty
            # table or downgrades to a fresh full prefill — and the old
            # pages may be shared with other slots (never overwrite)
            self._pool.release(slot, 0)
        self._cache_tokens[slot] = []
        self.slots[slot] = s
        self._fork_waiters.setdefault(leader_slot, []).append(
            (slot, s, leader_snap, ids))

    def _get_fork_fn(self, shape_key):
        fn = self._fork_fns.get(shape_key)
        if fn is None:
            def body(ck, cv, src, dst, n):
                C = kvcache.shape(ck)[2]
                mask = jnp.arange(C, dtype=jnp.int32) < n
                nk = kvcache.where_rows(mask, kvcache.slot_rows(ck, src),
                                        kvcache.slot_rows(ck, dst))
                nv = kvcache.where_rows(mask, kvcache.slot_rows(cv, src),
                                        kvcache.slot_rows(cv, dst))
                return (kvcache.tree_slot_update(ck, dst, nk),
                        kvcache.tree_slot_update(cv, dst, nv))

            fn = jax.jit(body, donate_argnums=(0, 1))
            self._fork_fns[shape_key] = fn
        return fn

    def _process_fork_waiters(self, leader_slot: int):
        """Called when a leader's final prefill resolves: fork its committed
        rows to each waiting sibling and queue their 1-token finals. A
        vanished/failed leader downgrades siblings to full prefills."""
        waiters = self._fork_waiters.pop(leader_slot, None)
        if not waiters:
            return
        for sib, s, lsnap, ids in waiters:
            if self.slots[sib] is not s:
                continue  # sibling cancelled while waiting
            leader_ok = (self.slots[leader_slot] is lsnap
                         and lsnap.phase == "decode"
                         and self._cache_tokens[leader_slot][:len(ids)] == ids)
            if leader_ok and len(ids) > 1 and self._paged:
                # PAGED fork-dedup: the sibling's table points at the
                # leader's full prompt pages (ref-counted, zero row
                # copies; one boundary-page clone when the prompt ends
                # mid-page). The leader only ever appends past its
                # prompt, so shared pages stay read-only for it.
                n = len(ids) - 1
                self._pool.release(sib, 0)
                shared = self._share_prefix(leader_slot, sib, n)
                s.pending = ids[shared:]
                s.written = shared
                s.committed = shared
                s.reused = shared
                self._reused_total += shared
                self._cache_tokens[sib] = list(ids)
                # paged siblings share the draft planes of the same pages
                # (ISSUE 13), so spec eligibility follows the same
                # admission purity gates as _start_request
                fsp = s.req.params
                s.spec_ok = (self._spec_mode != "off"
                             and not s.req.grammar
                             and s.mm_pos is None
                             and not fsp.logit_bias
                             and fsp.repeat_penalty in (0.0, 1.0)
                             and fsp.presence_penalty == 0.0
                             and fsp.frequency_penalty == 0.0
                             and (fsp.mirostat or 0) == 0)
                if s.spec_ok and self._spec_mode == "model":
                    self._ensure_draft_cache()
            elif leader_ok and len(ids) > 1:
                n = len(ids) - 1
                self.ck, self.cv = self._get_fork_fn("main")(
                    self.ck, self.cv, leader_slot, sib, n)
                # a sibling qualifies under the same purity gates as
                # admission; with the model drafter it additionally needs
                # the leader's draft rows to exist so they can be forked
                sp = s.req.params
                pure = (not s.req.grammar
                        and not sp.logit_bias
                        and sp.repeat_penalty in (0.0, 1.0)
                        and sp.presence_penalty == 0.0
                        and sp.frequency_penalty == 0.0
                        and (sp.mirostat or 0) == 0)
                if self._spec_mode == "model":
                    s.spec_ok = (pure and lsnap.spec_ok
                                 and self.dck is not None)
                else:
                    s.spec_ok = self._spec_mode != "off" and pure
                if self.dck is not None and lsnap.spec_ok:
                    self.dck, self.dcv = self._get_fork_fn("draft")(
                        self.dck, self.dcv, leader_slot, sib, n)
                s.pending = [ids[-1]]
                s.written = n
                s.committed = n
                s.reused = n
                self._reused_total += n
                self._cache_tokens[sib] = list(ids[:-1])
            else:
                # leader gone or 1-token prompt: plain full prefill
                s.pending = list(ids)
                s.written = 0
                self._cache_tokens[sib] = list(ids)
            s.phase = "prefill"
            self._prefill_queue.append(sib)

    # ---------- prompt-cache persistence ----------

    def _get_restore_fn(self):
        fn = self._fork_fns.get("restore")
        if fn is None:
            def body(ck, cv, kfull, vfull, slot, n):
                C = kvcache.shape(ck)[2]
                mask = jnp.arange(C, dtype=jnp.int32) < n
                nk = kvcache.where_rows(mask, kvcache.rows_from_float(kfull, ck),
                                        kvcache.slot_rows(ck, slot))
                nv = kvcache.where_rows(mask, kvcache.rows_from_float(vfull, cv),
                                        kvcache.slot_rows(cv, slot))
                return (kvcache.tree_slot_update(ck, slot, nk),
                        kvcache.tree_slot_update(cv, slot, nv))

            fn = jax.jit(body, donate_argnums=(0, 1))
            self._fork_fns["restore"] = fn
        return fn

    def _load_prompt_cache_rows(self, path: str, m: int):
        """Read a prompt-cache file into float16 staging arrays sized to
        the full cache row shape with rows [:m] filled. Returns
        (kfull, vfull, tokens) or (None, None, None) if unreadable.
        Shared by the leader's restore path and the lockstep follower's
        cache_restore replay (both must build IDENTICAL inputs)."""
        L, _, C, KV, hd = kvcache.shape(self.ck)
        try:
            data = np.load(path)
            ctoks = data["tokens"].tolist()
            # float16 staging (matches the file; halves the host alloc +
            # host->device transfer vs float32 — runs on the engine loop).
            # The row copies stay INSIDE the try: a concurrent re-save
            # (shorter prefix) or a different-config file surfaces as a
            # shape-mismatch ValueError here, and must degrade to
            # no-reuse, not fail the engine loop / kill a follower
            kfull = np.zeros((L, C, KV, hd), np.float16)
            vfull = np.zeros((L, C, KV, hd), np.float16)
            kfull[:, :m] = data["k"][:, :m]
            vfull[:, :m] = data["v"][:, :m]
        except Exception:
            __import__("logging").getLogger(__name__).exception(
                "unreadable prompt cache %s", path)
            return None, None, None
        return kfull, vfull, ctoks

    def _restore_prompt_cache(self, slot: int, req: GenRequest, ids: list,
                              common: int) -> int:
        """If the request names a prompt-cache file whose saved prefix beats
        the slot's own cached prefix, upload those KV rows and return the
        new reusable length (reference: prompt_cache_path restore,
        options.go:182-191)."""
        path = req.prompt_cache_path
        if not path or not os.path.exists(path):
            return common
        try:
            ctoks = np.load(path)["tokens"].tolist()
        except Exception:
            log_ = __import__("logging").getLogger(__name__)
            log_.exception("unreadable prompt cache %s", path)
            return common
        m = 0
        for a, b in zip(ctoks, ids):
            if a != b:
                break
            m += 1
        m = min(m, len(ids) - 1, self.ecfg.max_context - 1)
        if m <= common or m < 16:
            return common
        # re-compare the second read's tokens: a concurrent atomic re-save
        # between the two np.load calls would otherwise install KV rows
        # from a different file version than the prefix validated above
        kfull, vfull, ctoks2 = self._load_prompt_cache_rows(path, m)
        if kfull is None or ctoks2[:m] != ids[:m]:
            return common
        if self._paged:
            # the restore scatter writes rows [0, m) through the slot's
            # table — never into pages other slots still reference: drop
            # any shared pages first (restore beats sharing: m > common)
            npg = min(self._pool.pages_for(m), int(self._pool.owned[slot]))
            if any(self._pool.page_refs(slot, i) > 1 for i in range(npg)):
                self._pool.release(slot, 0)
            self._ensure_pages(slot, m)
            self._commit_ptab()
        if self._bus is not None:
            # followers replay the same restore body from the same file
            # (shared filesystem); the token prefix rides along so a
            # follower seeing a DIFFERENT file version fails loudly
            # instead of silently diverging the mesh
            self._bus.send("cache_restore", slot=slot, m=m, path=path,
                           tokens=ctoks[:m])
        self.ck, self.cv = self._get_restore_fn()(
            self.ck, self.cv, kfull, vfull, slot, m)
        return m

    def _get_cache_export_fn(self, n2: int):
        """Jitted (ck, cv, slot) -> dense float16 rows [L, n2, KV, hd],
        REPLICATED on the mesh: in multi-process serving the slot's rows
        live sharded across processes, so exporting them is a collective
        every process must issue (lockstep op "cache_save")."""
        key = ("export", n2)
        fn = self._fork_fns.get(key)
        if fn is None:
            out_sh = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                out_sh = NamedSharding(self.mesh, P())

            def body(ck, cv, slot):
                kr = kvcache.slot_rows(ck, slot)
                vr = kvcache.slot_rows(cv, slot)
                if kvcache.is_quant(kr):
                    kr = {"q": kr["q"][:, :n2], "s": kr["s"][:, :n2]}
                    vr = {"q": vr["q"][:, :n2], "s": vr["s"][:, :n2]}
                else:
                    kr, vr = kr[:, :n2], vr[:, :n2]
                return (kvcache.rows_to_float(kr, jnp.float16),
                        kvcache.rows_to_float(vr, jnp.float16))

            fn = jax.jit(body, static_argnums=(),
                         out_shardings=(out_sh, out_sh) if out_sh else None)
            self._fork_fns[key] = fn
        return fn

    def _save_prompt_cache(self, slot: int, s: "_Slot"):
        """Persist the slot's committed rows + tokens on finish."""
        req = s.req
        if not req.prompt_cache_path or req.prompt_cache_ro \
                or not self._fam_llama:
            return
        if self.ecfg.ga_n > 1:
            # rows may hold position-compressed (self-extend) keys; a
            # later raw-position engine restoring them would corrupt the
            # reused prefix — and restore is disabled while ga is on
            return
        n = s.committed if req.prompt_cache_all else min(s.prompt_len,
                                                         s.committed)
        tokens = self._cache_tokens[slot][:n]
        n = min(n, len(tokens))
        if n < 16:
            return  # below the reuse threshold; not worth the file
        try:
            # slice on DEVICE now (the backing ck/cv buffers get donated to
            # the next dispatch; an independent slice survives that), at a
            # power-of-two length so only log2(C) slice programs compile.
            # The expensive device->host sync + disk write runs on a
            # background thread, off the serving loop (r3 review finding).
            n2 = 1
            while n2 < n:
                n2 *= 2
            n2 = min(n2, self.ecfg.max_context)
            # dense-f16 export on device (dequantizes int8 rows in-jit, so
            # the file format is cache-dtype independent); in lockstep
            # mode the export is a replicated all-gather COLLECTIVE, so
            # the descriptor goes out first and every process issues it
            if self._bus is not None:
                self._bus.send("cache_save", slot=slot, n2=n2)
            self._commit_ptab()   # export gathers through the page table
            k_dev, v_dev = self._get_cache_export_fn(n2)(
                self.ck, self.cv, np.int32(slot))
            path = req.prompt_cache_path
            toks = np.asarray(tokens[:n], np.int32)

            def write():
                try:
                    k = np.asarray(k_dev)[:, :n]
                    v = np.asarray(v_dev)[:, :n]
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        np.savez(f, tokens=toks, k=k, v=v)
                    os.replace(tmp, path)
                except Exception:
                    __import__("logging").getLogger(__name__).exception(
                        "prompt cache save failed: %s", path)

            threading.Thread(target=write, daemon=True,
                             name="prompt-cache-save").start()
        except Exception:
            __import__("logging").getLogger(__name__).exception(
                "prompt cache save failed: %s", req.prompt_cache_path)

    # ---------- self-extend (group attention) ----------

    def _ga_c(self, P: int) -> int:
        """Position blocks fully compressed after ingesting P tokens."""
        return max(0, (P - 1) // self.ecfg.ga_w)

    def _ga_positions(self, lo: int, hi: int, c: int) -> "np.ndarray":
        """Grouped RoPE positions for raw rows [lo, hi) under c compressed
        blocks: each full block of ga_w raw tokens occupies ga_w/ga_n
        positions (integer-divided, so positions repeat within a group —
        that IS grouped attention); rows past the compressed region keep
        unit spacing."""
        n, w = self.ecfg.ga_n, self.ecfg.ga_w
        i = np.arange(lo, hi, dtype=np.int64)
        pos = np.where(i < c * w,
                       (i // w) * (w // n) + (i % w) // n,
                       c * (w // n) + (i - c * w))
        return pos.astype(np.int32)

    def _prefill_ga_piece(self, slot: int, s: "_Slot") -> bool:
        """One prefill piece for a slot whose prompt spans compressed
        position blocks: explicit grouped positions, one prompt per
        dispatch. (The reference ingests long prompts chunked and then
        divides their cached positions, grpc-server.cpp:1904-1927;
        ingesting directly at the final grouped positions is the same
        mapping without the intermediate surgery.)"""
        chunk = self._chunk
        remaining = len(s.pending)
        final = remaining <= chunk
        take = remaining if final else chunk
        bucket = self._bucket_for(take) if final else chunk
        positions = np.zeros((1, bucket), np.int32)
        positions[0, :take] = self._ga_positions(s.written, s.written + take,
                                                 s.ga_blocks)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :take] = s.pending[:take]
        self._ensure_pages(slot, s.written + take)
        self._commit_ptab()
        t0 = time.monotonic()
        if not final:
            self.ck, self.cv = self._get_ga_chunk_fn(bucket)(
                self.params, tokens, np.array([take], np.int32), self.ck,
                self.cv, np.array([slot], np.int32),
                np.array([s.written], np.int32), positions)
            s.pending = s.pending[take:]
            s.written += take
            s.committed = s.written
            s.t_prefill_ms += (time.monotonic() - t0) * 1e3
            return True
        out_ids, logprobs, self.ck, self.cv, self.rng_keys, mu_out = \
            self._get_ga_final_fn(bucket, s.written > 0)(
                self.params, tokens, np.array([take], np.int32), self.ck,
                self.cv, np.array([slot], np.int32),
                np.array([s.written], np.int32),
                self.ring.copy(), self.ring_pos.copy(), self.bias,
                self.rng_keys, sampling.pack_slot_params(self.slot_params),
                self.mu.copy(), positions)
        s.pending = []
        s.written += take
        if slot in self._prefill_queue:
            self._prefill_queue.remove(slot)
        item = _PendingPrefill([(slot, s)], out_ids, logprobs, mu_out, t0)
        self._fifo.append(item)
        self._sync_q.put(item)
        return True

    def _prefill_win_piece(self, slot: int, s: "_Slot") -> bool:
        """One prefill piece for a snap-back-windowed slot (ISSUE 16):
        cache rows are COMPACT (s.written) but RoPE positions are
        ABSOLUTE (win_off + written + t), so the piece rides the
        explicit-positions programs self-extend already compiled — same
        shapes, different position map, zero new program variants.
        Singly, like ga pieces: the packed/ragged programs derive
        positions from the cache row."""
        chunk = self._chunk
        remaining = len(s.pending)
        final = remaining <= chunk
        take = remaining if final else chunk
        bucket = self._bucket_for(take) if final else chunk
        positions = np.zeros((1, bucket), np.int32)
        positions[0, :take] = s.win_off + s.written + np.arange(
            take, dtype=np.int32)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :take] = s.pending[:take]
        self._ensure_pages(slot, s.written + take)
        self._commit_ptab()
        t0 = time.monotonic()
        if not final:
            self.ck, self.cv = self._get_ga_chunk_fn(bucket)(
                self.params, tokens, np.array([take], np.int32), self.ck,
                self.cv, np.array([slot], np.int32),
                np.array([s.written], np.int32), positions)
            s.pending = s.pending[take:]
            s.written += take
            s.committed = s.written
            s.t_prefill_ms += (time.monotonic() - t0) * 1e3
            return True
        out_ids, logprobs, self.ck, self.cv, self.rng_keys, mu_out = \
            self._get_ga_final_fn(bucket, s.written > 0)(
                self.params, tokens, np.array([take], np.int32), self.ck,
                self.cv, np.array([slot], np.int32),
                np.array([s.written], np.int32),
                self.ring.copy(), self.ring_pos.copy(), self.bias,
                self.rng_keys, sampling.pack_slot_params(self.slot_params),
                self.mu.copy(), positions)
        s.pending = []
        s.written += take
        if slot in self._prefill_queue:
            self._prefill_queue.remove(slot)
        item = _PendingPrefill([(slot, s)], out_ids, logprobs, mu_out, t0)
        self._fifo.append(item)
        self._sync_q.put(item)
        return True

    def _init_ga(self, slot: int, s: "_Slot", P: int):
        """Set the slot's self-extend state for a fresh P-token ingestion."""
        if self.ecfg.ga_n <= 1 or s.mm_pos is not None:
            s.ga_blocks = 0
            self.pos_offset[slot] = 0
            return
        n, w = self.ecfg.ga_n, self.ecfg.ga_w
        s.ga_blocks = self._ga_c(P)
        self.pos_offset[slot] = s.ga_blocks * (w - w // n)

    def _maybe_self_extend(self, slot: int, s: "_Slot") -> bool:
        """Compress newly completed position blocks (reference KV surgery:
        grpc-server.cpp:1904-1927, recomputeless here — cached keys are
        re-rotated in place since RoPE rotations compose). Returns True if
        a compression ran: the slot's not-yet-processed in-flight tokens
        carry stale positions and are dropped (recompute semantics, the
        same trade grammar rollback makes)."""
        n, w = self.ecfg.ga_n, self.ecfg.ga_w
        did = False
        while s.committed >= (s.ga_blocks + 1) * w:
            c = s.ga_blocks
            bd = w - w // n
            deltas = np.zeros((self.ecfg.max_context,), np.int32)
            i = np.arange(c * w, (c + 1) * w, dtype=np.int64)
            old = i - self.pos_offset[slot]
            new = c * (w // n) + (i - c * w) // n
            deltas[c * w:(c + 1) * w] = (new - old).astype(np.int32)
            deltas[(c + 1) * w:s.committed] = -bd
            self._commit_ptab()   # rotation reads/writes via the table
            self.ck = self._get_ga_rotate_fn()(self.ck, np.int32(slot), deltas)
            self.pos_offset[slot] += bd
            s.ga_blocks = c + 1
            did = True
        if did:
            # reset the slot's decode state to host truth: the pending
            # token toks[-1] occupies row cache_len-1 (same corrected
            # recipe as grammar rollback; see the invariant note there)
            self.lengths[slot] = max(s.cache_len - 1, 0)
            toks = self._cache_tokens[slot]
            self.cur_tokens[slot] = toks[-1] if toks else 0
            self.ring, self.ring_pos = sampling.set_slot_ring(
                self.ring, self.ring_pos, slot, toks)
            self._override.add(slot)
            for b in self._fifo:
                if isinstance(b, _Burst):
                    b.skip_slots.add(slot)
        return did

    def _prefill_plan(self, slot: int):
        """(final, take, bucket, continued) for a slot's next chunk."""
        s = self.slots[slot]
        chunk = self._chunk
        remaining = len(s.pending)
        final = remaining <= chunk
        take = remaining if final else chunk
        bucket = self._bucket_for(take) if final else chunk
        return final, take, bucket, s.written > 0

    def _prefill_step(self) -> bool:
        """Process the next prompt chunk(s).

        Fresh FINAL chunks sharing a bucket are batched into ONE dispatch of
        up to _final_pad prompts (padded by repeating the last entry) — the
        reference packs all prompt chunks into one llama_batch
        (grpc-server.cpp:1671+); per-prompt dispatches cost ~150ms of
        overhead each on the serving tunnel. Long-prompt (chunked) and
        continued (prefix-reuse) prefills go singly. Up to TWO final
        groups are in flight at a time (see _process_prefill).
        """
        if sum(1 for x in self._fifo if not isinstance(x, _Burst)) >= 2:
            return False
        while self._prefill_queue:
            slot = self._prefill_queue[0]
            s = self.slots[slot]
            if s is None or s.phase != "prefill":
                self._prefill_queue.pop(0)  # cancelled/stale entry
                continue
            break
        else:
            return False

        if self.ecfg.ga_n > 1 and s.ga_blocks > 0:
            # prompt spans compressed position blocks: explicit grouped
            # positions, singly (never grouped or fused)
            return self._prefill_ga_piece(slot, s)

        if self._win_pages:
            # snap-back during INGESTION too: a fresh long prompt must
            # never grow the device working set past the window — demote
            # committed middle pages before the next chunk lands, then
            # prefill at explicit absolute positions
            self._advance_window(slot, min(len(s.pending), self._chunk))
            if s.win_off > 0:
                return self._prefill_win_piece(slot, s)

        # RAGGED PACKED PREFILL (module doc): when the head slot is
        # eligible, one dispatch packs EVERY eligible queued slot's
        # pending tail under the token budget — replacing per-slot
        # chunks and the same-bucket final groups. Ineligible slots
        # (multimodal shapes, draft-mirrored spec slots) keep their
        # place in the queue and take this per-slot path when they
        # reach the head.
        if self._packed and self._pack_eligible(s):
            return self._prefill_step_packed()

        final, take, bucket, continued = self._prefill_plan(slot)

        def mm_rel(mm_pos, start, take, bucket):
            """Chunk-relative injection positions (pads -> OOB sentinel)."""
            rel = np.where((mm_pos >= start) & (mm_pos < start + take),
                           mm_pos - start, 1 << 30)
            return rel.astype(np.int32)[None]

        t0 = time.monotonic()
        if not final:
            self._ensure_pages(slot, s.written + take)
            self._commit_ptab()
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :take] = s.pending[:take]
            args = (self.params, tokens, np.array([take], np.int32), self.ck,
                    self.cv, np.array([slot], np.int32),
                    np.array([s.written], np.int32))
            if s.mm_pos is not None:
                fn = self._get_mm_chunk_fn(bucket, len(s.mm_pos))
                args = args + (mm_rel(s.mm_pos, s.written, take, bucket),
                               s.mm_vec[None])
            else:
                fn = self._get_chunk_fn(bucket)
                if self._bus is not None:
                    self._bus.send("chunk", bucket=bucket, tokens=tokens,
                                   seq_len=args[2], slot=args[5],
                                   start=args[6])
            with self._annot("prefill_chunk"):
                self.ck, self.cv = fn(*args)
            if self.dck is not None and s.spec_ok:
                # mirror the prompt into the draft cache (speculative
                # rounds need the same context; see engine/speculative.py)
                self.dck, self.dcv = self._get_draft_chunk_fn(bucket)(
                    self.draft_params, tokens, np.array([take], np.int32),
                    self.dck, self.dcv, np.array([slot], np.int32),
                    np.array([s.written], np.int32))
            s.pending = s.pending[take:]
            s.written += take
            s.committed = s.written
            t1 = time.monotonic()
            s.t_prefill_ms += (t1 - t0) * 1e3
            self._hobserve("prefill_dispatch_seconds", t1 - t0)
            if self.tracer.enabled:
                self.tracer.record("prefill_chunk", f"slot{slot}", t0, t1,
                                   rid=s.req.request_id,
                                   args={"tokens": take, "bucket": bucket})
            return True

        # collect a batch of fresh finals with the same bucket (queue order);
        # multimodal finals go singly (their injection shapes are per-request)
        group = [(slot, take)]
        if not continued and s.mm_pos is None:
            for other in self._prefill_queue[1:]:
                if len(group) >= self._final_pad:
                    break
                so = self.slots[other]
                if so is None or so.phase != "prefill" \
                        or so.mm_pos is not None or so.ga_blocks > 0 \
                        or so.win_off > 0:
                    continue
                of, ot, ob, oc = self._prefill_plan(other)
                if of and not oc and ob == bucket:
                    group.append((other, ot))
        # FUSED admission (r4): when the pipeline has room and a full-size
        # burst is runnable, prefill+first-token+decode-burst go out as ONE
        # dispatch (see _fused_body) — no separate prefill dispatch, no
        # activation round-trip, and no reason to hold admissions back
        if (not continued and s.mm_pos is None
                and self._n_inflight_bursts() < self.ecfg.pipeline_depth
                and self._pick_burst(
                    extra=[(t, self.slots[g].req.max_new_tokens)
                           for g, t in group]) == self.ecfg.decode_burst):
            return self._dispatch_fused(group, bucket)
        # pad to the next power of two (each size is precompiled): r3
        # padded every group straight to _final_pad, so a typical group of
        # ~7 prompts burned 2x its prefill compute on repeated padding rows
        if len(group) == 1:
            B = 1
        else:
            B = 2
            while B < len(group):
                B *= 2

        for gslot, gtake in group:
            self._ensure_pages(gslot, self.slots[gslot].written + gtake)
        self._commit_ptab()
        tokens = np.zeros((B, bucket), np.int32)
        seq_len = np.ones((B,), np.int32)
        slots_v = np.zeros((B,), np.int32)
        start_v = np.zeros((B,), np.int32)
        for b in range(B):
            gslot, gtake = group[min(b, len(group) - 1)]  # pad = repeat last
            gs = self.slots[gslot]
            tokens[b, :gtake] = gs.pending[:gtake]
            seq_len[b] = gtake
            slots_v[b] = gslot
            start_v[b] = gs.written

        # ring/ring_pos/slot_params copied: see the aliasing note in
        # _dispatch_decode (in-flight dispatches must not see host mutations)
        args = (self.params, tokens, seq_len, self.ck, self.cv, slots_v, start_v,
                self.ring.copy(), self.ring_pos.copy(), self.bias, self.rng_keys,
                sampling.pack_slot_params(self.slot_params), self.mu.copy())
        if s.mm_pos is not None:
            fn = self._get_mm_final_fn(bucket, len(s.mm_pos), continued)
            args = args + (mm_rel(s.mm_pos, start_v[0], take, bucket),
                           s.mm_vec[None])
        else:
            fn = self._get_final_fn(bucket, B, continued)
            if self._bus is not None:
                self._bus.send("final", bucket=bucket, B=B,
                               continued=continued, tokens=tokens,
                               seq_len=seq_len, slots_v=slots_v,
                               start_v=start_v, ring=args[7],
                               ring_pos=args[8], spp=args[11], mu=args[12])
        with self._annot("prefill_final"):
            out_ids, logprobs, self.ck, self.cv, self.rng_keys, mu_out = \
                fn(*args)
        if self.dck is not None and any(
                self.slots[g].spec_ok for g, _ in group):
            # draft ingests the same prompt rows (no sampling needed);
            # padded/ineligible rows are harmless duplicates
            self.dck, self.dcv = self._get_draft_chunk_fn(bucket)(
                self.draft_params, tokens, seq_len, self.dck, self.dcv,
                slots_v, start_v)
        # ASYNC: don't sync here — the result would be serialized behind any
        # in-flight decode burst, idling the device. The group rides the
        # dispatch FIFO; _drain_fifo block-syncs it when it reaches the
        # head (all device work dispatched before it has then been synced,
        # so the wait is just this prefill's own remaining compute — the
        # r3 design polled is_ready(), which LIES on this platform and
        # turned "ready" results into ~640 ms stalls). Bookkeeping
        # (pending/written) is advanced NOW so a second dispatch can't
        # double-prefill the same slots.
        for gslot, gtake in group:
            gs = self.slots[gslot]
            gs.pending = []
            gs.written += gtake
            if gslot in self._prefill_queue:
                self._prefill_queue.remove(gslot)
        item = _PendingPrefill(
            [(gslot, self.slots[gslot]) for gslot, _ in group],
            out_ids, logprobs, mu_out, t0)
        self._fifo.append(item)
        self._sync_q.put(item)
        t1 = time.monotonic()
        self._hobserve("prefill_dispatch_seconds", t1 - t0)
        if self.tracer.enabled:
            self.tracer.record("prefill_dispatch", "engine", t0, t1,
                               args={"slots": len(group), "bucket": bucket})
        return True

    def _pack_eligible(self, s: "_Slot") -> bool:
        """May this slot's prompt tail ride a ragged pack? Multimodal
        prompts keep their per-request injection shapes (own compiled
        variants) and position-COMPRESSED self-extend slots need
        explicit grouped positions — both go singly. Spec slots pack:
        their draft-cache mirror rides a packed ragged program of its
        own (_get_draft_packed_fn), dispatched right behind the
        target's."""
        return s.mm_pos is None and s.ga_blocks == 0 and s.win_off == 0

    def _prefill_step_packed(self) -> bool:
        """ONE ragged dispatch for this tick's prompt ingestion: walk the
        prefill queue in order, take each eligible slot's pending tail
        (up to prefill_chunk per slot) until the token budget fills,
        and run the packed program. Final segments (ordered FIRST so
        the _PendingPrefill group indexes the output rows 0..F-1)
        sample their first token and ride the dispatch FIFO exactly
        like a legacy final group; non-final segments only advance
        their written/committed bookkeeping — their next chunk packs
        on a later tick, decode bursts interleaving in between."""
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        budget = self._pack_budget
        # weighted-fair packing (ISSUE 10): when slots of MORE THAN ONE
        # priority class have pending prompt tokens, the scheduler's
        # deficit round-robin caps each class's share of the budget.
        # Single-class traffic never enters the DRR path, so the packing
        # below stays bit-for-bit identical to the FIFO engine's.
        infl_vec = None
        drr = None
        sched = self._sched
        if sched is not None:
            infl_vec, pend, _act = self._plan_vec()
            if sum(1 for n in pend if n > 0) > 1:
                sched.begin_tick(budget, pend)
                drr = pend
        segs = []                   # (slot, s, take, final)
        total = 0
        for slot in self._prefill_queue:
            if len(segs) >= S or total >= budget:
                break
            s = self.slots[slot]
            if s is None or s.phase != "prefill" \
                    or not self._pack_eligible(s) or not s.pending:
                continue
            take = min(len(s.pending), self._chunk, budget - total)
            if take <= 0:
                continue
            if drr is not None:
                # slack = budget no other class can absorb this tick
                # (their pending work or deficit is exhausted) — granted
                # beyond the deficit so the walk stays work-conserving
                r = s.prio
                others = sum(min(sched.deficit(j), drr[j])
                             for j in range(len(drr)) if j != r)
                slack = max(0, (budget - total) - others)
                take = sched.take(r, take, slack)
                if take <= 0:
                    continue
                drr[r] = max(0, drr[r] - take)
            segs.append((slot, s, take, take == len(s.pending)))
            total += take
        if not segs:
            return False
        # finals first: _process_prefill reads ids_np[b] for group row b
        segs.sort(key=lambda t: not t[3])

        t0 = time.monotonic()
        for slot, s, take, _f in segs:
            self._ensure_pages(slot, s.written + take)
        self._commit_ptab()

        bucket = next(b for b in self._pack_buckets if total <= b)
        (tokens, positions, seg_of, seg_slots, seg_start, seg_off,
         seg_len, final_mask) = self._pack_arrays(bucket, C, S)
        off = 0
        for b, (slot, s, take, final) in enumerate(segs):
            tokens[off:off + take] = s.pending[:take]
            positions[off:off + take] = np.arange(s.written,
                                                  s.written + take)
            seg_of[off:off + take] = b
            seg_slots[b] = slot
            seg_start[b] = s.written
            seg_off[b] = off
            seg_len[b] = take
            final_mask[b] = final
            off += take
        continued = any(s.written > 0 for _sl, s, _t, _f in segs)

        args = [tokens, positions, seg_of]
        meta = [seg_slots, seg_start, seg_off, seg_len, final_mask]
        if self.mesh is not None:
            # explicit replicated placement for the ragged batch
            # (parallel/sharding.py ragged specs) — the pack has no
            # slot/dp axis for GSPMD to infer
            from jax.sharding import NamedSharding

            from localai_tpu.parallel import sharding as shardlib

            psh = NamedSharding(self.mesh, shardlib.ragged_pack_spec())
            ssh = NamedSharding(self.mesh, shardlib.ragged_seg_spec())
            args = [jax.device_put(a, psh) for a in args]
            meta = [jax.device_put(a, ssh) for a in meta]

        self._pack_stats["dispatches"] += 1
        self._pack_stats["tokens"] += total
        self._pack_stats["segments"] += len(segs)
        self._pack_stats["pad_tokens"] += bucket - total
        if continued and llama.ragged_kernel_shape_fallback(
                self.ck, bucket, self.cfg):
            # this pack's SHAPE pushed the attention off the Pallas
            # kernel (the pre-segment-blocked grid fell back above ~1k
            # tokens at 8B head shapes) — counted per dispatch so the
            # cliff is observable in metrics() and gated in CI. Fresh
            # packs (continued=False) read no cache rows and take the
            # jnp path by design, so they never count.
            self._pack_stats["kernel_fallback"] += 1

        if self.dck is not None and any(
                s.spec_ok for _sl, s, _t, _f in segs):
            # draft mirrors the SAME ragged pack (no sampling): spec
            # slots used to force the whole pack onto the per-slot path;
            # padded / spec-ineligible segments are harmless duplicate
            # KV writes into draft rows nobody reads
            self.dck, self.dcv = self._get_draft_packed_fn(bucket)(
                self.draft_params, *args, *meta[:4], self.dck, self.dcv)

        # FUSED packed admission: when the pipeline has room and a
        # full-size burst is runnable, ragged prefill + first tokens +
        # the decode burst go out as ONE dispatch (_fused_packed_body)
        # in "mono" mode, or as the early-emit back-to-back pair
        # (_dispatch_packed_split) in "split" mode — the packed
        # generalization of _dispatch_fused, covering continued
        # segments too
        finals = [(slot, s, take) for slot, s, take, f in segs if f]
        if (finals and self._pack_fuse != "off"
                and self._n_inflight_bursts() < self.ecfg.pipeline_depth
                and self._pick_burst(
                    extra=[(s.written + t, s.req.max_new_tokens)
                           for _sl, s, t in finals],
                    infl_vec=infl_vec)
                == self.ecfg.decode_burst):
            if self._pack_fuse == "split":
                return self._dispatch_packed_split(segs, args, meta,
                                                   bucket, continued, t0)
            return self._dispatch_packed_fused(segs, args, meta, bucket,
                                               continued, t0)

        fn = self._get_packed_fn(bucket, continued)
        # ring/ring_pos/mu copied: in-flight dispatches must not see
        # host mutations (same aliasing rule as the legacy finals)
        with self._annot("prefill_pack"):
            out_ids, logprobs, self.ck, self.cv, self.rng_keys, mu_out = fn(
                self.params, *args, *meta, self.ck, self.cv,
                self.ring.copy(), self.ring_pos.copy(), self.bias,
                self.rng_keys, sampling.pack_slot_params(self.slot_params),
                self.mu.copy())

        group = []
        t1 = time.monotonic()
        for slot, s, take, final in segs:
            s.pending = s.pending[take:]
            s.written += take
            if final:
                if slot in self._prefill_queue:
                    self._prefill_queue.remove(slot)
                group.append((slot, s))
            else:
                # non-final: KV rows are committed in device dispatch
                # order (same contract as the legacy chunk path)
                s.committed = s.written
                s.t_prefill_ms += (t1 - t0) * 1e3
        self._tmark("dispatch_packed", t0)
        self._hobserve("prefill_dispatch_seconds", t1 - t0)
        if self.tracer.enabled:
            self.tracer.record("prefill_dispatch", "engine", t0, t1,
                               args={"tokens": total, "segments": len(segs),
                                     "bucket": bucket, "packed": True})
        if group:
            item = _PendingPrefill(group, out_ids, logprobs, mu_out, t0)
            self._fifo.append(item)
            self._sync_q.put(item)
        return True

    def _dispatch_packed_fused(self, segs, args, meta, bucket: int,
                               continued: bool, t0: float) -> bool:
        """Dispatch ragged prefill + first-token sampling + a full decode
        burst in ONE device call (_fused_packed_body). Final segments'
        slots flip to decode NOW and their first tokens come back in the
        burst's packed results (_process_burst group handling, identical
        to the legacy fused path); non-final segments only advance their
        prefill bookkeeping."""
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        K = self.ecfg.decode_burst
        group_snaps = []
        t1 = time.monotonic()
        for slot, s, take, final in segs:
            s.pending = s.pending[take:]
            s.written += take
            if not final:
                s.committed = s.written
                s.t_prefill_ms += (t1 - t0) * 1e3
                continue
            s.phase = "decode"
            # cache_len must reflect the prompt rows NOW (_pick_burst /
            # _plan_spec cost capacity against in-flight steps)
            s.cache_len = s.written
            self.lengths[slot] = s.written
            self.active_dev[slot] = True
            self._override.add(slot)
            if slot in self._prefill_queue:
                self._prefill_queue.remove(slot)
            group_snaps.append((slot, s))
        # budget-mask other decoding slots exactly like _dispatch_decode
        # (one FIFO pass for all slots' in-flight counts — ISSUE 9)
        infl = self._inflight_vec()
        active = self.active_dev.copy()
        included = list(group_snaps)
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode" \
                    or any(g == i for g, _ in group_snaps):
                continue
            if s.req.max_new_tokens - s.n_decoded - infl[i] <= 0:
                active[i] = False
                continue
            included.append((i, s))
        for gslot, gs in group_snaps:
            # pages for the prompt rows AND the K fused burst steps
            self._ensure_pages(gslot, min(C, gs.written + K + 2))
        for i, s in included:
            if any(g == i for g, _ in group_snaps):
                continue
            self._ensure_pages(i, min(C, int(self.lengths[i])
                                      + infl[i] + K + 2))
        self._commit_ptab()
        ov_mask = np.zeros((S,), np.bool_)
        if self._chain is None:
            chain = (self.cur_tokens.copy(), self.lengths.copy(),
                     self.ring.copy(), self.ring_pos.copy(), self.mu.copy())
        else:
            chain = self._chain
            for i in self._override:
                ov_mask[i] = True
        self._override.clear()
        fn = self._get_fused_packed_fn(bucket, continued)
        spp = sampling.pack_slot_params(self.slot_params)
        ovp = self._pack_ov(ov_mask)
        with self._annot("prefill_pack_fused"):
            pack, self.ck, self.cv, self.rng_keys, self._chain = fn(
                self.params, chain[0], self.ck, self.cv, chain[1],
                chain[2], chain[3], self.bias, self.rng_keys,
                spp, active, chain[4], ovp, *args, *meta)
        self._tmark("dispatch_packed_fused", t0)
        self._hobserve("prefill_dispatch_seconds", time.monotonic() - t0)
        if self.tracer.enabled:
            self.tracer.record("prefill_dispatch", "engine", t0,
                               time.monotonic(),
                               args={"segments": len(segs), "bucket": bucket,
                                     "packed": True, "fused": True})
        if self._trace:
            s_ = self._tstats.setdefault("burst_steps", [0.0, 0])
            s_[0] += K
            s_[1] += 1
            occ = self._tstats.setdefault("active_slots", [0.0, 0])
            occ[0] += len(included)
            occ[1] += 1
        b = _Burst(K, included, pack, group=group_snaps, t_dispatch=t0)
        self._fifo.append(b)
        self._sync_q.put(b)
        return True

    def _dispatch_packed_split(self, segs, args, meta, bucket: int,
                               continued: bool, t0: float) -> bool:
        """EARLY-EMIT fused tick: the same one-tick work as
        _dispatch_packed_fused, issued as TWO dispatches — the prefill
        half (_split_head_body: ragged prefill + first-token sampling +
        chain-state fold) and a plain decode burst chained off its
        device outputs. Between them the head's first tokens are synced
        and EMITTED (the only host round-trip; the device is computing
        the head for its whole duration, so the pipeline bubble is just
        the emit + dispatch latency) — finals' TTFT stops paying for the
        decode half (the tradeoff that kept fused auto real-chip-only).
        Host bookkeeping is the fused path's: finals flip to decode NOW,
        the burst rides the FIFO with ``head`` linked for its
        first-token rows."""
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        K = self.ecfg.decode_burst
        group_snaps = []
        t1 = time.monotonic()
        for slot, s, take, final in segs:
            s.pending = s.pending[take:]
            s.written += take
            if not final:
                s.committed = s.written
                s.t_prefill_ms += (t1 - t0) * 1e3
                continue
            s.phase = "decode"
            s.cache_len = s.written
            self.lengths[slot] = s.written
            self.active_dev[slot] = True
            self._override.add(slot)
            if slot in self._prefill_queue:
                self._prefill_queue.remove(slot)
            group_snaps.append((slot, s))
        infl = self._inflight_vec()
        active = self.active_dev.copy()
        included = list(group_snaps)
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode" \
                    or any(g == i for g, _ in group_snaps):
                continue
            if s.req.max_new_tokens - s.n_decoded - infl[i] <= 0:
                active[i] = False
                continue
            included.append((i, s))
        for gslot, gs in group_snaps:
            self._ensure_pages(gslot, min(C, gs.written + K + 2))
        for i, s in included:
            if any(g == i for g, _ in group_snaps):
                continue
            self._ensure_pages(i, min(C, int(self.lengths[i])
                                      + infl[i] + K + 2))
        self._commit_ptab()
        ov_mask = np.zeros((S,), np.bool_)
        if self._chain is None:
            chain = (self.cur_tokens.copy(), self.lengths.copy(),
                     self.ring.copy(), self.ring_pos.copy(), self.mu.copy())
        else:
            chain = self._chain
            for i in self._override:
                ov_mask[i] = True
        self._override.clear()
        spp = sampling.pack_slot_params(self.slot_params)
        head_fn = self._get_split_head_fn(bucket, continued)
        with self._annot("prefill_pack_head"):
            ids_f, lps_f, self.ck, self.cv, self.rng_keys, chain = head_fn(
                self.params, chain[0], self.ck, self.cv, chain[1],
                chain[2], chain[3], self.bias, self.rng_keys, spp,
                active, chain[4], self._pack_ov(ov_mask), *args, *meta)
        # EARLY EMIT before the decode half goes out: the head's tiny
        # outputs (first ids/logprobs/mu) sync on the worker while the
        # device is still computing them, the engine processes the group
        # — first tokens reach the streams HERE — and only then issues
        # the chained burst. On async backends the pipeline bubble is
        # just this host round-trip (the device is busy with the head
        # for the whole wait); on synchronous-dispatch backends (the CPU
        # smoke rig, where a jit call blocks for its own compute) the
        # wait is free — so TTFT stops paying for the decode half, which
        # is this mode's reason to exist ("mono" keeps the zero-bubble
        # fully-fused tick for throughput-first deployments).
        head = _PendingPrefill(group_snaps, ids_f, lps_f, chain[4], t0,
                               split=True)
        self._fifo.append(head)      # discoverable for the stall handler
        self._sync_q.put(head)
        self._wait_ready(head, t0)
        self._fifo.remove(head)
        tp = time.monotonic()
        self._process_prefill(head)
        self._tmark("finalize", tp)
        # a grammar rollback / context shift inside the head's emission
        # corrects host mirrors and poisons in-flight bursts by walking
        # the FIFO — the chained burst isn't dispatched yet, so it missed
        # that walk: anything newly in _override sampled conditioned on
        # state the rollback discarded and must be skipped the same way
        poisoned = set(self._override)
        # the burst chains off the head's DEVICE outputs: overrides were
        # consumed by the head, so its ov mask is all-False (pos_offset
        # still rides — it is current-host-truth every dispatch)
        burst_fn = self._get_burst_fn(K)
        with self._annot("decode_burst"):
            pack, self.ck, self.cv, self.rng_keys, self._chain = burst_fn(
                self.params, chain[0], self.ck, self.cv, chain[1],
                chain[2], chain[3], self.bias, self.rng_keys, spp,
                active, chain[4], self._pack_ov(np.zeros((S,), np.bool_)))
        self._tmark("dispatch_packed_split", t0)
        self._hobserve("prefill_dispatch_seconds", time.monotonic() - t0)
        if self.tracer.enabled:
            self.tracer.record("prefill_dispatch", "engine", t0,
                               time.monotonic(),
                               args={"segments": len(segs), "bucket": bucket,
                                     "packed": True, "fused": "split"})
        if self._trace:
            s_ = self._tstats.setdefault("burst_steps", [0.0, 0])
            s_[0] += K
            s_[1] += 1
            occ = self._tstats.setdefault("active_slots", [0.0, 0])
            occ[0] += len(included)
            occ[1] += 1
        b = _Burst(K, included, pack, group=group_snaps, t_dispatch=t0,
                   head=head)
        b.skip_slots |= poisoned
        self._fifo.append(b)
        self._sync_q.put(b)
        return True

    def _dispatch_fused(self, group, bucket: int) -> bool:
        """Dispatch final-prefill + first-token sampling + a full decode
        burst for ``group`` (fresh, non-multimodal prompts) in ONE device
        call. The group's slots flip to decode phase NOW; their first
        tokens come back in the burst's packed results."""
        t_d = time.monotonic()
        S = self.ecfg.num_slots
        K = self.ecfg.decode_burst
        if len(group) == 1:
            B = 1
        else:
            B = 2
            while B < len(group):
                B *= 2
        p_tokens = np.zeros((B, bucket), np.int32)
        p_seq = np.ones((B,), np.int32)
        p_slots = np.zeros((B,), np.int32)
        p_start = np.zeros((B,), np.int32)
        for b in range(B):
            gslot, gtake = group[min(b, len(group) - 1)]  # pad = repeat last
            gs = self.slots[gslot]
            p_tokens[b, :gtake] = gs.pending[:gtake]
            p_seq[b] = gtake
            p_slots[b] = gslot
            p_start[b] = gs.written
        group_snaps = []
        for gslot, gtake in group:
            gs = self.slots[gslot]
            gs.pending = []
            gs.written += gtake
            gs.phase = "decode"
            # cache_len must reflect the prompt rows NOW: _pick_burst and
            # _plan_spec cost capacity as cache_len + inflight decode
            # steps, and the fused burst is in flight from this moment
            gs.cache_len = gs.written
            self.lengths[gslot] = gs.written
            self.active_dev[gslot] = True
            self._override.add(gslot)
            if gslot in self._prefill_queue:
                self._prefill_queue.remove(gslot)
            group_snaps.append((gslot, gs))
        # budget-mask other decoding slots exactly like _dispatch_decode
        # (one FIFO pass for all slots' in-flight counts — ISSUE 9)
        infl = self._inflight_vec()
        active = self.active_dev.copy()
        included = list(group_snaps)
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode" or any(g == i for g, _ in group_snaps):
                continue
            if s.req.max_new_tokens - s.n_decoded - infl[i] <= 0:
                active[i] = False
                continue
            included.append((i, s))
        C = self.ecfg.max_context
        for gslot, gs in group_snaps:
            # pages for the prompt rows AND the K fused burst steps
            self._ensure_pages(gslot, min(C, gs.written + K + 2))
        for i, s in included:
            if any(g == i for g, _ in group_snaps):
                continue
            self._ensure_pages(i, min(C, int(self.lengths[i])
                                      + infl[i] + K + 2))
        self._commit_ptab()
        ov_mask = np.zeros((S,), np.bool_)
        if self._chain is None:
            chain = (self.cur_tokens.copy(), self.lengths.copy(),
                     self.ring.copy(), self.ring_pos.copy(), self.mu.copy())
        else:
            chain = self._chain
            for i in self._override:
                ov_mask[i] = True
        cold = self._chain is None
        self._override.clear()
        fn = self._get_fused_fn(bucket, B)
        spp = sampling.pack_slot_params(self.slot_params)
        ovp = self._pack_ov(ov_mask)
        if self._bus is not None:
            self._bus.send("fused", bucket=bucket, B=B,
                           chain=chain if cold else None,
                           spp=spp, active=active, ovp=ovp,
                           p_tokens=p_tokens, p_seq=p_seq, p_slots=p_slots,
                           p_start=p_start)
        with self._annot("prefill_fused"):
            pack, self.ck, self.cv, self.rng_keys, self._chain = fn(
                self.params, chain[0], self.ck, self.cv, chain[1],
                chain[2], chain[3], self.bias, self.rng_keys,
                spp, active, chain[4], ovp,
                p_tokens, p_seq, p_slots, p_start,
            )
        if self.dck is not None and any(s.spec_ok for _, s in group_snaps):
            self.dck, self.dcv = self._get_draft_chunk_fn(bucket)(
                self.draft_params, p_tokens, p_seq, self.dck, self.dcv,
                p_slots, p_start)
        self._tmark("dispatch_fused", t_d)
        self._hobserve("prefill_dispatch_seconds", time.monotonic() - t_d)
        if self.tracer.enabled:
            self.tracer.record("prefill_dispatch", "engine", t_d,
                               time.monotonic(),
                               args={"slots": len(group_snaps),
                                     "bucket": bucket, "fused": True})
        if self._trace:
            s_ = self._tstats.setdefault("burst_steps", [0.0, 0])
            s_[0] += K
            s_[1] += 1
            occ = self._tstats.setdefault("active_slots", [0.0, 0])
            occ[0] += len(included)
            occ[1] += 1
        b = _Burst(K, included, pack, group=group_snaps, t_dispatch=t_d)
        self._fifo.append(b)
        self._sync_q.put(b)
        return True

    def _process_prefill(self, item: "_PendingPrefill"):
        """Activate a dispatched final-prefill group (its results already
        synced by the worker): flip the slots to decode phase and mark
        them as chain OVERRIDES so the next burst dispatch picks their
        state from the host mirrors without a chain rebuild."""
        if not item.ready.is_set():
            tr = time.monotonic()
            self._wait_ready(item, item.t0)
            self._tmark("finalize_sync", tr)
        if item.err is not None:
            raise item.err
        if item.split:
            return self._process_split_head(item)
        group = item.group
        ids_np, lps_np, mu_np, t0 = item.ids_np, item.lps_np, item.mu_np, item.t0
        # scatter ONLY the group's mu entries — and only where the slot
        # still belongs to the dispatched request: a cancel + re-admit while
        # the prefill was in flight must not inherit the stale mu
        for gslot, snap in group:
            if self.slots[gslot] is snap:
                self.mu[gslot] = mu_np[gslot]
        t1 = time.monotonic()
        trc = self.tracer
        if trc.enabled and item.t_ready:
            # dispatch start -> sync-worker ready: device compute (plus
            # queueing behind earlier dispatches); ready -> now: the
            # engine loop's pickup lag
            trc.record("prefill_device", "engine", t0, item.t_ready,
                       args={"slots": len(group)})
            trc.record("finish_detect", "engine", item.t_ready, t1)

        for b, (gslot, snap) in enumerate(group):
            gs = self.slots[gslot]
            if gs is not snap:
                continue  # cancelled while the prefill was in flight
            first_id = int(ids_np[b])
            gs.cache_len = gs.written
            gs.committed = gs.written
            gs.phase = "decode"

            self.lengths[gslot] = gs.written
            self.cur_tokens[gslot] = first_id
            self.active_dev[gslot] = True
            self._override.add(gslot)
            # mirror the sampled token into the penalty ring
            self.ring[gslot, self.ring_pos[gslot] % sampling.RING_N] = first_id
            self.ring_pos[gslot] += 1

            gs.t_prefill_ms += (t1 - t0) * 1e3
            if gs.t_first_token == 0.0:
                gs.t_first_token = t1
                if gs.req.t_submit:
                    self._hobserve("ttft_seconds", t1 - gs.req.t_submit,
                                   rid=gs.req.request_id)
                if trc.enabled:
                    trc.record("prefill", f"slot{gslot}", t0, t1,
                               rid=gs.req.request_id,
                               args={"prompt_tokens": gs.prompt_len})
            self._emit(gslot, first_id, float(lps_np[b]))
        # leaders just committed: fork their rows to any waiting siblings
        # (vanished leaders downgrade the siblings to full prefills)
        for gslot, _snap in group:
            self._process_fork_waiters(gslot)
        self._flush_grammar_bias()
        self._flush_em_batch()

    def _process_split_head(self, item: "_PendingPrefill"):
        """EARLY-EMIT head processing (results already synced): emit the
        final segments' first tokens and stamp TTFT — NOTHING else. The
        slots flipped to decode at dispatch, the device chain state was
        advanced in-program, and the chained burst's fold carries the
        host-mirror updates; writing mirrors here would race the
        in-flight burst (a later dispatch composing them as overrides
        would REWIND device state). A grammar rollback / context shift /
        self-extend inside _emit poisons pipelined bursts via the usual
        FIFO walk; the burst CHAINED to this head dispatches after this
        runs, so _dispatch_packed_split carries anything newly overridden
        here into its skip_slots instead."""
        if item.processed:
            return
        item.processed = True
        group = item.group
        ids_np, lps_np, t0 = item.ids_np, item.lps_np, item.t0
        t1 = time.monotonic()
        trc = self.tracer
        if trc.enabled and item.t_ready:
            trc.record("prefill_device", "engine", t0, item.t_ready,
                       args={"slots": len(group), "split": True})
            trc.record("finish_detect", "engine", item.t_ready, t1)
        for b, (gslot, snap) in enumerate(group):
            gs = self.slots[gslot]
            if gs is not snap:
                continue  # cancelled while the head was in flight
            gs.committed = gs.written
            gs.t_prefill_ms += (t1 - t0) * 1e3
            if gs.t_first_token == 0.0:
                gs.t_first_token = t1
                if gs.req.t_submit:
                    self._hobserve("ttft_seconds", t1 - gs.req.t_submit,
                                   rid=gs.req.request_id)
                if trc.enabled:
                    trc.record("prefill", f"slot{gslot}", t0, t1,
                               rid=gs.req.request_id,
                               args={"prompt_tokens": gs.prompt_len,
                                     "fused": "split"})
            self._emit(gslot, int(ids_np[b]), float(lps_np[b]))
        for gslot, _snap in group:
            self._process_fork_waiters(gslot)
        self._flush_grammar_bias()
        self._flush_em_batch()

    def _pack_ov(self, ov_mask) -> "np.ndarray":
        """Build the packed override upload. Round-robin buffer reuse
        (ISSUE 9): a dispatch's async host->device copy must never read
        a buffer a LATER dispatch is refilling, so the pool is deeper
        than the pipeline can hold in flight — no per-dispatch
        allocation, no aliasing of live host mirrors."""
        p = self._ov_pool[self._ov_pool_idx]
        self._ov_pool_idx = (self._ov_pool_idx + 1) % len(self._ov_pool)
        p[0] = ov_mask
        p[1] = self.cur_tokens
        p[2] = self.lengths
        p[3] = self.ring_pos
        p[4] = self.mu
        p[5] = self.pos_offset
        # window-advance length rebase (ISSUE 16): subtracted from the
        # chained device lengths unconditionally; overridden slots take
        # their (already rebased) host lengths instead, so their delta
        # must not apply on top — and a COLD dispatch feeds rebased host
        # lengths for EVERY slot, so the whole delta row drops
        if self._chain is None:
            self._win_delta.fill(0)
        p[6] = self._win_delta
        p[6][np.asarray(ov_mask, bool)] = 0.0
        self._win_delta.fill(0)
        p[7:] = self.ring.T
        return p

    def _pack_arrays(self, bucket: int, C: int, S: int) -> tuple:
        """Reusable (round-robin) host arrays for one packed-prefill
        dispatch, reset to their pad values (ISSUE 9: eight fresh
        allocations per packed dispatch, gone). Pool depth mirrors
        _pack_ov: deeper than the pipeline can hold in flight, so an
        async upload never reads a buffer being refilled."""
        pool = self._seg_pools.get(bucket)
        if pool is None:
            depth = max(6, self.ecfg.pipeline_depth + 4)
            pool = self._seg_pools[bucket] = [
                (np.empty((bucket,), np.int32),    # tokens
                 np.empty((bucket,), np.int32),    # positions
                 np.empty((bucket,), np.int32),    # seg_of
                 np.empty((S,), np.int32),         # seg_slots
                 np.empty((S,), np.int32),         # seg_start
                 np.empty((S,), np.int32),         # seg_off
                 np.empty((S,), np.int32),         # seg_len
                 np.empty((S,), np.bool_))         # final_mask
                for _ in range(depth)]
            self._seg_pool_idx[bucket] = 0
        i = self._seg_pool_idx[bucket]
        self._seg_pool_idx[bucket] = (i + 1) % len(pool)
        (tokens, positions, seg_of, seg_slots, seg_start, seg_off,
         seg_len, final_mask) = pool[i]
        tokens.fill(0)
        positions.fill(C)      # pad: scatter drops
        seg_of.fill(S)         # pad: own segment id
        seg_slots.fill(S)      # pad: state writes drop
        seg_start.fill(0)
        seg_off.fill(0)
        seg_len.fill(0)
        final_mask.fill(False)
        return pool[i]

    def _n_inflight_bursts(self) -> int:
        return sum(1 for x in self._fifo if isinstance(x, _Burst))

    def _inflight_vec(self) -> list:
        """Decode tokens already dispatched (unprocessed) per slot, in
        ONE pass over the FIFO (ISSUE 9): dispatch planners that used to
        call the per-slot scan once per candidate slot — rescanning the
        FIFO S times per dispatch — take this vector once instead."""
        n = [0] * self.ecfg.num_slots
        for b in self._fifo:
            if not isinstance(b, _Burst):
                continue
            gset = {i for i, _ in b.group}
            for i, _ in b.slots:
                if i not in b.skip_slots:
                    # spec-masked slots may emit up to W tokens per round
                    # (conservative upper bound — acceptance is unknown
                    # until the tick syncs)
                    w = (b.spec_width if b.spec_width
                         and b.spec_mask[i] else 1)
                    n[i] += b.n_steps * w + (1 if i in gset else 0)
        return n

    def _inflight_steps(self, slot: int) -> int:
        """Decode tokens already dispatched (unprocessed) for a slot."""
        return self._inflight_vec()[slot]

    def _plan_vec(self):
        """One-pass planner state for a tick (ISSUE 10, extending the
        ISSUE-9 one-pass FIFO walk to the admission/budget walk): the
        in-flight vector plus per-class accounting — pending prompt
        tokens a class could pack this tick (chunk-capped, like the
        packed walk's own ``take``) and active slot counts.  Returns
        ``(infl_vec, pending_by_class, active_by_class)``."""
        infl_vec = self._inflight_vec()
        ncls = len(PRIORITY_CLASSES)
        pend = [0] * ncls
        act = [0] * ncls
        for s in self.slots:
            if s is None:
                continue
            act[s.prio] += 1
            if s.phase == "prefill" and s.pending:
                pend[s.prio] += min(len(s.pending), self._chunk)
        return infl_vec, pend, act

    def _drain_fifo(self, can_feed: bool = False,
                    block: bool = True) -> bool:
        """Process dispatched work. Prefill groups activate as soon as the
        sync worker flags them ready (any position in the FIFO — safe:
        a prefill group's slots are disjoint from every in-flight burst's
        participants, since they were mid-prefill at those dispatches).
        The oldest burst is block-synced only when the pipeline is already
        full or nothing more can be dispatched (``can_feed`` False) — at
        most one BLOCKING sync per call, so the loop refills the pipeline
        between syncs and the device always has work queued. Bursts that
        are ALREADY ready are all processed (ISSUE 9: their device work
        is done, so holding them to one per tick only inflated
        finish-detect by a full tick per queued burst); ``block`` False
        skips the blocking sync entirely (top-of-tick drain: pick up
        whatever completed while the previous tick packed prompts)."""
        progressed = False
        for item in [x for x in self._fifo
                     if not isinstance(x, _Burst) and x.ready.is_set()]:
            self._fifo.remove(item)
            t0 = time.monotonic()
            self._process_prefill(item)
            self._tmark("finalize", t0)
            progressed = True
        synced = False
        while True:
            acted = False
            for idx, item in enumerate(self._fifo):
                if not isinstance(item, _Burst):
                    continue   # a not-yet-ready prefill ahead; bursts may
                    # pass it
                if not item.ready.is_set():
                    if not block or synced or (
                            can_feed and self._n_inflight_bursts()
                            < self.ecfg.pipeline_depth):
                        break
                    synced = True
                del self._fifo[idx]
                t0 = time.monotonic()
                self._process_burst(item)
                self._tmark("process_burst", t0)
                progressed = True
                acted = True
                break
            if not acted or self._emitter is None:
                # emitter=0: at most one burst per call (seed cadence —
                # in-loop emission is too expensive to batch up)
                break
        return progressed

    def _pick_burst(self, extra=None, infl_vec=None) -> int:
        """Burst length for this dispatch: a power of two <= decode_burst,
        clamped so no slot crosses its context-shift threshold mid-burst
        (tokens past the threshold would be silently position-less).
        Grammar-constrained slots ride FULL bursts speculatively: tokens
        are verified against the automaton at processing time and the slot
        rolls back (free — recompute semantics) on the first invalid one
        (r3; replaces the r2 design that forced burst=1 fleet-wide).
        Slots that finish mid-burst (EOS/stop/budget) simply ride out the
        burst; their tail tokens are discarded host-side — cheaper than
        clamping every slot to the smallest remaining budget. Host mirrors
        lag by every in-flight (pipelined) burst, so those steps count
        against the capacity clamp too."""
        cap = self.ecfg.decode_burst
        budget = 1
        if infl_vec is None:
            infl_vec = self._inflight_vec()
        for i, s in enumerate(self.slots):
            if s is None or s.phase != "decode":
                continue
            infl = infl_vec[i]
            used = s.cache_len + infl
            cap = min(cap, max(1, self.ecfg.max_context - 2 - used))
            budget = max(budget, s.req.max_new_tokens - s.n_decoded - infl)
        for take, max_new in (extra or ()):
            cap = min(cap, max(1, self.ecfg.max_context - 2 - take))
            budget = max(budget, max_new - 1)  # first token sampled in-fn
        cap = min(cap, budget)
        if self._sched is not None:
            # priority-weighted burst sizing (ISSUE 11, S2): when prompt
            # work of a strictly higher class waits behind this burst,
            # the scheduler's weights shrink it so admission comes back
            # around sooner. preempt=0 -> _sched is None -> bit-for-bit
            # today's sizing; so is any single-class workload.
            pend = [0] * len(PRIORITY_CLASSES)
            dec_rank = None
            for s in self.slots:
                if s is None:
                    continue
                if s.phase == "prefill" and s.pending:
                    pend[s.prio] += 1
                elif s.phase == "decode":
                    dec_rank = (s.prio if dec_rank is None
                                else min(dec_rank, s.prio))
            cap = self._sched.burst_share(dec_rank, pend, cap)
        k = 1
        while k * 2 <= cap:
            k *= 2
        return k

    def _ensure_draft_cache(self):
        """Lazily materialize the draft-model KV cache (model drafter
        only — the n-gram drafter has no draft state). On paged engines
        it lives in the PAGED pool riding the MAIN page table (ISSUE
        13): draft rows sit at the same page ids as the target's, so
        prefix sharing, COW cloning and offload/restore extend to spec
        slots with no second allocator."""
        if self.dck is not None or self.draft_cfg is None:
            return
        self.dck, self.dcv = llama.init_cache(
            self.draft_cfg, self.ecfg.num_slots, self.ecfg.max_context,
            self.ecfg.cache_dtype,
            **({"page_size": self._pool.page_size,
                "num_pages": self._pool_pages} if self._paged else {}))
        if self._paged:
            # the fresh draft cache carries an empty page table; dirty
            # the allocator so the next commit stamps live state into it
            self._pool.dirty = True

    def _spec_tick_body(self, params, tokens, ck, cv, lengths, ring,
                        ring_pos, bias, keys, slot_params, active, mu,
                        ov_pack, spec_mask, dparams=None, dck=None,
                        dcv=None, *, n_rounds: int,
                        flags: tuple = (True, True, True)):
        """The FUSED spec tick (ISSUE 13): n_rounds speculative rounds in
        ONE dispatch, where spec-masked slots take a D-token
        draft-propose + target-verify round and every other active slot
        takes a plain decode+sample step. The plain rows run the exact
        _make_scan_step ops (engine_decode + sampling.sample, spec rows
        masked out of the KV write and the state folds) so their stream
        stays bit-identical to a plain burst; spec rows verify through
        the same continued-prefill forward spec_round uses, with plain
        rows parked at the OOB row so the scatter drops them. Replaces
        the r3 whole-engine spec/burst alternation (_spec_turn) — mixed
        traffic no longer starves greedy slots of speculation, and spec
        ticks ride the same pipelined device chain as plain bursts.

        Spec rows accept greedily (accept_greedy, byte-identical to
        plain greedy) when the slot is greedy, and via rejection
        sampling against the filtered verify distribution
        (accept_sampled + sampling.verify_dist, ISSUE 18 —
        distribution-identical to plain sampling) when temperature > 0;
        both modes share ONE compiled body so the precompile ladder and
        the COMPILES_AFTER_WARMUP=0 gate are untouched.

        Pack layout [2*R*W + R + 1, S] f32: ids (R*W rows, round-major),
        logprobs (R*W), per-round emit counts (R), mu — where W =
        n_draft + 1 tokens per spec round (accepted prefix + bonus) and
        plain rows emit exactly 1 at position 0 of their round."""
        from localai_tpu.engine import speculative

        sp = sampling.unpack_slot_params(slot_params)
        tokens, lengths, ring, ring_pos, mu, pos_offset = \
            self._compose_overrides(tokens, lengths, ring, ring_pos, mu,
                                    ov_pack)
        D = self.ecfg.n_draft
        W = D + 1
        S = self.ecfg.num_slots
        C = kvcache.shape(ck)[2]
        model_mode = dck is not None
        spec_active = active & spec_mask
        plain_active = active & ~spec_mask
        slot_ids = jnp.arange(S, dtype=jnp.int32)

        def round_step(carry, _):
            (tokens, ck, cv, dck, dcv, lengths, ring, ring_pos, keys,
             mu) = carry
            if model_mode:
                drafts, dck, dcv = speculative.draft_propose(
                    dparams, self.draft_cfg, tokens, lengths, dck, dcv,
                    spec_active, D)
            else:
                drafts = speculative.ngram_propose(
                    tokens, ring, ring_pos, D, self.ecfg.spec_ngram)
            # plain decode step for the non-spec rows (bit-identical ops
            # to _make_scan_step; spec rows masked out of the KV write)
            logits, ck, cv = self.family.engine_decode(
                params, self.cfg, tokens, lengths, plain_active, ck, cv,
                pos_offset=pos_offset)
            ids0, lps0, new_keys, new_mu = sampling.sample(
                logits, sp, ring, ring_pos, bias, keys, mu,
                use_penalties=flags[0], use_typical=flags[1],
                use_mirostat=flags[2])
            keys = jnp.where(plain_active[:, None], new_keys, keys)
            mu = jnp.where(plain_active, new_mu, mu)
            # verify forward for the spec rows: current token + D
            # proposals scored in one continued prefill; plain rows park
            # at the OOB start so their writes drop (their single KV
            # write stays the decode step's above)
            tin = jnp.concatenate([tokens[:, None], drafts], axis=1)
            seq = jnp.full((S,), W, jnp.int32)
            start = jnp.where(spec_active, lengths, C)
            all_logits, ck, cv = self.family.prefill(
                params, self.cfg, tin, seq, ck, cv, slot_ids, start,
                continued=True, return_all_logits=True)
            # filtered verify distribution via the sampler's own code
            # path (sampling.filter_window under verify_dist): idx[:,:,0]
            # is approx_max_k's retained global argmax with the same
            # tie-breaks as sampling.sample's greedy path, so the greedy
            # spec stream matches plain greedy bit-for-bit — and the
            # window probs ARE the law plain sampling draws from, so
            # rejection acceptance against them is distribution-lossless
            vidx, vprobs = sampling.verify_dist(all_logits, sp,
                                                use_typical=flags[1])
            greedy = vidx[:, :, 0]
            out_spec, n_spec, _k = speculative.accept_greedy(
                drafts, greedy, spec_active)
            logp = jax.nn.log_softmax(all_logits, axis=-1)
            lp_spec = jnp.take_along_axis(
                logp, out_spec[:, :, None], axis=2)[:, :, 0]
            # ISSUE 18: sampled spec rows accept via rejection sampling.
            # Scatter the window distribution to vocab for acceptance and
            # residual resampling (n-gram/greedy-draft proposals are
            # deterministic, so draft_probs=None one-hot degeneration)
            samp_active = spec_active & ~jnp.asarray(sp["greedy"])
            V = all_logits.shape[-1]
            rows = jnp.arange(S * W, dtype=jnp.int32)[:, None]
            tgt = jnp.zeros((S * W, V), jnp.float32).at[
                rows, vidx.reshape(S * W, -1)].set(
                vprobs.reshape(S * W, -1)).reshape(S, W, V)
            out_ss, n_ss, _ks, keys_ss = speculative.accept_sampled(
                drafts, tgt, None, keys, samp_active)
            lp_ss = jnp.log(jnp.clip(jnp.take_along_axis(
                tgt, out_ss[:, :, None], axis=2)[:, :, 0], 1e-20))
            out_spec = jnp.where(samp_active[:, None], out_ss, out_spec)
            n_spec = jnp.where(samp_active, n_ss, n_spec)
            lp_spec = jnp.where(samp_active[:, None], lp_ss, lp_spec)
            keys = jnp.where(samp_active[:, None], keys_ss, keys)
            pad = jnp.zeros((S, D), jnp.int32)
            out = jnp.where(spec_mask[:, None], out_spec,
                            jnp.concatenate([ids0[:, None], pad], axis=1))
            lps = jnp.where(spec_mask[:, None], lp_spec,
                            jnp.concatenate(
                                [lps0[:, None], pad.astype(jnp.float32)],
                                axis=1))
            n_out = jnp.where(spec_active, n_spec,
                              plain_active.astype(jnp.int32))
            for j in range(W):   # W is static: unrolled ring pushes
                ring, ring_pos = sampling.update_ring(
                    ring, ring_pos, out[:, j], active & (j < n_out))
            lengths = lengths + n_out
            last = jnp.take_along_axis(
                out, jnp.maximum(n_out - 1, 0)[:, None], axis=1)[:, 0]
            tokens = jnp.where(active, last, tokens)
            return ((tokens, ck, cv, dck, dcv, lengths, ring, ring_pos,
                     keys, mu), (out.T, lps.T, n_out))

        carry = (tokens, ck, cv, dck, dcv, lengths, ring, ring_pos, keys,
                 mu)
        carry, (ids_all, lps_all, n_all) = jax.lax.scan(
            round_step, carry, None, length=n_rounds)
        (tokens, ck, cv, dck, dcv, lengths, ring, ring_pos, keys,
         mu) = carry
        R = n_rounds
        pack = jnp.concatenate(
            [ids_all.reshape(R * W, S).astype(jnp.float32),
             lps_all.reshape(R * W, S),
             n_all.astype(jnp.float32), mu[None, :]], axis=0)
        chain = (tokens, lengths, ring, ring_pos, mu)
        if model_mode:
            return pack, ck, cv, keys, chain, dck, dcv
        return pack, ck, cv, keys, chain

    def _get_spec_tick_fn(self, n_rounds: int,
                          flags: tuple = (True, True, True)):
        key = ("spec_tick", n_rounds, flags)
        fn = self._burst_fns.get(key)
        if fn is None:
            self._cobs.note_program("spec_tick", (n_rounds, flags))
            donate = ((2, 3, 8, 15, 16) if self._spec_mode == "model"
                      else (2, 3, 8))
            fn = jax.jit(
                lambda *a: self._spec_tick_body(*a, n_rounds=n_rounds,
                                                flags=flags),
                donate_argnums=donate)
            self._burst_fns[key] = fn
        return fn

    def _plan_spec(self, included: list, infl: list):
        """Spec plan for this tick: (n_rounds, spec_mask) or None for a
        plain burst. A slot joins spec rounds iff it admitted spec_ok
        (ungrammared, penalty/mirostat-free — greedy AND sampled since
        ISSUE 18) and has W = n_draft + 1 rows of headroom
        past the steps already in flight; everyone else in ``included``
        rides the same tick as a plain-decode row. Round count follows
        _pick_burst's sizing discipline with spec slots charged W rows
        and W tokens of budget per round, floored to a power of two so
        only the precompiled ladder ever runs."""
        if self._spec_mode == "off" or self.ecfg.ga_n > 1:
            # spec rounds advance positions row=position; they are not
            # self-extend-aware — mutually exclusive features
            return None
        if self._spec_mode == "model" and self.dck is None:
            return None
        W = self.ecfg.n_draft + 1
        S = self.ecfg.num_slots
        C = self.ecfg.max_context
        mask = np.zeros((S,), np.bool_)
        for i in included:
            s = self.slots[i]
            # windowed slots decode singly: spec verify rows assume
            # row == position, which the snap-back rebase breaks
            if s.win_off > 0:
                continue
            if s.spec_ok and C - 2 - (s.cache_len + infl[i]) >= W:
                mask[i] = True
        if not mask.any():
            return None
        cap = max(1, self.ecfg.decode_burst // W)
        budget = 1
        for i in included:
            s = self.slots[i]
            used = s.cache_len + infl[i]
            rem = s.req.max_new_tokens - s.n_decoded - infl[i]
            if mask[i]:
                cap = min(cap, max(1, (C - 2 - used) // W))
                budget = max(budget, (rem + W - 1) // W)
            else:
                cap = min(cap, max(1, C - 2 - used))
                budget = max(budget, rem)
        cap = min(cap, budget)
        if self._sched is not None:
            # priority-weighted sizing, mirroring _pick_burst (ISSUE 11)
            pend = [0] * len(PRIORITY_CLASSES)
            dec_rank = None
            for s in self.slots:
                if s is None:
                    continue
                if s.phase == "prefill" and s.pending:
                    pend[s.prio] += 1
                elif s.phase == "decode":
                    dec_rank = (s.prio if dec_rank is None
                                else min(dec_rank, s.prio))
            cap = self._sched.burst_share(dec_rank, pend, cap)
        k = 1
        while k * 2 <= cap:
            k *= 2
        return k, mask

    def _dispatch_decode(self) -> bool:
        """Dispatch the next decode burst — or, when spec-eligible slots
        are decoding, a FUSED SPEC TICK (ISSUE 13: draft-propose +
        target-verify rounds for the eligible slots, plain decode steps
        for everyone else, ONE chained dispatch — no whole-engine
        spec/burst alternation) — if the pipeline has room and some
        decoding slot still has budget beyond the steps already in
        flight. Never blocks: burst-to-burst state
        (tokens/lengths/ring/mu) chains device-side, and host events are
        composed in as per-slot overrides (see _decode_burst_body)."""
        if self._n_inflight_bursts() >= self.ecfg.pipeline_depth:
            return False
        decoding = [i for i, s in enumerate(self.slots)
                    if s is not None and s.phase == "decode"]
        if not decoding:
            return False
        active = self.active_dev.copy()
        included = []
        infl = self._inflight_vec()   # one FIFO pass for all slots (ISSUE 9)
        for i in decoding:
            s = self.slots[i]
            if s.req.max_new_tokens - s.n_decoded - infl[i] <= 0:
                # in-flight steps already cover this slot's budget: mask it
                # out so it doesn't ride the new burst as garbage compute
                # (with depth-2 pipelining that waste measured ~30% of all
                # dispatched slot-steps on the wave-shaped bench). Release
                # happens when the in-flight results are emitted; grammar
                # rollbacks recover budget and simply re-include the slot
                # on a later dispatch.
                active[i] = False
                continue
            included.append(i)
        if not included:
            return False
        if self._win_pages:
            # snap-back BEFORE planning/ensure: demote cold middle pages
            # so the upcoming steps land inside the bounded working set
            # (the rebase rides _win_delta into the chain, so no
            # override — and no host sync — is forced)
            upcoming = self.ecfg.decode_burst * (self.ecfg.n_draft + 1) + 2
            for i in included:
                self._advance_window(i, infl[i] + upcoming)
        plan = self._plan_spec(included, infl)
        W = self.ecfg.n_draft + 1
        if plan is not None:
            n_steps, spec_mask = plan
        else:
            n_steps, spec_mask = self._pick_burst(infl_vec=infl), None
        if self._paged:
            C = self.ecfg.max_context
            for i in included:
                # spec-masked slots write up to W rows per round (the
                # rejected tail is overwritten by the next round)
                need = (n_steps * W if spec_mask is not None
                        and spec_mask[i] else n_steps)
                self._ensure_pages(i, min(C, int(self.lengths[i])
                                          + infl[i] + need + 2))
            self._commit_ptab()
        f = sampling.feature_flags(self.slot_params, self.active_dev)
        flags = (f["use_penalties"], f["use_typical"], f["use_mirostat"])
        if any(flags) and flags != (True, True, True):
            # only the two precompiled variants exist; mixed feature sets
            # use the full sampler rather than compiling mid-request
            flags = (True, True, True)
        fn = (self._get_spec_tick_fn(n_steps, flags) if plan is not None
              else self._get_burst_fn(n_steps, flags))
        t_d = time.monotonic()
        S = self.ecfg.num_slots
        ov_mask = np.zeros((S,), np.bool_)
        if self._chain is None:
            # cold chain: feed everything from the host mirrors
            chain = (self.cur_tokens.copy(), self.lengths.copy(),
                     self.ring.copy(), self.ring_pos.copy(), self.mu.copy())
        else:
            chain = self._chain
            for i in self._override:
                ov_mask[i] = True
        cold = self._chain is None
        self._override.clear()
        # snapshot the PARTICIPATING SLOT OBJECTS: a slot index may be
        # released and re-admitted while this burst is in flight, and the
        # new occupant must never receive the stale burst's tokens
        burst_slots = [(i, self.slots[i]) for i in included]
        spp = sampling.pack_slot_params(self.slot_params)
        ovp = self._pack_ov(ov_mask)
        if self._bus is not None:
            self._bus.send("burst", k=n_steps, flags=flags,
                           chain=chain if cold else None,
                           spp=spp, active=active, ovp=ovp)
        with self._annot("decode_burst"):
            if plan is None:
                pack, self.ck, self.cv, self.rng_keys, self._chain = fn(
                    self.params, chain[0], self.ck, self.cv, chain[1],
                    chain[2], chain[3], self.bias, self.rng_keys,
                    spp, active, chain[4], ovp,
                )
            elif self._spec_mode == "model":
                (pack, self.ck, self.cv, self.rng_keys, self._chain,
                 self.dck, self.dcv) = fn(
                    self.params, chain[0], self.ck, self.cv, chain[1],
                    chain[2], chain[3], self.bias, self.rng_keys,
                    spp, active, chain[4], ovp, spec_mask,
                    self.draft_params, self.dck, self.dcv,
                )
            else:
                pack, self.ck, self.cv, self.rng_keys, self._chain = fn(
                    self.params, chain[0], self.ck, self.cv, chain[1],
                    chain[2], chain[3], self.bias, self.rng_keys,
                    spp, active, chain[4], ovp, spec_mask,
                )
        self._tmark("dispatch", t_d)
        if self.tracer.enabled:
            self.tracer.record(
                "decode_dispatch", "engine", t_d, time.monotonic(),
                args={"steps": n_steps, "slots": len(included),
                      **({"spec_slots": int(spec_mask.sum()),
                          "spec_width": W} if plan is not None else {})})
        if self._trace:
            s = self._tstats.setdefault("burst_steps", [0.0, 0])
            s[0] += n_steps
            s[1] += 1
            # occupancy: the compiled step computes ALL slots, so every
            # inactive slot wastes 1/S of the burst — this stat is the
            # device-waste diagnostic (avg = slots riding per burst)
            occ = self._tstats.setdefault("active_slots", [0.0, 0])
            occ[0] += len(included)
            occ[1] += 1
        b = _Burst(n_steps, burst_slots, pack, t_dispatch=t_d)
        if plan is not None:
            b.spec_mask = spec_mask
            b.spec_width = W
            # dispatch-time snapshot for per-mode fold attribution: the
            # slot may be re-admitted with different params in flight
            b.spec_greedy = self.slot_params["greedy"].copy()
            st = self._spec_stats
            st["dispatches"] += 1
            if any(not spec_mask[i] for i in included):
                st["mixed_dispatches"] += 1
        self._fifo.append(b)
        self._sync_q.put(b)
        return True

    def _live(self, i, snap):
        return self.slots[i] is snap and snap.phase == "decode"

    def _fold_burst(self, b: "_Burst"):
        """Sync a burst's packed results (ONE device->host transfer) and
        fold the device-side state evolution into the host mirrors. Cheap
        (~1ms past the device sync) and idempotent; emission is separate
        so it can overlap the NEXT dispatch."""
        if b.folded:
            return
        t0 = time.monotonic()
        if not b.ready.is_set():
            self._wait_ready(b, b.t_dispatch)   # worker-side sync in flight
        if b.err is not None:
            raise b.err
        packed = b.pack_np                  # [2K+1(+2), S] f32
        self._tmark("burst_wait", t0)
        K = b.n_steps
        if b.spec_width:
            # spec tick pack: ids/lps are [R*W, S] round-major, then the
            # [R, S] per-round emit counts, then mu
            KW = K * b.spec_width
            b.ids_np = packed[:KW].astype(np.int32)
            b.lps_np = packed[KW:2 * KW]
            b.n_out_np = packed[2 * KW:2 * KW + K].astype(np.int32)
            mu_np = packed[2 * KW + K]
        else:
            b.ids_np = packed[:K].astype(np.int32)
            b.lps_np = packed[K:2 * K]
            mu_np = packed[2 * K]
        if b.group:
            if b.head is not None:
                # early-emit split: the first tokens synced with the
                # HEAD (ready before this burst — same worker, dispatch
                # order); rebuild the slot-indexed rows the ring fold
                # below reads. The burst pack itself is a PLAIN pack
                # (no first-token rows).
                h = b.head
                if not h.ready.is_set():
                    self._wait_ready(h, h.t0)
                if h.err is not None:
                    raise h.err
                S = self.ecfg.num_slots
                b.first_ids = np.zeros((S,), np.int32)
                b.first_lps = np.zeros((S,), np.float32)
                for gi, (i, _snap) in enumerate(b.group):
                    b.first_ids[i] = h.ids_np[gi]
                    b.first_lps[i] = h.lps_np[gi]
            else:
                b.first_ids = packed[2 * K + 1].astype(np.int32)
                b.first_lps = packed[2 * K + 2]
        live_idx = [i for i, snap in b.slots
                    if self._live(i, snap) and i not in b.skip_slots]
        for i in live_idx:
            self.mu[i] = mu_np[i]
        if b.spec_width:
            # fused spec tick: per-slot VARIABLE advance — each round
            # emitted n_out tokens (spec rows: accepted prefix + bonus;
            # plain rows: exactly 1 at position 0); the mirrors must
            # replay the device's ring/length evolution token-by-token
            Wd = b.spec_width
            st = self._spec_stats
            for i in live_idx:
                ns = b.n_out_np[:, i]
                tot = int(ns.sum())
                if tot <= 0:
                    continue
                self.cur_tokens[i] = b.ids_np[(K - 1) * Wd
                                              + int(ns[K - 1]) - 1, i]
                self.lengths[i] += tot
                rp = int(self.ring_pos[i])
                for r in range(K):
                    for j in range(int(ns[r])):
                        self.ring[i, rp % sampling.RING_N] = \
                            b.ids_np[r * Wd + j, i]
                        rp += 1
                self.ring_pos[i] = rp
                if b.spec_mask[i]:
                    st["rounds"] += K
                    st["proposed"] += K * (Wd - 1)
                    st["accepted"] += tot - K
                    st["tokens"] += tot
                    # ISSUE 18 per-mode split (greedy accept_greedy vs
                    # sampled rejection acceptance), attributed from the
                    # dispatch-time greedy snapshot
                    mode = ("greedy" if b.spec_greedy is None
                            or b.spec_greedy[i] else "sampled")
                    bm = st["by_mode"][mode]
                    bm["rounds"] += K
                    bm["proposed"] += K * (Wd - 1)
                    bm["accepted"] += tot - K
                    bm["tokens"] += tot
            b.folded = True
            return
        for i in live_idx:
            self.cur_tokens[i] = b.ids_np[-1, i]
            self.lengths[i] += b.n_steps
        # fused groups: the in-fn first token precedes the burst ids in the
        # ring (mirror must match the device evolution)
        for i, snap in b.group:
            if self._live(i, snap) and i not in b.skip_slots:
                self.ring[i, self.ring_pos[i] % sampling.RING_N] = b.first_ids[i]
                self.ring_pos[i] += 1
        sampling.host_update_ring(self.ring, self.ring_pos, b.ids_np, live_idx)
        b.folded = True

    def _process_burst(self, b: "_Burst"):
        """Fold (if not already) then emit a burst's tokens (emission may
        release slots or trigger context shifts — both mark the device
        chain dirty). Per-slot events are COALESCED into one queue put per
        burst (see StreamEvent.token_ids)."""
        if b.head is not None and not b.head.processed:
            # the pipeline block-synced this burst past its own
            # not-yet-processed head (_drain_fifo's burst walk passes
            # non-burst items): emit the head's first tokens NOW, in
            # stream order, before the burst's. The burst is already out
            # of the FIFO, but rollback / shift / self-extend poisoning
            # inside the head's emission walks self._fifo — keep the
            # burst discoverable for the duration.
            if b.head in self._fifo:
                self._fifo.remove(b.head)
            self._fifo.appendleft(b)
            try:
                self._process_prefill(b.head)
            finally:
                self._fifo.remove(b)
        self._fold_burst(b)
        if not b.group and b.t_dispatch:
            dt = (time.monotonic() - b.t_dispatch) * 1e3
            self._burst_ms_ema += 0.2 * (dt - self._burst_ms_ema)
        t0 = time.monotonic()
        t_proc = t0
        tr = self.tracer
        if b.t_dispatch:
            t_rdy = b.t_ready or t_proc
            self._hobserve("decode_burst_seconds",
                           max(0.0, t_rdy - b.t_dispatch))
            if self._t_last_burst:
                # burst-to-burst cadence / steps: the stream-visible ITL.
                # Spec ticks divide by the MEAN tokens actually emitted
                # per live slot (accepted + bonus), so acceptance shows
                # up as ITL improvement, not as phantom long bursts
                steps = b.n_steps
                if b.spec_width and b.n_out_np is not None:
                    per_slot = b.n_out_np.sum(axis=0)
                    live = per_slot[per_slot > 0]
                    if live.size:
                        steps = float(live.mean())
                self._hobserve("itl_seconds",
                               max(0.0, t_proc - self._t_last_burst)
                               / max(1.0, steps))
            self._t_last_burst = t_proc
            if tr.enabled:
                tr.record("decode_burst_device", "engine",
                          b.t_dispatch, t_rdy,
                          args={"steps": b.n_steps, "slots": len(b.slots),
                                "fused": bool(b.group),
                                "spec": bool(b.spec_width)})
                if b.spec_width:
                    # spec_round span, split draft-vs-verify so decomp_ms
                    # attributes speculation honestly. The split is
                    # ANALYTIC (the fused program has no host-visible
                    # internal boundary): the model drafter runs D of the
                    # round's D+1 sequential forwards, the n-gram match
                    # is a fixed small slice of the round
                    nsp = b.n_out_np
                    spec_idx = [i for i, _s in b.slots if b.spec_mask[i]]
                    tot = int(sum(int(nsp[:, i].sum()) for i in spec_idx))
                    share = ((b.spec_width - 1) / b.spec_width
                             if self._spec_mode == "model" else 0.1)
                    mid = b.t_dispatch + (t_rdy - b.t_dispatch) * share
                    tr.record("spec_round", "engine", b.t_dispatch, t_rdy,
                              args={"mode": self._spec_mode,
                                    "rounds": b.n_steps,
                                    "spec_slots": len(spec_idx),
                                    "proposed": b.n_steps
                                    * (b.spec_width - 1) * len(spec_idx),
                                    "accepted": max(
                                        0, tot - b.n_steps
                                        * len(spec_idx))})
                    tr.record("spec_draft", "engine", b.t_dispatch, mid,
                              args={"analytic": True})
                    tr.record("spec_verify", "engine", mid, t_rdy,
                              args={"analytic": True})
                tr.record("finish_detect", "engine", t_rdy, t_proc)
                for i, snap in b.slots:
                    if self._live(i, snap) and i not in b.skip_slots:
                        tr.record("decode", f"slot{i}", b.t_dispatch, t_rdy,
                                  rid=snap.req.request_id,
                                  args={"steps": b.n_steps})
        # emitter mode hands tokens over as one immutable batch instead
        # of coalescing events in-loop (ISSUE 9)
        self._sink_buf = {} if self._emitter is None else None
        rolled: set = set()   # grammar slots rolled back mid-burst
        try:
            # fused-admission slots: emit the in-fn sampled first token
            # before their burst tokens (this is their TTFT event)
            t1 = time.monotonic()
            for i, snap in b.group:
                if not self._live(i, snap) or i in b.skip_slots:
                    continue
                if b.head is not None:
                    # early-emit split: the head already emitted this
                    # slot's first token, stamped its TTFT, and set
                    # committed/cache_len (which the emission advanced —
                    # resetting them here would rewind the slot)
                    continue
                snap.cache_len = snap.written
                snap.committed = snap.written
                # charge only the prefill's share of the fused dispatch:
                # subtract the typical plain-burst latency (EMA) so the
                # timing stays comparable with the non-fused path
                snap.t_prefill_ms += max(
                    0.0, (t1 - b.t_dispatch) * 1e3 - self._burst_ms_ema)
                if snap.t_first_token == 0.0:
                    snap.t_first_token = t1
                    if snap.req.t_submit:
                        self._hobserve("ttft_seconds",
                                       t1 - snap.req.t_submit,
                                       rid=snap.req.request_id)
                    if tr.enabled:
                        tr.record("prefill", f"slot{i}", b.t_dispatch, t1,
                                  rid=snap.req.request_id,
                                  args={"prompt_tokens": snap.prompt_len,
                                        "fused": True})
                if not self._emit(i, int(b.first_ids[i]),
                                  float(b.first_lps[i])):
                    rolled.add(i)
            for i, _snap in b.group:
                self._process_fork_waiters(i)
            if b.spec_width:
                # fused spec tick: round-major emission, each slot emits
                # its round's n_out tokens (plain rows: 1 at position 0)
                Wd = b.spec_width
                for r in range(b.n_steps):
                    for i, snap in b.slots:
                        if i in rolled or i in b.skip_slots \
                                or not self._live(i, snap):
                            continue
                        for j in range(int(b.n_out_np[r, i])):
                            if i in rolled or not self._live(i, snap):
                                break
                            snap.committed = min(snap.committed + 1,
                                                 snap.cache_len)
                            if not self._emit(
                                    i, int(b.ids_np[r * Wd + j, i]),
                                    float(b.lps_np[r * Wd + j, i])):
                                rolled.add(i)
                                break
            else:
                for j in range(b.n_steps):
                    for i, snap in b.slots:
                        if i in rolled or i in b.skip_slots \
                                or not self._live(i, snap):
                            continue  # finished/shifted/replaced/rolled-back
                        # the step just wrote this slot's previous
                        # token's KV row
                        snap.committed = min(snap.committed + 1,
                                             snap.cache_len)
                        if not self._emit(i, int(b.ids_np[j, i]),
                                          float(b.lps_np[j, i])):
                            rolled.add(i)
        finally:
            buf, self._sink_buf = self._sink_buf, None
            self._tmark("emit_loop", t0)
            self._flush_grammar_bias()
            self._flush_em_batch()
            t0 = time.monotonic()
            if tr.enabled:
                # emit = detok + stop-scan walltime; flush is separate.
                # With the emitter on this shrinks to id-level control +
                # one queue put — the text work records under emit_bg on
                # the emitter thread instead.
                tr.record("emit", "engine", t_proc, t0,
                          args={"steps": b.n_steps})
            if buf:
                for (_slot, out), evs in buf.items():
                    out.put(evs[0] if len(evs) == 1 else _merge_events(evs))
            self._tmark("emit_flush", t0)
            if tr.enabled:
                tr.record("stream_flush", "engine", t0, time.monotonic(),
                          args={"streams": len(buf) if buf else 0})

    def _emit_token(self, slot: int, token_id: int, logprob: float) -> bool:
        """Emit one token for a slot. Returns False when the token was a
        grammar-invalid speculative sample and the slot rolled back (the
        slot's remaining tokens in the current burst must be skipped)."""
        s = self.slots[slot]
        s.generated.append(token_id)
        s.n_decoded += 1
        self._total_tokens += 1
        finish = None
        shifted = False

        if token_id in self.eos_ids and not (s.req.ignore_eos and s.grammar is None):
            if s.grammar is not None and s.cur_penalty is not None \
                    and s.cur_penalty[token_id] != 0.0:
                # speculative EOS sampled under a STALE mask while the
                # grammar cannot terminate yet — discard and resume
                return self._rollback_grammar(slot, s)
            finish = "stop"
            delta = s.held_text + s.detok.flush()
        elif s.grammar is not None and not self._advance_grammar(slot, s, token_id):
            # speculative token fell outside the grammar (stale mask mid-
            # burst) — roll back instead of emitting invalid output
            return self._rollback_grammar(slot, s)
        elif s.n_decoded >= s.req.max_new_tokens:
            finish = "length"
            delta = s.held_text + s.detok.push(token_id) + s.detok.flush()
        elif s.win_off + s.cache_len + 1 >= self.ecfg.max_context - 1:
            if self.ecfg.context_shift:
                delta = s.held_text + s.detok.push(token_id)
                s.held_text = ""
                # stop sequences still apply at the shift-trigger token —
                # a completing stop must finish, not leak past the shift
                if s.req.stop_sequences:
                    cut = self._check_stops(s, delta)
                    if cut is not None:
                        delta, finish = cut, "stop"
                    elif delta:
                        delta, s.held_text = self._holdback(s, delta)
                if finish is None:
                    self._context_shift(slot, s, token_id)
                    shifted = True
            else:
                finish = "length"
                delta = s.held_text + s.detok.push(token_id) + s.detok.flush()
        else:
            delta = s.held_text + s.detok.push(token_id)
            s.held_text = ""
            # stop-sequence handling with partial-match holdback
            if s.req.stop_sequences:
                cut = self._check_stops(s, delta)
                if cut is not None:
                    delta, finish = cut, "stop"
                elif delta:
                    delta, s.held_text = self._holdback(s, delta)

        extended = False
        if finish is None and not shifted:
            # this token's KV is written by the next decode step
            self._cache_tokens[slot].append(token_id)
            s.cache_len += 1
            if self.ecfg.ga_n > 1 and s.mm_pos is None:
                extended = self._maybe_self_extend(slot, s)

        ev = StreamEvent(
            token_id=token_id, text=delta, logprob=logprob,
            finish_reason=finish,
            prompt_tokens=s.prompt_len, completion_tokens=s.n_decoded,
        )
        buf = self._sink_buf
        if finish:
            dt = time.monotonic() - s.t_first_token
            # TTFT decomposition (VERDICT r4 #9): how long the request sat
            # in the admission queue vs the admit->first-token span (which
            # itself splits into prefill dispatch time, t_prefill_ms, and
            # waiting on other slots' work)
            queue_wait_ms = max(0.0, (s.t_start - s.req.t_submit) * 1e3) \
                if s.req.t_submit else 0.0
            admit_to_first_ms = max(0.0, (s.t_first_token - s.t_start) * 1e3) \
                if s.t_first_token else 0.0
            ev.timings = {
                "prefill_ms": s.t_prefill_ms,
                "queue_wait_ms": queue_wait_ms,
                "admit_to_first_ms": admit_to_first_ms,
                "reused_prompt_tokens": s.reused,
                "decode_tokens_per_s": (s.n_decoded - 1) / dt if dt > 0 and s.n_decoded > 1 else 0.0,
            }
            with self._decomp_lock:
                self._ttft_decomp.append(
                    (queue_wait_ms, admit_to_first_ms, s.t_prefill_ms))
            t_done = time.monotonic()
            if self.tracer.enabled and s.req.t_submit:
                self.tracer.record("request", f"slot{slot}",
                                   s.req.t_submit, t_done,
                                   rid=s.req.request_id,
                                   args={"completion_tokens": s.n_decoded,
                                         "finish": finish})
            if self._slow_ms > 0:
                ttft_ms = queue_wait_ms + admit_to_first_ms
                e2e_ms = (t_done - s.req.t_submit) * 1e3 \
                    if s.req.t_submit else 0.0
                if ttft_ms > self._slow_ms or e2e_ms > self._slow_ms:
                    import json as _json
                    import logging as _logging

                    _logging.getLogger(__name__).warning(
                        "slow request %s: %s", s.req.request_id,
                        _json.dumps({
                            "threshold_ms": self._slow_ms,
                            "e2e_ms": round(e2e_ms, 1),
                            "ttft_ms": round(ttft_ms, 1),
                            "completion_tokens": s.n_decoded,
                            "spans": {k: (round(v, 1)
                                          if isinstance(v, float) else v)
                                      for k, v in ev.timings.items()},
                        }, sort_keys=True))
            # goodput (ISSUE 8): ONLY clean finishes count — sheds,
            # timeouts and stall aborts never reach this branch
            self._goodput.add(s.n_decoded)
            self._slo_finish(s, s.n_decoded, t_done,
                            queue_wait_ms + admit_to_first_ms,
                            queue_wait_ms)
            EVENTS.emit("complete", rid=s.req.request_id, finish=finish,
                        completion_tokens=s.n_decoded,
                        e2e_ms=round((t_done - s.req.t_submit) * 1e3, 1)
                        if s.req.t_submit else None)
            self._save_prompt_cache(slot, s)
            self._release_slot(slot)
            if buf is not None:
                evs = buf.pop((slot, s.req.out), None)
                if evs:
                    s.req.out.put(evs[0] if len(evs) == 1 else _merge_events(evs))
            s.req.out.put(ev)
            s.req.out.put(None)
        elif buf is not None:
            buf.setdefault((slot, s.req.out), []).append(ev)
        else:
            s.req.out.put(ev)
        # a self-extend compression invalidates the slot's remaining
        # in-flight tokens (stale positions) — skip them like a rollback,
        # but the token above was valid and HAS been emitted
        return not extended

    # ---------- event-driven emission (ISSUE 9) ----------

    def _emit_token_ev(self, slot: int, token_id: int, logprob: float) -> bool:
        """Event-driven twin of _emit_token: identical id-level control
        flow (EOS, grammar advance/rollback, length, context shift, KV
        bookkeeping), but NO text work — the token joins the per-tick
        batch handed to the emitter worker, which owns detok, stop-scan
        and every ``req.out`` put. Stop sequences are text-level, so in
        this mode they are detected by the EMITTER and fed back via
        ``_apply_emitter_notes``."""
        s = self.slots[slot]
        s.generated.append(token_id)
        s.n_decoded += 1
        self._total_tokens += 1
        finish = None
        shifted = False

        if token_id in self.eos_ids and not (s.req.ignore_eos and s.grammar is None):
            if s.grammar is not None and s.cur_penalty is not None \
                    and s.cur_penalty[token_id] != 0.0:
                # speculative EOS sampled under a STALE mask while the
                # grammar cannot terminate yet — discard and resume
                return self._rollback_grammar(slot, s)
            finish = "stop"
        elif s.grammar is not None and not self._advance_grammar(slot, s, token_id):
            # speculative token fell outside the grammar (stale mask mid-
            # burst) — roll back instead of emitting invalid output
            return self._rollback_grammar(slot, s)
        elif s.n_decoded >= s.req.max_new_tokens:
            finish = "length"
        elif s.win_off + s.cache_len + 1 >= self.ecfg.max_context - 1:
            if self.ecfg.context_shift:
                # the emitter still stop-scans this token; a stop that
                # completes here aborts the shifted slot via the note
                # channel — the re-prefill is wasted work, the emitted
                # OUTPUT is identical to the in-loop path
                self._context_shift(slot, s, token_id)
                shifted = True
            else:
                finish = "length"

        extended = False
        if finish is None and not shifted:
            # this token's KV is written by the next decode step
            self._cache_tokens[slot].append(token_id)
            s.cache_len += 1
            if self.ecfg.ga_n > 1 and s.mm_pos is None:
                extended = self._maybe_self_extend(slot, s)

        e = self._em_batch.get(slot)
        if e is None or e["snap"] is not s:
            e = self._em_batch[slot] = {
                "slot": slot, "snap": s, "tokens": [],
                "finish": None, "timings": None}
        # n_decoded is captured per token: the snapshot keeps mutating
        # while the batch rides the queue
        e["tokens"].append((token_id, logprob, s.n_decoded))
        if finish:
            timings = self._finish_timings_ev(s, s.n_decoded,
                                              time.monotonic())
            e["finish"] = finish
            e["timings"] = timings
            self._finish_accounting_ev(slot, s, finish, s.n_decoded,
                                       timings)
        return not extended

    def _flush_em_batch(self):
        """Hand the tick's accumulated token batch to the emitter as ONE
        queue item — per-slot FIFO order is the queue's FIFO order."""
        if self._em_batch:
            batch, self._em_batch = self._em_batch, {}
            self._emitter.push_batch(list(batch.values()))

    def _finish_timings_ev(self, s: "_Slot", ndec: int, t_done: float) -> dict:
        """Final-event timings for an engine-detected finish (same fields
        _emit_token computes inline; the emitter mirrors this for the
        stops it detects itself)."""
        dt = t_done - s.t_first_token
        queue_wait_ms = max(0.0, (s.t_start - s.req.t_submit) * 1e3) \
            if s.req.t_submit else 0.0
        admit_to_first_ms = max(0.0, (s.t_first_token - s.t_start) * 1e3) \
            if s.t_first_token else 0.0
        return {
            "prefill_ms": s.t_prefill_ms,
            "queue_wait_ms": queue_wait_ms,
            "admit_to_first_ms": admit_to_first_ms,
            "reused_prompt_tokens": s.reused,
            "decode_tokens_per_s":
                (ndec - 1) / dt if dt > 0 and ndec > 1 else 0.0,
        }

    def _finish_accounting_ev(self, slot: int, s: "_Slot", finish: str,
                              ndec: int, timings: dict):
        """Everything _emit_token's finish branch does besides the stream
        puts (those belong to the emitter): TTFT decomposition, request
        span, slow-request log, goodput, completion event, prompt-cache
        save, slot release."""
        with self._decomp_lock:
            self._ttft_decomp.append(
                (timings["queue_wait_ms"], timings["admit_to_first_ms"],
                 s.t_prefill_ms))
        t_done = time.monotonic()
        if self.tracer.enabled and s.req.t_submit:
            self.tracer.record("request", f"slot{slot}",
                               s.req.t_submit, t_done,
                               rid=s.req.request_id,
                               args={"completion_tokens": ndec,
                                     "finish": finish})
        if self._slow_ms > 0:
            ttft_ms = timings["queue_wait_ms"] + timings["admit_to_first_ms"]
            e2e_ms = (t_done - s.req.t_submit) * 1e3 \
                if s.req.t_submit else 0.0
            if ttft_ms > self._slow_ms or e2e_ms > self._slow_ms:
                import json as _json
                import logging as _logging

                _logging.getLogger(__name__).warning(
                    "slow request %s: %s", s.req.request_id,
                    _json.dumps({
                        "threshold_ms": self._slow_ms,
                        "e2e_ms": round(e2e_ms, 1),
                        "ttft_ms": round(ttft_ms, 1),
                        "completion_tokens": ndec,
                        "spans": {k: (round(v, 1)
                                      if isinstance(v, float) else v)
                                  for k, v in timings.items()},
                    }, sort_keys=True))
        # goodput (ISSUE 8): ONLY clean finishes count — sheds, timeouts
        # and stall aborts never reach this branch
        self._goodput.add(ndec)
        self._slo_finish(s, ndec, t_done,
                         timings["queue_wait_ms"]
                         + timings["admit_to_first_ms"],
                         timings["queue_wait_ms"])
        EVENTS.emit("complete", rid=s.req.request_id, finish=finish,
                    completion_tokens=ndec,
                    e2e_ms=round((t_done - s.req.t_submit) * 1e3, 1)
                    if s.req.t_submit else None)
        self._save_prompt_cache(slot, s)
        self._release_slot(slot)

    def _make_emitter(self):
        from localai_tpu.engine.emitter import EmitterWorker

        def note(slot, snap, ndec, timings):
            with self._em_lock:
                self._em_notes.append(("stop", slot, snap, ndec, timings))
            self._wake.set()

        def note_abort(slot, snap):
            with self._em_lock:
                self._em_notes.append(("abort", slot, snap, 0, None))
            self._wake.set()

        return EmitterWorker(tracer=self.tracer, stream_event=StreamEvent,
                             merge_events=_merge_events, note_finish=note,
                             note_abort=note_abort)

    def _apply_emitter_notes(self):
        """Apply emitter-side finishes. ``stop`` notes are detected
        stop-sequence completions: the emitter has already truncated the
        text and closed the stream; the engine side releases the slot,
        pulls a racing context-shift re-prefill back out of the queue,
        and accounts the completion. ``abort`` notes are emitter-side
        item failures (e.g. a detokenizer exception) whose streams the
        emitter already failed — release only, no completion accounting
        (mirrors the in-loop generic handler). Tokens decoded past the
        note are discarded with the slot (same rule as any other
        in-flight invalidation)."""
        if self._emitter is None or not self._em_notes:
            return
        with self._em_lock:
            notes, self._em_notes = self._em_notes, []
        for kind, slot, snap, ndec, timings in notes:
            if self.slots[slot] is not snap:
                continue   # engine finished/aborted the slot first
            # a context shift may have queued this slot for re-prefill
            # right after the note-carrying token; the request is over
            try:
                self._prefill_queue.remove(slot)
            except ValueError:
                pass
            # in-flight bursts must not keep decoding for the dead slot
            for b in self._fifo:
                if isinstance(b, _Burst):
                    b.skip_slots.add(slot)
            if kind == "stop":
                self._finish_accounting_ev(slot, snap, "stop", ndec,
                                           timings)
            else:
                self._release_slot(slot)
            self._process_fork_waiters(slot)

    def _check_emitter_wedge(self):
        """Watchdog coverage for a wedged EMITTER: if the worker has been
        stuck on one item longer than the dispatch stall budget (or died
        with work still queued), take over its queue, fail every affected
        stream directly, and build a fresh worker."""
        em = self._emitter
        if em is None:
            return
        stall_s = self.ecfg.dispatch_stall_ms / 1e3
        if stall_s <= 0:
            return
        t = em.t_item_start
        wedged = (t > 0 and time.monotonic() - t > stall_s) \
            or (not em.alive and em.qsize() > 0)
        if not wedged:
            return
        import logging

        logging.getLogger(__name__).error(
            "emitter wedged (> %d ms on one item); replacing worker",
            self.ecfg.dispatch_stall_ms)
        with self._lc_lock:
            self._lc["stalls"] += 1
        EVENTS.emit("emitter_wedge",
                    dispatch_stall_ms=self.ecfg.dispatch_stall_ms,
                    queued=em.qsize())
        # fail the streams of still-queued items (their tokens/finals
        # are lost with the worker) plus every still-active slot
        victims: dict = {}
        for it in em.takeover():
            if it[0] == "batch":
                for e in it[1]:
                    victims[id(e["snap"])] = e["snap"]
            else:
                victims[id(it[2])] = it[2]
        for i, s in enumerate(self.slots):
            if s is not None:
                victims[id(s)] = s
                self._release_slot(i)
                self._process_fork_waiters(i)
        for s in victims.values():
            s.req.out.put(StreamEvent(
                token_id=-1, text="", logprob=0.0, finish_reason="stop",
                error=(f"emitter wedged > {self.ecfg.dispatch_stall_ms} "
                       f"ms; request aborted"),
                error_kind="stall"))
            s.req.out.put(None)
        self._emitter = self._make_emitter()

    def _context_shift(self, slot: int, s: _Slot, token_id: int):
        """Cache full mid-generation: re-prefill the tail half of the logical
        context into the slot and keep generating (reference KV surgery:
        grpc-server.cpp:1832,1916-1927 — recomputed here; see module doc)."""
        history = self._cache_tokens[slot] + [token_id]
        keep = max(self.ecfg.max_context // 2, 1)
        new_ids = history[-keep:]
        if self._paged:
            # the shift re-prefills from row 0: retain the committed
            # full pages in the prefix cache (a parallel conversation
            # sharing this history can still splice them), then give the
            # table back and re-allocate lazily per chunk — never
            # rewrite a page another slot or the cache reads
            if s.win_off > 0:
                # windowed slot (ISSUE 16): sink-only retention + tail
                # offload — the compact table has no contiguous absolute
                # image for a full insert
                self._retire_window(slot, s)
            elif self._pcache is not None:
                n_ins = s.committed
                if self.ecfg.ga_n > 1:
                    # fully-compressed rows only (see _release_slot)
                    n_ins = min(n_ins, s.ga_blocks * self.ecfg.ga_w)
                self._pcache.insert(self._pool, slot,
                                    self._cache_tokens[slot][:n_ins])
            self._pool.release(slot, 0)
        s.phase = "prefill"
        s.pending = list(new_ids)
        s.written = 0
        s.cache_len = 0
        s.committed = 0
        s.win_off = 0
        s.chain_keys = []       # the token stream is re-based: new chain
        self._cache_tokens[slot] = list(new_ids)
        reused = 0
        if (self._paged and self._pcache is not None and s.mm_pos is None
                and self.ecfg.ga_n <= 1):
            # re-prefill reuse (ISSUE 16 satellite): the kept tail is the
            # SUFFIX of history this slot just retained/offloaded page by
            # page — but chain keys hash from the stream ROOT, so only a
            # kept window whose pages were retained under the SAME root
            # (e.g. a prior shift or a shared conversation prefix) can
            # splice. When it can, the shift's re-prefill shrinks to the
            # un-cached tail via the ordinary admission tiers, COW pages
            # and all, instead of recomputing the whole half-context.
            reused = self._paged_admission(slot, new_ids, 0,
                                           rid=s.req.request_id)
            s.win_off = self._adm_win_off
            s.pending = new_ids[reused + s.win_off:]
            s.written = reused
            s.reused = reused
        self._init_ga(slot, s, len(new_ids))
        if s.win_off:
            self.pos_offset[slot] = s.win_off
        self.active_dev[slot] = False
        self.lengths[slot] = 0
        # restart the penalty ring from the kept window
        self.ring, self.ring_pos = sampling.set_slot_ring(
            self.ring, self.ring_pos, slot, new_ids)
        self._prefill_queue.append(slot)
        # every in-flight burst dispatched before the shift sampled tokens
        # conditioned on the discarded context — drop this slot from them
        # (same invalidation rule as _rollback_grammar / self-extend)
        for b in self._fifo:
            if isinstance(b, _Burst):
                b.skip_slots.add(slot)

    def _check_stops(self, s: _Slot, delta: str) -> Optional[str]:
        """If a stop sequence completes in emitted+delta text, return the
        delta truncated before the stop; else None."""
        total = s.detok.text  # includes delta already
        for stop in s.req.stop_sequences:
            idx = total.find(stop, max(0, len(total) - len(delta) - len(stop)))
            if idx != -1:
                emitted_before = len(total) - len(delta)
                return delta[: max(0, idx - emitted_before)]
        return None

    def _holdback(self, s: _Slot, delta: str) -> tuple[str, str]:
        """Withhold a suffix of delta that is a prefix of any stop sequence."""
        total = s.detok.text
        hold = 0
        for stop in s.req.stop_sequences:
            for k in range(min(len(stop) - 1, len(total)), 0, -1):
                if total.endswith(stop[:k]):
                    hold = max(hold, min(k, len(delta)))
                    break
        if hold:
            return delta[:-hold], delta[-hold:]
        return delta, ""

    def _retire_window(self, slot: int, s: "_Slot") -> int:
        """Shared windowed-slot retirement (ISSUE 16): the table holds
        sinks ++ tail window at COMPACT rows, so only the sink prefix is
        contiguous absolute truth the device tier may retain. The
        committed tail-window pages are offloaded under their ABSOLUTE
        chain keys first (with policy=demote the middle is already host-
        resident, so the whole chain survives for a future windowed
        re-admission), the sinks are retained, and the sink row count is
        returned for the caller's release/trim."""
        pool = self._pool
        pg = pool.page_size
        n_full = min(s.committed // pg, int(pool.owned[slot]))
        sink = min(self._win_sink, n_full)
        if self._hstore is not None and self._pcache is not None:
            base = s.win_off // pg
            keys = self._abs_chain_keys(slot, s, base + n_full)
            victims = []
            for t in range(sink, n_full):
                ap = base + t
                if ap >= len(keys) or self._hstore.contains(keys[ap]):
                    continue
                parent = keys[ap - 1] if ap > 0 else kvcache.PAGE_HASH_ROOT
                victims.append((keys[ap], parent, ap,
                                int(pool.ptab[slot, t])))
            if victims:
                self._dispatch_offload(victims)
        if self._pcache is not None and sink > 0:
            self._pcache.insert(pool, slot,
                                self._cache_tokens[slot][:sink * pg])
        return sink * pg

    def _release_slot(self, slot: int):
        # _cache_tokens is intentionally preserved (trimmed to rows whose KV
        # write actually executed) — the slot's rows stay valid and a future
        # request sharing a prefix reuses them
        s = self.slots[slot]
        if s is not None and s.win_off > 0 and self._paged:
            # snap-back window (ISSUE 16): compact bookkeeping no longer
            # maps 1:1 onto the absolute token history — retire via the
            # windowed path (offload tail, retain sinks only)
            sink_rows = self._retire_window(slot, s)
            self._pool.release(slot, sink_rows)
            self._cache_tokens[slot] = self._cache_tokens[slot][:sink_rows]
            self.slots[slot] = None
            self.active_dev[slot] = False
            self.lengths[slot] = 0
            return
        if s is not None:
            self._cache_tokens[slot] = self._cache_tokens[slot][:s.committed]
        if self._paged:
            # cross-release retention FIRST (while the slot's references
            # still pin the pages): committed full pages enter the
            # token-hash store and survive this slot's next tenant
            if self._pcache is not None:
                n_ins = len(self._cache_tokens[slot])
                if self.ecfg.ga_n > 1 and s is not None:
                    # only FULLY-COMPRESSED rows are stable under
                    # self-extend (later block completions never rotate
                    # them again) — the raw tail must not be retained
                    # under a token key that promises final-form rows
                    n_ins = min(n_ins, s.ga_blocks * self.ecfg.ga_w)
                self._pcache.insert(self._pool, slot,
                                    self._cache_tokens[slot][:n_ins])
                if self.kv_checkpoint and n_ins > 0:
                    # cluster mode (ISSUE 17): the finished chain also
                    # lands in the host tier at release, so a peer host
                    # can serve this prefix via the streaming transport
                    # even when the release-to-next-request gap is
                    # shorter than the watermark checkpoint cadence
                    self._offload_chain(self._cache_tokens[slot][:n_ins])
            # keep the retained prefix's pages in the table too (same
            # reuse story as _cache_tokens — the slot's own next request
            # reuses them for free); everything past returns to the pool
            self._pool.release(slot, len(self._cache_tokens[slot]))
        self.slots[slot] = None
        self.active_dev[slot] = False
        self.lengths[slot] = 0
