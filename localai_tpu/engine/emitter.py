"""Dedicated stream-emission worker (ISSUE 9): detok, stop-sequence
scanning, logprob/event assembly and ``req.out`` queue puts OFF the
engine scheduler loop.

The engine thread keeps all id-level control — EOS, grammar advance and
rollback, length limits, context-shift triggers, KV bookkeeping and slot
release for engine-detected finishes — and hands this worker one
immutable token batch per processed burst / prefill pass
(``push_batch``). The worker owns all text-level state for a request:
the slot snapshot's ``IncrementalDetokenizer`` and ``held_text`` are
single-writer (this thread) while the emitter is on, and the worker is
the ONLY writer of ``req.out`` for slotted requests, so per-slot FIFO
order is simply the queue's FIFO order.

Stop sequences are text-level, so they are DETECTED here — possibly
after the engine has already dispatched further decode steps for the
slot. The worker truncates byte-identically to the in-loop path, closes
the stream, and feeds the finish back via ``note_finish``; the engine
applies the note on its next tick (release the slot, pull a racing
context-shift re-prefill back out of the queue, account goodput).
Tokens decoded past the stop are discarded exactly like tokens decoded
past any other in-flight invalidation (rollback / shift / release:
slots ride out bursts).

Failure paths (cancel, timeout, stall-abort, engine error, shutdown)
route their final events through ``push_final`` on the same queue, so
they land AFTER any still-queued tokens for the stream. A worker wedged
longer than the dispatch stall budget is detected by the engine's
watchdog via the ``t_item_start`` heartbeat and replaced wholesale
(``abandon``); the ``emitter_wedge_ms`` fault drives that path in
chaos tests.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from localai_tpu.services.faults import FAULTS

log = logging.getLogger(__name__)


def check_stops(snap, delta):
    """If a stop sequence completes in emitted+delta text, return the
    delta truncated before the stop; else None. Byte-for-byte mirror of
    the in-loop ``Engine._check_stops``."""
    total = snap.detok.text  # includes delta already
    for stop in snap.req.stop_sequences:
        idx = total.find(stop, max(0, len(total) - len(delta) - len(stop)))
        if idx != -1:
            emitted_before = len(total) - len(delta)
            return delta[: max(0, idx - emitted_before)]
    return None


def holdback(snap, delta):
    """Withhold a suffix of delta that is a prefix of any stop sequence
    (mirror of ``Engine._holdback``)."""
    total = snap.detok.text
    hold = 0
    for stop in snap.req.stop_sequences:
        for k in range(min(len(stop) - 1, len(total)), 0, -1):
            if total.endswith(stop[:k]):
                hold = max(hold, min(k, len(delta)))
                break
    if hold:
        return delta[:-hold], delta[-hold:]
    return delta, ""


class EmitterWorker:
    """One background thread draining immutable token batches.

    Constructor takes the engine's collaborators instead of importing
    them (engine imports this module; the reverse would be a cycle):
    ``stream_event`` is the StreamEvent dataclass, ``merge_events`` the
    per-burst coalescer, ``note_finish(slot, snap, ndec, timings)`` the
    engine callback for emitter-detected stop-sequence finishes, and
    ``note_abort(slot, snap)`` the callback for streams this worker had
    to FAIL (an item raised — e.g. a detokenizer exception): the stream
    is already closed with a structured error here; the engine just
    releases the slot.
    """

    def __init__(self, tracer, stream_event, merge_events, note_finish,
                 note_abort=None, name: str = "engine-emitter"):
        self._tracer = tracer
        self._StreamEvent = stream_event
        self._merge = merge_events
        self._note_finish = note_finish
        self._note_abort = note_abort
        self._q: "queue.Queue" = queue.Queue()
        self._dead = False
        # per-slot text-level state: slot -> [snap, finished]. Bounded by
        # the slot count: a new snap for a slot resets the entry, and a
        # finished flag makes late items for the old snap no-ops (no
        # double-None on cancel-after-stop races).
        self._st: dict = {}
        # watchdog heartbeat: monotonic stamp of the item being processed
        # RIGHT NOW, 0.0 when idle — the engine's stall watchdog reads it.
        self.t_item_start = 0.0
        self.emitted = 0          # tokens emitted (telemetry / tests)
        self._unfinished = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self._thread.start()

    # ---- engine-side API (single producer: the engine thread, plus the
    # ---- shutdown caller after that thread is joined) ----

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def qsize(self) -> int:
        return self._q.qsize()

    def idle(self) -> bool:
        with self._lock:
            return self._unfinished == 0

    def push_batch(self, entries) -> None:
        """Hand over one immutable token batch (one burst/prefill pass).

        Each entry: ``{slot, snap, tokens: [(id, logprob, n_decoded)],
        finish: None|"stop"|"length", timings: dict|None}`` — ``finish``
        set only for engine-detected finishes (EOS / length), in which
        case ``timings`` carries the engine-computed final timings."""
        with self._lock:
            self._unfinished += 1
        self._q.put(("batch", entries))

    def push_final(self, slot, snap, evs) -> None:
        """Route a failure/shutdown final through the stream's FIFO so it
        lands after any still-queued tokens. An ``evs`` list ending in
        None closes the stream (later items for the snap are dropped)."""
        with self._lock:
            self._unfinished += 1
        self._q.put(("final", slot, snap, evs))

    def abandon(self) -> None:
        """Watchdog kill: the (possibly wedged) thread discards whatever
        remains when it wakes; the engine builds a fresh worker. Never
        joins — the thread may stay stuck for a while."""
        self._dead = True
        self._q.put(None)

    def takeover(self) -> list:
        """Watchdog kill + queue seizure: mark the worker dead, hand back
        every still-queued item so the engine can fail those streams
        directly. Never joins — the thread may stay stuck on its current
        item for a while; anything it puts after the engine's direct
        error+None close lands past the sentinel and consumers ignore
        it."""
        self._dead = True
        items = []
        while True:
            try:
                it = self._q.get_nowait()
            except queue.Empty:
                break
            if it is not None:
                items.append(it)
        self._q.put(None)   # unstick the thread so it can exit
        return items

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait until everything queued so far has been processed."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.idle():
                return True
            if not self.alive:
                return self.idle()
            time.sleep(0.002)
        return False

    def stop(self, timeout: float = 5.0) -> bool:
        """Drain then terminate the worker thread (engine shutdown)."""
        ok = self.drain(timeout)
        self._dead = True
        self._q.put(None)
        self._thread.join(timeout=2.0)
        return ok

    # ---- worker thread ----

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None or self._dead:
                break
            self.t_item_start = time.monotonic()
            try:
                if FAULTS.active:
                    ms = FAULTS.take("emitter_wedge_ms")
                    if ms is not None:
                        time.sleep(float(ms) / 1e3)
                if item[0] == "batch":
                    self._process_batch(item[1])
                else:
                    _kind, slot, snap, evs = item
                    self._final(slot, snap, evs)
            except Exception as e:
                log.exception("emitter item failed")
                self._fail_item(item, e)
            finally:
                self.t_item_start = 0.0
                with self._lock:
                    self._unfinished -= 1

    def _state(self, slot, snap):
        st = self._st.get(slot)
        if st is None or st[0] is not snap:
            st = self._st[slot] = [snap, False]
        return st

    def _final(self, slot, snap, evs):
        st = self._state(slot, snap)
        if st[1]:
            return   # stream already closed (e.g. emitter-detected stop)
        out = snap.req.out
        for ev in evs:
            out.put(ev)
        if evs and evs[-1] is None:
            st[1] = True

    def _fail_item(self, item, exc):
        """An item raised mid-processing: fail every affected stream with
        a structured error so no consumer hangs on a stream whose tokens
        died with the exception (mirror of the engine loop's generic
        handler), and tell the engine to release the slots. Must never
        raise — it runs inside the worker's exception handler."""
        try:
            if item[0] == "batch":
                affected = [(e["slot"], e["snap"]) for e in item[1]]
            else:
                affected = [(item[1], item[2])]
            for slot, snap in affected:
                st = self._state(slot, snap)
                if st[1]:
                    continue
                st[1] = True
                snap.req.out.put(self._StreamEvent(
                    token_id=-1, text="", logprob=0.0, finish_reason="stop",
                    error=f"{type(exc).__name__}: {exc}"))
                snap.req.out.put(None)
                if self._note_abort is not None:
                    self._note_abort(slot, snap)
        except Exception:
            log.exception("emitter failure cleanup failed")

    def _process_batch(self, entries):
        t0 = time.monotonic()
        # compute pass first: detok + stop-scan + event assembly, no
        # queue traffic. Finished flags flip HERE, so later entries for
        # an already-finished snap in the same batch still short-circuit
        # exactly as the interleaved per-entry path did.
        writes = []
        notes = []
        for e in entries:
            out, evs, note = self._build_entry(e)
            if evs:
                writes.append((out, evs))
            if note is not None:
                notes.append(note)
        # then ONE writer pass per drained batch (ISSUE 10, closes the
        # PR-9 follow-up): with preemption making multi-slot finals in
        # one tick common, the puts go out back-to-back instead of
        # interleaving with per-slot detok work
        tput = time.monotonic()
        for out, evs in writes:
            for ev in evs:
                out.put(ev)
        # engine feedback after the streams are closed (same order the
        # per-entry path produced: put, put None, then note_finish)
        for slot, snap, ndec, timings in notes:
            self._note_finish(slot, snap, ndec, timings)
        t1 = time.monotonic()
        tr = self._tracer
        if tr.enabled:
            # same emit-vs-flush split as the in-loop spans, recorded
            # under the _bg names so the decomposition keeps this thread's
            # walltime out of host_loop (it overlaps the engine loop)
            tr.record("emit_bg", "emitter", t0, tput,
                      args={"entries": len(entries)})
            tr.record("stream_flush_bg", "emitter", tput, t1)

    def _timings(self, snap, ndec):
        """Final-event timings for an emitter-detected stop (the engine
        computes these itself for finishes it detects)."""
        t_done = time.monotonic()
        req = snap.req
        dt = t_done - snap.t_first_token
        queue_wait_ms = max(0.0, (snap.t_start - req.t_submit) * 1e3) \
            if req.t_submit else 0.0
        admit_to_first_ms = max(0.0, (snap.t_first_token - snap.t_start) * 1e3) \
            if snap.t_first_token else 0.0
        return {
            "prefill_ms": snap.t_prefill_ms,
            "queue_wait_ms": queue_wait_ms,
            "admit_to_first_ms": admit_to_first_ms,
            "reused_prompt_tokens": snap.reused,
            "decode_tokens_per_s":
                (ndec - 1) / dt if dt > 0 and ndec > 1 else 0.0,
        }

    def _build_entry(self, e):
        """Detok + stop-scan + event assembly for one entry, NO queue
        traffic: returns ``(out_queue, events, note)``. ``events`` may
        end in the None stream-close sentinel; ``note`` is the
        ``(slot, snap, ndec, timings)`` engine feedback for an
        emitter-DETECTED stop (the engine does not know yet — it must
        release the slot and drop tokens decoded past the stop)."""
        snap = e["snap"]
        slot = e["slot"]
        st = self._state(slot, snap)
        if st[1]:
            return None, (), None
        toks = e["tokens"]
        finish = e["finish"]
        evs = []
        last_j = len(toks) - 1
        for j, (tok, lp, ndec) in enumerate(toks):
            fin = finish if j == last_j else None
            timings = None   # set only for emitter-DETECTED stops
            if fin == "stop":
                # engine-detected EOS: the token itself is never
                # detokenized (in-loop parity)
                delta = snap.held_text + snap.detok.flush()
                snap.held_text = ""
            elif fin == "length":
                delta = snap.held_text + snap.detok.push(tok) \
                    + snap.detok.flush()
                snap.held_text = ""
            else:
                delta = snap.held_text + snap.detok.push(tok)
                snap.held_text = ""
                if snap.req.stop_sequences:
                    cut = check_stops(snap, delta)
                    if cut is not None:
                        delta, fin = cut, "stop"
                        timings = self._timings(snap, ndec)
                    elif delta:
                        delta, snap.held_text = holdback(snap, delta)
            ev = self._StreamEvent(
                token_id=tok, text=delta, logprob=lp, finish_reason=fin,
                prompt_tokens=snap.prompt_len, completion_tokens=ndec)
            self.emitted += 1
            if fin is not None:
                st[1] = True
                ev.timings = e["timings"] if timings is None else timings
                final = []
                if evs:
                    final.append(evs[0] if len(evs) == 1
                                 else self._merge(evs))
                final.append(ev)
                final.append(None)
                note = (slot, snap, ndec, timings) \
                    if timings is not None else None
                return snap.req.out, final, note
            evs.append(ev)
        if evs:
            return snap.req.out, \
                [evs[0] if len(evs) == 1 else self._merge(evs)], None
        return None, (), None
