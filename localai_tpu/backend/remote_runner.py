"""Remote-API passthrough LLM backend (reference parity:
backend/go/llm/langchain/langchain.go + pkg/langchain/huggingface.go —
the lowest-priority greedy fallback that answers via the HuggingFace
Inference API when no local backend can serve a model).

LoadModel `model` is either a full endpoint URL (http/https) or a HF
model id (mapped to the public inference endpoint). The token comes from
HUGGINGFACEHUB_API_TOKEN / HF_TOKEN, like the reference.
"""

from __future__ import annotations

import json
import logging
import os
import urllib.request

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger(__name__)

HF_ENDPOINT = "https://api-inference.huggingface.co/models/{model}"


class RemoteServicer(BackendServicer):
    def __init__(self):
        self.endpoint = None
        self.token = ""

    def LoadModel(self, request, context):
        model = request.model or ""
        if model.startswith(("http://", "https://")):
            self.endpoint = model
        elif model:
            self.endpoint = HF_ENDPOINT.format(model=model)
        else:
            return pb.Result(success=False, message="no model/endpoint")
        self.token = (os.environ.get("HUGGINGFACEHUB_API_TOKEN")
                      or os.environ.get("HF_TOKEN") or "")
        return pb.Result(success=True, message="remote endpoint set")

    def _infer(self, opts: "pb.PredictOptions") -> str:
        body = {
            "inputs": opts.prompt,
            "parameters": {
                "max_new_tokens": opts.max_tokens or 256,
                "temperature": max(opts.temperature, 1e-3)
                if opts.temperature else None,
                "top_p": opts.top_p or None,
                "top_k": opts.top_k or None,
                "return_full_text": False,
            },
        }
        body["parameters"] = {k: v for k, v in body["parameters"].items()
                              if v is not None}
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self.token}"}
                        if self.token else {})})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read().decode())
        # HF text-generation shape: [{"generated_text": "..."}]
        if isinstance(out, list) and out and "generated_text" in out[0]:
            return out[0]["generated_text"]
        if isinstance(out, dict) and "generated_text" in out:
            return out["generated_text"]
        raise ValueError(f"unexpected remote response: {str(out)[:200]}")

    def Predict(self, request, context):
        if self.endpoint is None:
            import grpc

            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no endpoint configured")
        try:
            text = self._infer(request)
            return pb.Reply(message=text.encode("utf-8"))
        except Exception as e:
            log.exception("remote inference failed")
            import grpc

            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"{type(e).__name__}: {e}")

    def PredictStream(self, request, context):
        # the reference's langchain backend is also non-incremental: one
        # remote call, one reply (langchain.go:34-62)
        yield self.Predict(request, context)

    def Status(self, request, context):
        state = (pb.StatusResponse.READY if self.endpoint
                 else pb.StatusResponse.UNINITIALIZED)
        return pb.StatusResponse(state=state,
                                 memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    args = parser.parse_args(argv)
    server = make_server(RemoteServicer(), args.addr)
    server.start()
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
