"""Fake echo backend — hermetic test double for the backend contract.

The reference has NO fake backend (its API tests run real models;
SURVEY.md section 4 takeaway). This fills that gap: a fully in-memory
servicer usable in-process (embedded) or spawned
(python -m localai_tpu.backend.fake --addr ...), so HTTP-layer tests are
fast and deterministic.

Behavior: PredictStream emits the prompt's whitespace tokens back one by
one (prefixed configurably); Embedding returns a hash-derived unit vector;
TTS/Image write tiny valid files; Stores is a real in-memory store.
"""

from __future__ import annotations

import hashlib
import math
import os
import struct
import time

import grpc

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server


class FakeServicer(BackendServicer):
    def __init__(self, delay_s: float = 0.0, handshake: bool = True):
        self.delay_s = delay_s
        self.loaded = None
        self.store: dict = {}
        # clock-handshake + trace-propagation test hooks (ISSUE 12):
        # handshake=False restores the legacy plain-"loaded" reply the
        # loader must stay tolerant of; seen_metadata records each
        # Predict/PredictStream call's invocation metadata so tests can
        # assert the localai-trace-id hop end to end
        self.handshake = handshake
        self.seen_metadata: list = []
        self.last_trace_id = ""
        self._t0_epoch = time.time()

    def LoadModel(self, request, context):
        if "fail" in request.model:
            return pb.Result(success=False, message="fake load failure")
        self.loaded = request
        if not self.handshake:
            return pb.Result(success=True, message="loaded")
        import json

        return pb.Result(success=True, message=json.dumps({
            "status": "loaded",
            "handshake": {"wall": time.time(), "mono": time.monotonic(),
                          "trace_epoch": self._t0_epoch,
                          "pid": os.getpid()}}))

    def _capture_meta(self, context) -> dict:
        md = {}
        fn = getattr(context, "invocation_metadata", None)
        if fn is not None:
            for k, v in fn() or ():
                md[str(k)] = str(v)
        self.seen_metadata.append(md)
        if md.get("localai-trace-id"):
            self.last_trace_id = md["localai-trace-id"]
        return md

    def _chunks(self, opts):
        words = opts.prompt.split() or ["echo"]
        n = opts.max_tokens or len(words)
        return words[:n]

    def Predict(self, request, context):
        self._capture_meta(context)
        chunks = self._chunks(request)
        text = " ".join(chunks)
        if request.echo:
            text = request.prompt + text
        return pb.Reply(
            message=text.encode(), tokens=len(chunks),
            prompt_tokens=len(request.prompt.split()), finish_reason="stop",
        )

    def PredictStream(self, request, context):
        self._capture_meta(context)
        chunks = self._chunks(request)
        stops = list(request.stop_sequences)
        for i, w in enumerate(chunks):
            if self.delay_s:
                time.sleep(self.delay_s)
            text = (" " if i else "") + w
            if any(s in w for s in stops):
                yield pb.Reply(message=b"", tokens=i + 1, finish_reason="stop")
                return
            yield pb.Reply(
                message=text.encode(), token_id=i, tokens=i + 1,
                prompt_tokens=len(request.prompt.split()),
                finish_reason="stop" if i == len(chunks) - 1 else "",
            )

    def Embedding(self, request, context):
        h = hashlib.sha256(request.prompt.encode()).digest()
        vals = [b / 255.0 for b in h[:16]]
        norm = math.sqrt(sum(v * v for v in vals)) or 1.0
        return pb.EmbeddingResult(embeddings=[v / norm for v in vals])

    def TokenizeString(self, request, context):
        toks = [abs(hash(w)) % 50000 for w in request.prompt.split()]
        return pb.TokenizationResponse(length=len(toks), tokens=toks)

    def TTS(self, request, context):
        _write_wav(request.dst, b"\x00\x00" * 1600)
        return pb.Result(success=True, message="ok")

    def SoundGeneration(self, request, context):
        _write_wav(request.dst, b"\x00\x01" * 1600)
        return pb.Result(success=True, message="ok")

    def AudioTranscription(self, request, context):
        return pb.TranscriptResult(
            segments=[pb.TranscriptSegment(id=0, start=0, end=int(1e9), text="fake transcript")],
            text="fake transcript",
        )

    def GenerateImage(self, request, context):
        # 1x1 black PNG
        png = bytes.fromhex(
            "89504e470d0a1a0a0000000d49484452000000010000000108060000001f15c489"
            "0000000d4944415478da636400000000060003660d23380000000049454e44ae426082"
        )
        os.makedirs(os.path.dirname(request.dst) or ".", exist_ok=True)
        with open(request.dst, "wb") as f:
            f.write(png)
        return pb.Result(success=True, message="ok")

    def Rerank(self, request, context):
        scored = sorted(
            (
                (sum(1 for w in request.query.split() if w.lower() in d.lower()), i, d)
                for i, d in enumerate(request.documents)
            ),
            reverse=True,
        )
        top = scored[: request.top_n or len(scored)]
        return pb.RerankResult(
            usage=pb.Usage(total_tokens=len(request.query.split()), prompt_tokens=0),
            results=[
                pb.DocumentResult(index=i, text=d, relevance_score=float(s))
                for s, i, d in top
            ],
        )

    def Status(self, request, context):
        return pb.StatusResponse(
            state=pb.StatusResponse.READY if self.loaded else pb.StatusResponse.UNINITIALIZED,
            memory=pb.MemoryUsageData(total=0),
        )

    def _options(self) -> dict:
        """Parse the loaded model's proto options (one "k=v,..." string)
        into a dict — the shape-switching seams below all key off it."""
        opts = {}
        raw = self.loaded.options if self.loaded is not None else ""
        for s in str(raw).split(","):
            if "=" in s:
                k, v = s.split("=", 1)
                opts[k.strip()] = v.strip()
        return opts

    def _autoscale_payload(self, opts: dict):
        """(pool_stats, state_autoscale) mirroring EnginePool.metrics()
        / .state_snapshot() when engines=N>1 or autoscale=1 was
        requested (ISSUE 19), else (None, None). The HTTP layer's
        /readyz, /metrics and /debug/state parse these shapes off the
        real runner; the fake answers the same ones so those surfaces
        are testable hermetically."""
        n = int(opts.get("engines", "1") or 1)
        auto = str(opts.get("autoscale", "0")).lower() in (
            "1", "true", "on", "yes")
        if n <= 1 and not auto:
            return None, None
        n = max(1, n)
        stats = {
            "engine_replicas": n,
            "engine_replicas_target": n,
            "replicas": [{"replica": i, "alive": True, "draining": False,
                          "queued": 0, "slots_in_flight": 0,
                          "slots_total": 1} for i in range(n)],
            "pool": {"replicas_alive": n, "replicas_target": n,
                     "affinity_hits": 0, "affinity_misses": 0,
                     "routed": 0, "migrations": {}, "index_keys": 0},
        }
        last = None
        if auto:
            last = {"t": 0.0, "direction": "out", "from": n, "to": n,
                    "reason": "fake", "signals": {}}
            stats["pool"]["autoscale"] = {
                "decisions": {"out": 0, "in": 0},
                "flaps_suppressed": {"out": 0, "in": 0},
                "flaps": 0, "last_decision": last,
                "params": {"min": 1, "max": max(2, n), "burn_out": 1.0,
                           "burn_in": 0.05, "queue_out_frac": 0.5,
                           "dwell_s": 2.0, "cooldown_s": 4.0,
                           "idle_in_s": 1.5},
            }
        state_auto = {"enabled": auto, "target": n, "replicas_alive": n,
                      "replicas_routable": n, "last_decision": last}
        return stats, state_auto

    def GetMetrics(self, request, context):
        stats, _ = self._autoscale_payload(self._options())
        if stats is None:
            return pb.MetricsResponse(slots_total=1, slots_active=0)
        import json

        return pb.MetricsResponse(
            slots_total=len(stats["replicas"]), slots_active=0,
            prompt_json_for_slot=json.dumps(stats))

    def _kv_payload(self) -> dict:
        """The GetState "kv" key (ISSUE 15): honors the model's
        kv_audit= option ({"mode": "off"} shape) and answers the
        EnginePool merged multi-replica view when engines=N>1 was
        requested — shape mirrors engine.kv_debug()/pool.kv_debug()."""
        opts = self._options()
        mode = opts.get("kv_audit", "on")
        if mode == "off":
            return {"mode": "off", "replica": 0}

        def replica(i: int) -> dict:
            return {
                "mode": mode, "replica": i,
                "pool": {"pages_total": 8, "page_size": 16, "free": 7,
                         "active": 1, "retained": 0, "shared": 0,
                         "oversubscription": 0.0,
                         "fragmentation": {"holes": 0, "ratio": 0.0},
                         "pages_per_slot": [1]},
                "chains": [{"key": "00" * 8, "parent": "00" * 8,
                            "page": 0, "depth": 0, "tick": 1}],
                "audit": {"mode": mode, "checks": 1, "violations": 0,
                          "leaked_pages": 0, "ledger_events": 1,
                          "ledger": {"events_total": 1, "live_pages": 1,
                                     "live_holds": 0,
                                     "counts": {"alloc": 1}},
                          "last_violations": []},
                "ledger_tail": [{"seq": 1, "op": "alloc", "page": 0,
                                 "slot": "0", "key": "", "rid": ""}],
                "host": {"pages": 0, "bytes": 0},
            }

        n = int(opts.get("engines", "1") or 1)
        if n > 1:
            return {"engine_replicas": n,
                    "replicas": [replica(i) for i in range(n)],
                    "pool_index_keys": 0,
                    "shared_host": {"pages": 0, "bytes": 0,
                                    "mapped_keys": 0}}
        return replica(0)

    def GetState(self, request, context):
        # minimal engine-state + event-ring snapshot (the /debug/state
        # and /debug/events merge paths need a backend that answers;
        # shape mirrors backend/runner.py GetState)
        import json
        import time

        st = {"slots": [None], "slots_active": 0, "queued": 0,
              "warm": True,
              "compiles": {"compiles_total": 0,
                           "compile_seconds_total": 0.0,
                           "compiles_after_warmup": 0,
                           "warm": True},
              "last_compiles": [], "watermarks": {},
              "goodput": {"goodput_tokens_total": 0, "mfu": 0.0},
              "weight_bytes": 0}
        _, state_auto = self._autoscale_payload(self._options())
        if state_auto is not None:
            st["autoscale"] = state_auto
        return pb.Reply(message=json.dumps({
            "state": st,
            "events": [{"ts": time.time(), "event": "admit", "seq": 1,
                        "rid": "fake0000"}],
            "kv": self._kv_payload(),
        }).encode("utf-8"))

    def GetTrace(self, request, context):
        # minimal valid Chrome trace (the /debug/trace merge path needs
        # a backend that answers; shape mirrors services/tracing.py,
        # INCLUDING the localai epoch block and a span keyed by the last
        # propagated trace id so the cross-process merge is testable)
        import json

        decode_args = {}
        if self.last_trace_id:
            decode_args["request_id"] = self.last_trace_id
        return pb.Reply(message=json.dumps({
            "displayTimeUnit": "ms",
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": "fake"}},
                {"name": "decode", "cat": "engine", "ph": "X", "pid": 1,
                 "tid": 1, "ts": 0.0, "dur": 100.0, "args": decode_args},
            ],
            "localai": {"t0_epoch": self._t0_epoch, "pid": os.getpid()},
        }).encode("utf-8"))

    # --- stores: real in-memory implementation ---
    def StoresSet(self, request, context):
        for k, v in zip(request.keys, request.values):
            self.store[tuple(k.floats)] = bytes(v.bytes)
        return pb.Result(success=True)

    def StoresDelete(self, request, context):
        for k in request.keys:
            self.store.pop(tuple(k.floats), None)
        return pb.Result(success=True)

    def StoresGet(self, request, context):
        keys, values = [], []
        for k in request.keys:
            t = tuple(k.floats)
            if t in self.store:
                keys.append(pb.StoresKey(floats=list(t)))
                values.append(pb.StoresValue(bytes=self.store[t]))
        return pb.StoresGetResult(keys=keys, values=values)

    def StoresFind(self, request, context):
        q = list(request.key.floats)
        qn = math.sqrt(sum(x * x for x in q)) or 1.0
        sims = []
        for t, v in self.store.items():
            dot = sum(a * b for a, b in zip(q, t))
            tn = math.sqrt(sum(x * x for x in t)) or 1.0
            sims.append((dot / (qn * tn), t, v))
        sims.sort(reverse=True)
        top = sims[: request.top_k or len(sims)]
        return pb.StoresFindResult(
            keys=[pb.StoresKey(floats=list(t)) for _, t, _ in top],
            values=[pb.StoresValue(bytes=v) for _, _, v in top],
            similarities=[s for s, _, _ in top],
        )


def _write_wav(dst: str, pcm: bytes, rate: int = 16000):
    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    hdr = b"RIFF" + struct.pack("<I", 36 + len(pcm)) + b"WAVEfmt " + struct.pack(
        "<IHHIIHH", 16, 1, 1, rate, rate * 2, 2, 16
    ) + b"data" + struct.pack("<I", len(pcm))
    with open(dst, "wb") as f:
        f.write(hdr + pcm)


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--delay", type=float, default=0.0)
    args = parser.parse_args(argv)
    server = make_server(FakeServicer(args.delay), args.addr)
    server.start()
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
