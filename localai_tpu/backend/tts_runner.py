"""TTS backend: neural synthesis on TPU behind the TTS/SoundGeneration RPCs.

Capability parity with the reference's TTS backends (reference:
backend/go/tts/piper.go:1-49 — TTS(text, model, voice, dst) writes a WAV
file; backend/python/transformers-musicgen/backend.py SoundGeneration
with duration). Voice selection maps to a deterministic parameter seed
when no trained checkpoint is present (offline environments), so the
full gRPC -> synthesis -> WAV path stays real.
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.tts_runner")


class _VocabTokenizer:
    """Minimal VITS text frontend when transformers' VitsTokenizer is
    unavailable: vocab.json char map with the interspersed pad token
    (VitsTokenizer add_blank semantics)."""

    def __init__(self, model_dir: str):
        import json

        with open(os.path.join(model_dir, "vocab.json")) as f:
            self.vocab = json.load(f)
        self.pad = self.vocab.get("<pad>", self.vocab.get(" ", 0))

    def encode(self, text: str):
        ids = [self.pad]
        for ch in text.lower():
            tid = self.vocab.get(ch)
            if tid is None:
                continue
            ids += [tid, self.pad]
        return ids


class TTSServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        self._voice_cache = {}
        self._lock = threading.Lock()
        # real-checkpoint path (HF VitsModel: facebook/mms-tts-*,
        # kakao-enterprise/vits-*) — set when config.json says vits
        self.vits = None       # (cfg, params)
        self.vits_tokenizer = None
        # real music generation (HF MusicgenForConditionalGeneration)
        self.musicgen = None   # (cfg, params)
        self.musicgen_tokenizer = None
        # Bark three-stage pipeline (suno/bark-*; reference:
        # backend/python/bark/backend.py)
        self.bark = None       # (cfg, params, codec_cfg, codec_params)
        self.bark_tokenizer = None

    def LoadModel(self, request, context):
        try:
            import json as _json

            import jax

            from localai_tpu.models import tts

            model_dir = request.model
            if request.model_path and model_dir and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            self.model_dir = model_dir
            cfg_path = os.path.join(model_dir or "", "config.json")
            cfg_dict = {}
            if model_dir and os.path.exists(cfg_path):
                with open(cfg_path) as f:
                    cfg_dict = _json.load(f)
            # a reload must never leave a previous real model active
            self.vits = None
            self.vits_tokenizer = None
            self.musicgen = None
            self.musicgen_tokenizer = None
            self.bark = None
            self.bark_tokenizer = None
            if cfg_dict.get("model_type") == "bark":
                # suno/bark-class checkpoint: semantic -> coarse -> fine
                # GPTs + EnCodec decode, torch forward parity
                # (models/bark.py; reference: backend/python/bark/
                # backend.py:1-93)
                from localai_tpu.models import bark as jbark

                bcfg = jbark.BarkConfig.from_dir(model_dir)
                params, codec_cfg, codec = jbark.load_hf_params(model_dir,
                                                                bcfg)
                self.bark = (bcfg, params, codec_cfg, codec)
                from transformers import AutoTokenizer

                self.bark_tokenizer = AutoTokenizer.from_pretrained(model_dir)
                self.cfg = tts.TTSConfig()
                self.params = params
            elif cfg_dict.get("model_type") == "musicgen":
                # published MusicGen checkpoint (facebook/musicgen-*):
                # T5 text encoder + codebook LM + EnCodec decode, full
                # torch parity (models/musicgen.py; reference:
                # backend/python/transformers-musicgen/backend.py)
                from localai_tpu.models import musicgen as jmg

                mcfg = jmg.MusicgenConfig.from_json(cfg_path)
                self.musicgen = (mcfg, jmg.load_hf_params(model_dir, mcfg))
                from transformers import AutoTokenizer

                self.musicgen_tokenizer = AutoTokenizer.from_pretrained(
                    model_dir)
                self.cfg = tts.TTSConfig()
                self.params = self.musicgen[1]
            elif cfg_dict.get("model_type") == "vits":
                # published VITS/MMS checkpoint: full parity stack
                from localai_tpu.models import vits as jvits

                vcfg, vparams = jvits.load_params(
                    model_dir, jvits.VitsConfig.from_dict(cfg_dict))
                self.vits = (vcfg, vparams)
                # voice clone (r5, VERDICT r4 #4): a tone-color encoder in
                # the model dir + ModelOptions.audio_path (the reference's
                # audio-prompt field, vall-e-x/backend.py:61-68) condition
                # synthesis on a reference recording
                from localai_tpu.models import voice_clone as vc

                self.tone = vc.load_params(model_dir)
                self.ref_embedding = None
                if request.audio_path:
                    ref = request.audio_path
                    if request.model_path and not os.path.isabs(ref):
                        ref = os.path.join(request.model_path, ref)
                    if self.tone[0] is None:
                        raise ValueError(
                            "audio_path given but the model has no tone "
                            "encoder (tone_encoder.safetensors) — voice "
                            "cloning needs one")
                    self.ref_embedding = vc.embed_reference(
                        self.tone[0], self.tone[1], ref)
                try:
                    from transformers import AutoTokenizer

                    self.vits_tokenizer = AutoTokenizer.from_pretrained(model_dir)
                    self.vits_tokenizer("probe")  # some variants need phonemizer
                except Exception:
                    self.vits_tokenizer = _VocabTokenizer(model_dir)
                # keep a toy config for SoundGeneration sample-rate math
                self.cfg = tts.TTSConfig()
                self.params = vparams
            elif cfg_dict:
                self.cfg = tts.TTSConfig.from_json(cfg_path)
                self.params = tts.load_params(model_dir, self.cfg)
            else:
                # no checkpoint: deterministic random voice (see module doc)
                self.cfg = tts.TTSConfig()
                self.params = tts.init_params(self.cfg, jax.random.PRNGKey(0))
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _vits_synthesize(self, text: str, voice: str = "") -> tuple:
        from localai_tpu.models import vits as jvits

        vcfg, vparams = self.vits
        ids = self.vits_tokenizer(text)["input_ids"] \
            if callable(self.vits_tokenizer) else \
            self.vits_tokenizer.encode(text)
        # voice clone: a WAV path as the voice (per-request reference
        # audio — ElevenLabs voice_id / TTSRequest.voice) or the
        # load-time audio_path embedding
        ref_emb = getattr(self, "ref_embedding", None)
        tone = getattr(self, "tone", (None, None))
        if voice and voice.lower().endswith(".wav"):
            if tone[0] is None:
                raise ValueError(
                    "reference-audio voice given but the model has no "
                    "tone encoder (tone_encoder.safetensors)")
            # the voice field arrives from the HTTP API: confine it to the
            # model dir so it can't read (or existence-probe) arbitrary
            # server paths
            base = os.path.realpath(getattr(self, "model_dir", "") or ".")
            ref = os.path.realpath(os.path.join(base, voice))
            if ref != base and not ref.startswith(base + os.sep):
                raise ValueError(
                    "reference-audio voice must name a WAV inside the "
                    "model directory")
            if not os.path.exists(ref):
                raise ValueError(f"reference audio not found: {voice}")
            from localai_tpu.models import voice_clone as vc

            ref_emb = vc.embed_reference(tone[0], tone[1], ref)
        if ref_emb is not None:
            wave = jvits.synthesize(vparams, vcfg,
                                    np.asarray(ids, np.int32),
                                    speaker_embedding=ref_emb,
                                    frame_pad_to=64)
            return wave, vcfg.sampling_rate
        speaker = None
        if vcfg.num_speakers > 1:
            try:
                speaker = int(voice) if voice else 0
            except ValueError:
                speaker = 0
            if not 0 <= speaker < vcfg.num_speakers:
                # JAX gathers clamp silently; surface the config error
                raise ValueError(
                    f"voice {speaker} out of range 0-{vcfg.num_speakers - 1}")
        wave = jvits.synthesize(vparams, vcfg, np.asarray(ids, np.int32),
                                speaker_id=speaker, frame_pad_to=64)
        return wave, vcfg.sampling_rate

    def _bark_synthesize(self, text: str, voice: str = "") -> tuple:
        """Bark pipeline. ``voice`` may name a suno-format .npz speaker
        preset (semantic_prompt/coarse_prompt/fine_prompt arrays) inside
        the model dir; its semantic prompt conditions generation."""
        from localai_tpu.models import bark as jbark

        bcfg, params, codec_cfg, codec = self.bark
        history = None
        if voice:
            base = os.path.realpath(getattr(self, "model_dir", "") or ".")
            ref = os.path.realpath(os.path.join(
                base, voice if voice.endswith(".npz") else voice + ".npz"))
            # confine like the VITS voice-clone path: HTTP-supplied names
            # must not probe arbitrary server paths
            if ref != base and not ref.startswith(base + os.sep):
                raise ValueError(
                    "bark voice preset must name an .npz inside the "
                    "model directory")
            if not os.path.exists(ref):
                raise ValueError(f"voice preset not found: {voice}")
            npz = np.load(ref)
            history = {k: npz[k] for k in npz.files}
        # no [CLS]/[SEP]: BarkProcessor tokenizes with
        # add_special_tokens=False — special ids would be offset into
        # tokens the semantic GPT never saw
        enc = self.bark_tokenizer(text, add_special_tokens=False)
        ids = np.asarray(enc["input_ids"], np.int64)[None]
        max_sem = int(os.environ.get("LOCALAI_BARK_MAX_SEMANTIC", "0")) or None
        wave = jbark.generate_speech(
            params, bcfg, codec_cfg, codec, ids,
            np.asarray([ids.shape[1]]), history=history,
            max_semantic=max_sem)
        return wave[0], codec_cfg.sampling_rate

    def _params_for_voice(self, voice: str):
        if not voice:
            return self.params
        p = self._voice_cache.get(voice)
        if p is None:
            import jax

            from localai_tpu.models import tts

            seed = int.from_bytes(hashlib.sha256(voice.encode()).digest()[:4], "little")
            p = tts.init_params(self.cfg, jax.random.PRNGKey(seed))
            if len(self._voice_cache) > 8:
                self._voice_cache.clear()
            self._voice_cache[voice] = p
        return p

    def TTS(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import tts

        try:
            with self._lock:
                if self.musicgen is not None:
                    # the reference's musicgen backend serves TTS too
                    # (transformers-musicgen backend.py TTS -> generate)
                    wave, rate = self._musicgen_generate(
                        pb.SoundGenerationRequest(text=request.text,
                                                  duration=8.0))
                    tts.write_wav(request.dst, wave, sample_rate=rate)
                    return pb.Result(success=True, message="ok")
                if self.bark is not None:
                    wave, rate = self._bark_synthesize(request.text,
                                                       request.voice)
                    tts.write_wav(request.dst, wave, sample_rate=rate)
                    return pb.Result(success=True, message="ok")
                if self.vits is not None:
                    wave, rate = self._vits_synthesize(request.text,
                                                       request.voice)
                    tts.write_wav(request.dst, wave, sample_rate=rate)
                    return pb.Result(success=True, message="ok")
                wave = tts.synthesize(self._params_for_voice(request.voice),
                                      self.cfg, request.text)
            tts.write_wav(request.dst, wave)
            return pb.Result(success=True, message="ok")
        except Exception as e:
            log.exception("TTS failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def SoundGeneration(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import tts

        try:
            with self._lock:
                if self.musicgen is not None:
                    wave, rate = self._musicgen_generate(request)
                    tts.write_wav(request.dst, wave, sample_rate=rate)
                    return pb.Result(success=True, message="ok")
                if self.bark is not None:
                    wave, rate = self._bark_synthesize(request.text)
                elif self.vits is not None:
                    wave, rate = self._vits_synthesize(request.text)
                else:
                    wave = tts.synthesize(self._params_for_voice(""), self.cfg,
                                          request.text)
                    rate = tts.SAMPLE_RATE
            if request.HasField("duration"):
                want = int(request.duration * rate)
                reps = max(1, -(-want // max(len(wave), 1)))
                wave = np.tile(wave, reps)[:want]
            tts.write_wav(request.dst, wave, sample_rate=rate)
            return pb.Result(success=True, message="ok")
        except Exception as e:
            log.exception("SoundGeneration failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _musicgen_generate(self, request) -> tuple:
        """Reference semantics (transformers-musicgen backend.py:1-176):
        text prompt + optional duration (default 8 s) + temperature /
        do_sample; sampled top-k generation with CFG."""
        from localai_tpu.models import musicgen as jmg

        mcfg, params = self.musicgen
        duration = (float(request.duration)
                    if request.HasField("duration") else 8.0)
        frames = max(1, int(round(duration * mcfg.frame_rate)))
        do_sample = (bool(request.sample)
                     if request.HasField("sample") else True)
        temperature = (float(request.temperature)
                       if request.HasField("temperature") else 1.0)
        if not do_sample:
            temperature = 0.0
        enc = self.musicgen_tokenizer(request.text, return_tensors="np")
        tokens = np.asarray(enc["input_ids"], np.int32)
        mask = np.asarray(enc.get(
            "attention_mask", np.ones_like(tokens)), np.int32)
        wave = jmg.generate(params, mcfg, tokens, mask, frames=frames,
                            temperature=temperature,
                            seed=hash(request.text) & 0x7FFFFFFF)
        return wave, mcfg.enc.sampling_rate

    def Status(self, request, context):
        state = pb.StatusResponse.READY if self.params is not None else \
            pb.StatusResponse.UNINITIALIZED
        return pb.StatusResponse(state=state, memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    servicer = TTSServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("tts backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
