"""TTS backend: neural synthesis on TPU behind the TTS/SoundGeneration RPCs.

Capability parity with the reference's TTS backends (reference:
backend/go/tts/piper.go:1-49 — TTS(text, model, voice, dst) writes a WAV
file; backend/python/transformers-musicgen/backend.py SoundGeneration
with duration). Voice selection maps to a deterministic parameter seed
when no trained checkpoint is present (offline environments), so the
full gRPC -> synthesis -> WAV path stays real.
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.tts_runner")


class TTSServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        self._voice_cache = {}
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        try:
            import jax

            from localai_tpu.models import tts

            model_dir = request.model
            if request.model_path and model_dir and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            if model_dir and os.path.exists(os.path.join(model_dir, "config.json")):
                self.cfg = tts.TTSConfig.from_json(os.path.join(model_dir, "config.json"))
                self.params = tts.load_params(model_dir, self.cfg)
            else:
                # no checkpoint: deterministic random voice (see module doc)
                self.cfg = tts.TTSConfig()
                self.params = tts.init_params(self.cfg, jax.random.PRNGKey(0))
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _params_for_voice(self, voice: str):
        if not voice:
            return self.params
        p = self._voice_cache.get(voice)
        if p is None:
            import jax

            from localai_tpu.models import tts

            seed = int.from_bytes(hashlib.sha256(voice.encode()).digest()[:4], "little")
            p = tts.init_params(self.cfg, jax.random.PRNGKey(seed))
            if len(self._voice_cache) > 8:
                self._voice_cache.clear()
            self._voice_cache[voice] = p
        return p

    def TTS(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import tts

        try:
            with self._lock:
                wave = tts.synthesize(self._params_for_voice(request.voice),
                                      self.cfg, request.text)
            tts.write_wav(request.dst, wave)
            return pb.Result(success=True, message="ok")
        except Exception as e:
            log.exception("TTS failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def SoundGeneration(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import tts

        try:
            with self._lock:
                wave = tts.synthesize(self._params_for_voice(""), self.cfg,
                                      request.text)
            if request.HasField("duration"):
                want = int(request.duration * tts.SAMPLE_RATE)
                reps = max(1, -(-want // max(len(wave), 1)))
                wave = np.tile(wave, reps)[:want]
            tts.write_wav(request.dst, wave)
            return pb.Result(success=True, message="ok")
        except Exception as e:
            log.exception("SoundGeneration failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def Status(self, request, context):
        state = pb.StatusResponse.READY if self.params is not None else \
            pb.StatusResponse.UNINITIALIZED
        return pb.StatusResponse(state=state, memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    servicer = TTSServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("tts backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
