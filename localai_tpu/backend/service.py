"""gRPC plumbing for the backend contract — hand-rolled stubs.

The environment has grpcio + protoc but not grpcio-tools, so instead of
generated service stubs this module builds client/server bindings from a
method table using grpc's generic API. Same wire format, less magic.

Parity: reference pkg/grpc/client.go (Go client, one method per RPC) and
pkg/grpc/server.go (shim letting in-tree backends serve the proto). The
reference dials a new connection per call (client.go:60 — noted as a wart
in SURVEY.md); here one channel is created per backend and reused.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent import futures
from typing import Iterator, Optional

import grpc

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.services.faults import FAULTS

_log = logging.getLogger("localai_tpu.backend.service")

SERVICE = "localai_tpu.Backend"

# name -> (request message, response message, server_streaming)
METHODS = {
    "Health": (pb.HealthMessage, pb.Reply, False),
    "LoadModel": (pb.ModelOptions, pb.Result, False),
    "Predict": (pb.PredictOptions, pb.Reply, False),
    "PredictStream": (pb.PredictOptions, pb.Reply, True),
    "Embedding": (pb.PredictOptions, pb.EmbeddingResult, False),
    "TokenizeString": (pb.PredictOptions, pb.TokenizationResponse, False),
    "GenerateImage": (pb.GenerateImageRequest, pb.Result, False),
    "TTS": (pb.TTSRequest, pb.Result, False),
    "SoundGeneration": (pb.SoundGenerationRequest, pb.Result, False),
    "AudioTranscription": (pb.TranscriptRequest, pb.TranscriptResult, False),
    "Rerank": (pb.RerankRequest, pb.RerankResult, False),
    "Status": (pb.HealthMessage, pb.StatusResponse, False),
    "GetMetrics": (pb.MetricsRequest, pb.MetricsResponse, False),
    # observability side-channel (no new proto messages — the hand-rolled
    # stubs can't grow fields, but METHODS can grow RPCs):
    #   GetTrace: Reply.message carries Chrome trace-event JSON (UTF-8)
    #   Profile:  PredictOptions.prompt carries a JSON {"seconds": N};
    #             Result.message is the capture directory
    #   GetState: Reply.message carries a JSON {"state": engine state
    #             snapshot, "events": event-log ring} (ISSUE 8)
    "GetTrace": (pb.MetricsRequest, pb.Reply, False),
    "GetState": (pb.MetricsRequest, pb.Reply, False),
    "Profile": (pb.PredictOptions, pb.Result, False),
    "StoresSet": (pb.StoresSetOptions, pb.Result, False),
    "StoresDelete": (pb.StoresDeleteOptions, pb.Result, False),
    "StoresGet": (pb.StoresGetOptions, pb.StoresGetResult, False),
    "StoresFind": (pb.StoresFindOptions, pb.StoresFindResult, False),
}


def parse_options(options: str) -> dict:
    """ModelOptions.options wire format ("k=v,k2=v2", produced by
    capabilities.build_model_options) -> dict. The ONE parser every
    backend shares."""
    return dict(kv.split("=", 1) for kv in (options or "").split(",")
                if "=" in kv)


class BackendServicer:
    """Base servicer: every RPC answers UNIMPLEMENTED unless overridden.

    Concrete backends (engine runner, fake echo, store backend) override
    the subset they support — mirrors the reference's base backend
    (pkg/grpc/base/base.go:16 'Unimplemented' pattern).
    """

    def Health(self, request, context) -> pb.Reply:
        return pb.Reply(message=b"OK")

    def __getattr__(self, name):
        if name in METHODS:
            def _unimplemented(request, context):
                context.abort(grpc.StatusCode.UNIMPLEMENTED, f"{name} not implemented")
            return _unimplemented
        raise AttributeError(name)


def _inject_faults(name: str, fn, streaming: bool):
    """Wrap an RPC handler with the chaos-harness injection points
    (services/faults.py). With nothing armed this is one attribute read
    per call. Wrapping at the server layer covers every backend — the
    real engine runner AND the fake echo backend tests spawn.

    - ``rpc_unavailable=<Method>``: abort that RPC with UNAVAILABLE
      before the handler runs (the client-side idempotent-unary retry
      must absorb it).
    - ``kill_backend_after_tokens=N``: hard-exit the backend process
      after N streamed PredictStream tokens (a mid-stream crash, the
      supervisor's worst case).
    """
    if streaming:
        def wrapped(request, context):
            if FAULTS.active and FAULTS.take("rpc_unavailable", match=name):
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              f"injected fault: rpc_unavailable on {name}")
            tokens = 0
            for resp in fn(request, context):
                yield resp
                if FAULTS.active:
                    tokens += len(getattr(resp, "token_ids", ()) or ()) or 1
                    kill = FAULTS.value("kill_backend_after_tokens")
                    if kill is not None and tokens >= int(kill):
                        FAULTS.take("kill_backend_after_tokens")
                        _log.warning(
                            "injected fault: killing backend after %d "
                            "streamed tokens", tokens)
                        import os

                        os._exit(17)
    else:
        def wrapped(request, context):
            if FAULTS.active and FAULTS.take("rpc_unavailable", match=name):
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              f"injected fault: rpc_unavailable on {name}")
            return fn(request, context)
    return wrapped


def make_server(servicer: BackendServicer, addr: str, max_workers: int = 16,
                options: Optional[list] = None) -> grpc.Server:
    """Build (not start) a grpc server for the contract bound to addr."""
    handlers = {}
    for name, (req_cls, resp_cls, streaming) in METHODS.items():
        fn = _inject_faults(name, getattr(servicer, name), streaming)
        if streaming:
            h = grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)
        else:
            h = grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)
        handlers[name] = h
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=options or [
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    # add_insecure_port returns 0 on bind failure WITHOUT raising; an
    # unchecked 0 surfaces later as an opaque connect timeout. Raising
    # here makes the free_port() -> bind race a deterministic message the
    # spawn-side retry (modelmgr/process.py) can detect in the log tail.
    if server.add_insecure_port(addr) == 0:
        raise RuntimeError(f"could not bind {addr}: address already in use")
    return server


class BackendClient:
    """Typed client over one reusable channel.

    `parallel=False` serializes Predict* calls with a lock, matching the
    reference's opMutex behavior for backends that cannot batch
    (pkg/grpc/client.go:15-22).
    """

    def __init__(self, addr: str, parallel: bool = True):
        self.addr = addr
        self.parallel = parallel
        self._lock = threading.Lock()
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                ("grpc.max_receive_message_length", 64 * 1024 * 1024),
                ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ],
        )
        self._stubs = {}
        for name, (req_cls, resp_cls, streaming) in METHODS.items():
            path = f"/{SERVICE}/{name}"
            if streaming:
                self._stubs[name] = self._channel.unary_stream(
                    path, request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString)
            else:
                self._stubs[name] = self._channel.unary_unary(
                    path, request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString)

    def close(self):
        self._channel.close()

    def _maybe_locked(self):
        class _NullCtx:
            def __enter__(self): return None
            def __exit__(self, *a): return False
        return self._lock if not self.parallel else _NullCtx()

    def _retry_unary(self, name: str, req, timeout: float,
                     attempts: int = 3, base_delay: float = 0.05):
        """Call an IDEMPOTENT unary RPC, retrying on UNAVAILABLE with
        exponential delay (ISSUE 7 crash recovery): a one-packet blip or
        a backend mid-respawn should cost a retry, not a client error.
        Only read-only/stateless methods route through here — Predict*
        may have produced tokens before dying and must never re-run
        implicitly."""
        delay = base_delay
        for attempt in range(attempts):
            try:
                return self._stubs[name](req, timeout=timeout)
            except grpc.RpcError as e:
                code = e.code() if callable(getattr(e, "code", None)) else None
                if code != grpc.StatusCode.UNAVAILABLE \
                        or attempt == attempts - 1:
                    raise
                _log.warning("%s UNAVAILABLE (attempt %d/%d), retrying in "
                             "%.2fs", name, attempt + 1, attempts, delay)
                time.sleep(delay)
                delay *= 2

    # --- typed wrappers ---
    def health(self, timeout: float = 5.0) -> bool:
        # wait_for_ready rides out gRPC's reconnect backoff while a spawned
        # backend is still importing — without it, fail-fast probes and the
        # backoff schedule can interleave so health never observes readiness.
        try:
            r = self._stubs["Health"](pb.HealthMessage(), timeout=timeout,
                                      wait_for_ready=True)
            return r.message == b"OK"
        except grpc.RpcError:
            return False

    def load_model(self, opts: pb.ModelOptions, timeout: float = 900.0) -> pb.Result:
        return self._stubs["LoadModel"](opts, timeout=timeout)

    def predict(self, opts: pb.PredictOptions, timeout: float = 600.0,
                metadata=None) -> pb.Reply:
        # per-request scheduling hints (e.g. ("localai-priority", "high"))
        # ride invocation metadata: the compiled descriptor cannot grow
        # PredictOptions fields (ISSUE 10)
        with self._maybe_locked():
            return self._stubs["Predict"](opts, timeout=timeout,
                                          metadata=metadata)

    def predict_stream(self, opts: pb.PredictOptions, timeout: float = 600.0,
                       metadata=None) -> Iterator[pb.Reply]:
        with self._maybe_locked():
            yield from self._stubs["PredictStream"](opts, timeout=timeout,
                                                    metadata=metadata)

    def embedding(self, opts: pb.PredictOptions, timeout: float = 120.0) -> pb.EmbeddingResult:
        return self._retry_unary("Embedding", opts, timeout)

    def tokenize(self, opts: pb.PredictOptions, timeout: float = 60.0) -> pb.TokenizationResponse:
        return self._retry_unary("TokenizeString", opts, timeout)

    def generate_image(self, req: pb.GenerateImageRequest, timeout: float = 600.0) -> pb.Result:
        return self._stubs["GenerateImage"](req, timeout=timeout)

    def tts(self, req: pb.TTSRequest, timeout: float = 600.0) -> pb.Result:
        return self._stubs["TTS"](req, timeout=timeout)

    def sound_generation(self, req: pb.SoundGenerationRequest, timeout: float = 600.0) -> pb.Result:
        return self._stubs["SoundGeneration"](req, timeout=timeout)

    def transcribe(self, req: pb.TranscriptRequest, timeout: float = 600.0) -> pb.TranscriptResult:
        return self._stubs["AudioTranscription"](req, timeout=timeout)

    def rerank(self, req: pb.RerankRequest, timeout: float = 120.0) -> pb.RerankResult:
        return self._retry_unary("Rerank", req, timeout)

    def status(self, timeout: float = 10.0) -> pb.StatusResponse:
        return self._stubs["Status"](pb.HealthMessage(), timeout=timeout)

    def get_metrics(self, timeout: float = 10.0) -> pb.MetricsResponse:
        return self._retry_unary("GetMetrics", pb.MetricsRequest(),
                                 timeout)

    def get_trace(self, timeout: float = 10.0) -> pb.Reply:
        """Chrome trace-event JSON of the engine's span ring (UTF-8 in
        Reply.message)."""
        return self._stubs["GetTrace"](pb.MetricsRequest(), timeout=timeout)

    def get_state(self, timeout: float = 10.0) -> pb.Reply:
        """Live engine-state + event-log ring snapshot (JSON in
        Reply.message, ISSUE 8). Read-only — safe to retry."""
        return self._retry_unary("GetState", pb.MetricsRequest(), timeout)

    def profile(self, seconds: float, timeout: float = 120.0) -> pb.Result:
        """Capture a jax.profiler trace for `seconds`; Result.message is
        the directory holding the capture."""
        import json

        opts = pb.PredictOptions(prompt=json.dumps({"seconds": seconds}))
        return self._stubs["Profile"](opts, timeout=timeout)

    def stores_set(self, req: pb.StoresSetOptions, timeout: float = 60.0) -> pb.Result:
        return self._stubs["StoresSet"](req, timeout=timeout)

    def stores_delete(self, req: pb.StoresDeleteOptions, timeout: float = 60.0) -> pb.Result:
        return self._stubs["StoresDelete"](req, timeout=timeout)

    def stores_get(self, req: pb.StoresGetOptions, timeout: float = 60.0) -> pb.StoresGetResult:
        return self._stubs["StoresGet"](req, timeout=timeout)

    def stores_find(self, req: pb.StoresFindOptions, timeout: float = 60.0) -> pb.StoresFindResult:
        return self._stubs["StoresFind"](req, timeout=timeout)
