"""Image-generation backend: diffusion UNet + DDIM on TPU.

Capability parity with the reference's diffusers backend (reference:
backend/python/diffusers/backend.py:1-510 — GenerateImage RPC: prompt,
negative prompt, steps, seed, cfg scale, width/height, dst file; also the
NCNN stable-diffusion wrappers backend/go/image/stablediffusion/). The
sampler renders at the model's native size and rescales to the requested
width/height when they differ.
"""

from __future__ import annotations

import argparse
import logging
import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.diffusion_runner")


class DiffusionServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        # diffusers-layout pipeline (SD-class: unet/ + vae/ + text_encoder/)
        self.sd_pipe = None
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        try:
            import jax

            from localai_tpu.models import diffusion

            model_dir = request.model
            if request.model_path and model_dir and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            with self._lock:   # no torn state visible to GenerateImage
                self.sd_pipe = None
                # model-level default scheduler (model YAML `scheduler:`)
                self.scheduler = request.scheduler or "ddim"
                if model_dir and os.path.isdir(os.path.join(model_dir, "unet")):
                    # diffusers pipeline directory (reference:
                    # backend/python/diffusers/backend.py LoadModel, incl.
                    # ControlNet attach + LoRA fuse-at-load :297-314)
                    from localai_tpu.models import sd

                    from localai_tpu.backend.service import parse_options

                    extra = parse_options(request.options)
                    # video knobs (num_frames/fps/motion) ride the same
                    # options wire; GenerateImage reads them per model
                    self.extra = extra
                    loras = []
                    if request.lora_adapter:
                        lp = request.lora_adapter
                        if request.model_path and not os.path.isabs(lp):
                            lp = os.path.join(request.model_path, lp)
                        loras.append(lp)
                    self.sd_pipe = sd.SDPipeline.load(
                        model_dir,
                        controlnet=extra.get("controlnet", ""),
                        lora_paths=tuple(loras),
                        lora_scale=request.lora_scale or 1.0)
                    self.cfg = diffusion.DiffusionConfig()
                    self.params = self.sd_pipe.unet
                elif model_dir and os.path.exists(
                        os.path.join(model_dir, "config.json")):
                    self.cfg = diffusion.DiffusionConfig.from_json(
                        os.path.join(model_dir, "config.json"))
                    self.params = diffusion.load_params(model_dir, self.cfg)
                else:
                    self.cfg = diffusion.DiffusionConfig()
                    self.params = diffusion.init_params(
                        self.cfg, jax.random.PRNGKey(0))
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def GenerateImage(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import diffusion

        try:
            with self._lock:
                if self.sd_pipe is not None:
                    # SD-class pipeline renders at the requested size
                    # (rounded to the VAE factor inside txt2img)
                    w = request.width or 512
                    h = request.height or 512
                    scheduler = (request.scheduler
                                 or getattr(self, "scheduler", "")
                                 or "ddim")
                    if request.mode in ("txt2vid", "img2vid"):
                        # video generation (reference: diffusers
                        # backend.py:199-223,440-453 — img2vid from a src
                        # image, txt2vid from the prompt, video file at
                        # dst). Frame count rides the options wire
                        # (num_frames=), fps likewise.
                        from localai_tpu.models import sd as sdlib

                        extra = getattr(self, "extra", {}) or {}
                        frames_n = int(extra.get("num_frames", 14) or 14)
                        fps = int(extra.get("fps", 7) or 7)
                        motion = float(extra.get("motion", 1.0) or 1.0)
                        common = dict(
                            negative_prompt=request.negative_prompt,
                            num_frames=frames_n,
                            steps=request.step or 20,
                            cfg_scale=float(request.cfg_scale or 7),
                            seed=request.seed, scheduler=scheduler,
                            motion=motion)
                        if request.mode == "img2vid":
                            if not request.src:
                                return pb.Result(
                                    success=False,
                                    message="img2vid needs a source image "
                                            "(src)")
                            from PIL import Image

                            init = np.asarray(Image.open(request.src)
                                              .convert("RGB"))
                            strength = (float(request.strength)
                                        if request.HasField("strength")
                                        else 0.5)
                            frames = self.sd_pipe.img2vid(
                                init, prompt=request.positive_prompt,
                                strength=strength, **common)
                        else:
                            frames = self.sd_pipe.txt2vid(
                                request.positive_prompt, height=h, width=w,
                                **common)
                        os.makedirs(os.path.dirname(request.dst) or ".",
                                    exist_ok=True)
                        sdlib.write_video(request.dst, frames, fps=fps)
                        return pb.Result(success=True, message="ok")
                    if request.src and request.mode == "controlnet":
                        # src is the CONTROL image (canny/pose map), not
                        # an init image: structure-conditioned txt2img
                        # (reference: diffusers backend.py:297-314)
                        from PIL import Image

                        ctrl = np.asarray(Image.open(request.src)
                                          .convert("RGB"))
                        img = self.sd_pipe.txt2img(
                            request.positive_prompt,
                            negative_prompt=request.negative_prompt,
                            height=h, width=w,
                            steps=request.step or 20,
                            cfg_scale=float(request.cfg_scale or 7),
                            seed=request.seed, scheduler=scheduler,
                            control_image=ctrl)
                    elif request.src:
                        # img2img (reference: diffusers backend
                        # backend.py:399-424 — src image + strength)
                        from PIL import Image

                        init = np.asarray(Image.open(request.src)
                                          .convert("RGB"))
                        strength = (float(request.strength)
                                    if request.HasField("strength") else 0.75)
                        img = self.sd_pipe.img2img(
                            request.positive_prompt, init,
                            negative_prompt=request.negative_prompt,
                            strength=strength,
                            steps=request.step or 20,
                            cfg_scale=float(request.cfg_scale or 7),
                            seed=request.seed, scheduler=scheduler)
                        # requested size still applies (resized below,
                        # like the other branches); default = init size
                        h = request.height or img.shape[0]
                        w = request.width or img.shape[1]
                    else:
                        img = self.sd_pipe.txt2img(
                            request.positive_prompt,
                            negative_prompt=request.negative_prompt,
                            height=h, width=w,
                            steps=request.step or 20,
                            cfg_scale=float(request.cfg_scale or 7),
                            seed=request.seed, scheduler=scheduler)
                else:
                    if request.src or request.scheduler or \
                            request.HasField("strength"):
                        # these are diffusers-pipeline features; silently
                        # returning an unrelated txt2img would be worse
                        return pb.Result(
                            success=False,
                            message="img2img/scheduler/strength require a "
                                    "diffusers pipeline directory")
                    img = diffusion.ddim_sample(
                        self.params, self.cfg,
                        prompt=request.positive_prompt,
                        negative_prompt=request.negative_prompt,
                        steps=request.step or 20,
                        seed=request.seed,
                        guidance=float(request.cfg_scale or 7),
                    )
                    w = request.width or self.cfg.image_size
                    h = request.height or self.cfg.image_size
            from PIL import Image

            im = Image.fromarray(img)
            if (w, h) != im.size:
                im = im.resize((w, h), Image.BICUBIC)
            os.makedirs(os.path.dirname(request.dst) or ".", exist_ok=True)
            im.save(request.dst)
            return pb.Result(success=True, message="ok")
        except Exception as e:
            log.exception("GenerateImage failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def Status(self, request, context):
        state = pb.StatusResponse.READY if self.params is not None else \
            pb.StatusResponse.UNINITIALIZED
        return pb.StatusResponse(state=state, memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    servicer = DiffusionServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("diffusion backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
