"""Rerank backend: BERT cross-encoder scoring on TPU.

Capability parity with the reference's reranker backend (reference:
backend/python/rerankers/backend.py:1-123 — jina-compatible Rerank RPC:
query + documents -> DocumentResult{index, text, relevance_score} sorted
by score, with Usage token accounting). TPU-first: all (query, document)
pairs are scored in ONE bucketed batch through the jitted cross-encoder
instead of the reference's per-pair python loop.
"""

from __future__ import annotations

import argparse
import logging
import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.rerank_runner")

_BUCKETS = (64, 128, 256, 512)
_PAIR_BATCH = 16  # pairs per jitted call (padded; one compile per bucket)


class RerankServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        self.tokenizer = None
        self._fns = {}
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        try:
            from localai_tpu.models import bert

            model_dir = request.model
            if request.model_path and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            self.cfg = bert.BertConfig.from_json(os.path.join(model_dir, "config.json"))
            self.params = bert.load_hf_cross_params(model_dir, self.cfg)
            self._fns.clear()

            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(request.tokenizer or model_dir)
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _score_fn(self, bucket: int):
        fn = self._fns.get(bucket)
        if fn is None:
            import jax

            from localai_tpu.models import bert

            fn = jax.jit(lambda p, t, m, ty: bert.cross_score(p, self.cfg, t, m, ty))
            self._fns[bucket] = fn
        return fn

    def Rerank(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        if not request.documents:
            return pb.RerankResult(usage=pb.Usage())

        max_len = min(self.cfg.max_position_embeddings, _BUCKETS[-1])
        enc = self.tokenizer(
            [request.query] * len(request.documents),
            list(request.documents),
            truncation=True, max_length=max_len, padding=False,
        )
        total_tokens = sum(len(x) for x in enc["input_ids"])
        longest = max(len(x) for x in enc["input_ids"])
        bucket = next((b for b in _BUCKETS if longest <= b), _BUCKETS[-1])

        n = len(request.documents)
        scores = np.zeros((n,), np.float32)
        with self._lock:
            for off in range(0, n, _PAIR_BATCH):
                chunk = min(_PAIR_BATCH, n - off)
                tokens = np.zeros((_PAIR_BATCH, bucket), np.int32)
                mask = np.zeros((_PAIR_BATCH, bucket), bool)
                types = np.zeros((_PAIR_BATCH, bucket), np.int32)
                for b in range(chunk):
                    ids = enc["input_ids"][off + b][:bucket]
                    tokens[b, : len(ids)] = ids
                    mask[b, : len(ids)] = True
                    ty = enc.get("token_type_ids")
                    if ty is not None:
                        types[b, : len(ids)] = ty[off + b][:bucket]
                out = self._score_fn(bucket)(self.params, tokens, mask, types)
                scores[off:off + chunk] = np.asarray(out)[:chunk]

        order = np.argsort(-scores)
        top_n = request.top_n or n
        results = [
            pb.DocumentResult(
                index=int(i),
                text=request.documents[int(i)],
                relevance_score=float(scores[int(i)]),
            )
            for i in order[:top_n]
        ]
        return pb.RerankResult(
            usage=pb.Usage(total_tokens=total_tokens, prompt_tokens=total_tokens),
            results=results,
        )

    def Status(self, request, context):
        state = pb.StatusResponse.READY if self.params is not None else \
            pb.StatusResponse.UNINITIALIZED
        return pb.StatusResponse(state=state, memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    servicer = RerankServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("rerank backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
