"""Vector store backend: columnar keys + brute-force cosine top-K.

Parity with the reference's local-store (reference: backend/go/stores/
store.go:17-99,300+ — sorted columnar keys/values, normalization tracking,
cosine/dot top-K). TPU re-design: keys live in one contiguous numpy matrix
(jnp on device when large) so top-K is a single matmul + argpartition
instead of a per-key loop.

Run: python -m localai_tpu.backend.store_backend --addr 127.0.0.1:PORT
(or embedded via ModelLoader.register_embedded("local-store", StoreServicer)).
"""

from __future__ import annotations

import threading

import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server


class StoreServicer(BackendServicer):
    def __init__(self):
        self._lock = threading.Lock()
        self._keys = np.zeros((0, 0), np.float32)   # [N, D]
        self._norms = np.zeros((0,), np.float32)
        self._values: list[bytes] = []
        self._index: dict[tuple, int] = {}

    def LoadModel(self, request, context):
        return pb.Result(success=True, message="store ready")

    def StoresSet(self, request, context):
        with self._lock:
            for k, v in zip(request.keys, request.values):
                key = np.asarray(k.floats, np.float32)
                t = tuple(key.tolist())
                if self._keys.size == 0:
                    self._keys = key[None, :]
                    self._norms = np.array([np.linalg.norm(key)], np.float32)
                    self._values = [bytes(v.bytes)]
                    self._index = {t: 0}
                    continue
                if key.shape[0] != self._keys.shape[1]:
                    context.abort(3, f"key dim {key.shape[0]} != store dim {self._keys.shape[1]}")
                idx = self._index.get(t)
                if idx is not None:
                    self._values[idx] = bytes(v.bytes)
                else:
                    self._index[t] = len(self._values)
                    self._keys = np.vstack([self._keys, key[None, :]])
                    self._norms = np.append(self._norms, np.linalg.norm(key))
                    self._values.append(bytes(v.bytes))
        return pb.Result(success=True)

    def StoresDelete(self, request, context):
        with self._lock:
            drop = set()
            for k in request.keys:
                t = tuple(np.asarray(k.floats, np.float32).tolist())
                if t in self._index:
                    drop.add(self._index.pop(t))
            if drop:
                keep = [i for i in range(len(self._values)) if i not in drop]
                self._keys = self._keys[keep] if keep else np.zeros((0, 0), np.float32)
                self._norms = self._norms[keep] if keep else np.zeros((0,), np.float32)
                self._values = [self._values[i] for i in keep]
                self._index = {tuple(self._keys[j].tolist()): j for j in range(len(keep))}
        return pb.Result(success=True)

    def StoresGet(self, request, context):
        keys, values = [], []
        with self._lock:
            for k in request.keys:
                t = tuple(np.asarray(k.floats, np.float32).tolist())
                idx = self._index.get(t)
                if idx is not None:
                    keys.append(pb.StoresKey(floats=list(t)))
                    values.append(pb.StoresValue(bytes=self._values[idx]))
        return pb.StoresGetResult(keys=keys, values=values)

    def StoresFind(self, request, context):
        q = np.asarray(request.key.floats, np.float32)
        top_k = request.top_k or 10
        with self._lock:
            if len(self._values) == 0:
                return pb.StoresFindResult()
            if q.shape[0] != self._keys.shape[1]:
                context.abort(3, f"key dim {q.shape[0]} != store dim {self._keys.shape[1]}")
            # cosine when norms differ; dot product when all unit (reference
            # tracks normalization to pick the metric, store.go:48-99)
            dots = self._keys @ q
            qn = np.linalg.norm(q)
            all_unit = np.allclose(self._norms, 1.0, atol=1e-3) and abs(qn - 1.0) < 1e-3
            if all_unit:
                sims = dots
            else:
                sims = dots / np.maximum(self._norms * qn, 1e-12)
            k = min(top_k, len(self._values))
            idx = np.argpartition(-sims, k - 1)[:k]
            idx = idx[np.argsort(-sims[idx])]
            return pb.StoresFindResult(
                keys=[pb.StoresKey(floats=self._keys[i].tolist()) for i in idx],
                values=[pb.StoresValue(bytes=self._values[i]) for i in idx],
                similarities=[float(sims[i]) for i in idx],
            )


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    args = parser.parse_args(argv)
    server = make_server(StoreServicer(), args.addr)
    server.start()
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
