"""The TPU engine served over the backend contract.

This is the process spawned per model by the model manager — the analogue
of the reference's llama.cpp gRPC server binary (reference:
backend/cpp/llama/grpc-server.cpp:2503-2541 main, --addr flag), with the
slot machinery replaced by localai_tpu.engine.

Run: python -m localai_tpu.backend.runner --addr 127.0.0.1:PORT
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Optional

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import (BackendServicer, make_server,
                                         parse_options)

log = logging.getLogger("localai_tpu.backend.runner")

# engine lifecycle failure kinds -> gRPC status codes, so the core can
# distinguish shed (retry later) from timeout from stall without parsing
# message strings (services/errors.py maps them back to HTTP 429/504/503)
_EVENT_STATUS = {
    "shed": grpc.StatusCode.RESOURCE_EXHAUSTED,
    "timeout": grpc.StatusCode.DEADLINE_EXCEEDED,
    "stall": grpc.StatusCode.ABORTED,
}


def _abort_event(context, ev):
    """Abort the RPC for an engine error event with the kind-mapped
    status code; the engine's Retry-After hint rides trailing metadata
    (the hand-rolled stubs cannot grow proto fields)."""
    if ev.retry_after_s:
        context.set_trailing_metadata(
            (("localai-retry-after", f"{ev.retry_after_s:g}"),))
    context.abort(_EVENT_STATUS.get(ev.error_kind, grpc.StatusCode.INTERNAL),
                  ev.error)


def _sampling_from_predict(opts: pb.PredictOptions):
    from localai_tpu.engine.sampling import SamplingParamsHost

    return SamplingParamsHost(
        temperature=opts.temperature,
        top_k=opts.top_k,
        top_p=opts.top_p if opts.top_p > 0 else 1.0,
        min_p=opts.min_p,
        typical_p=opts.typical_p if opts.typical_p > 0 else 1.0,
        repeat_penalty=opts.repeat_penalty if opts.repeat_penalty > 0 else 1.0,
        # llama.cpp semantics: -1 = whole context (capped at the ring size
        # here), 0/unset = default 64 (proto3 cannot distinguish explicit 0)
        repeat_last_n=(opts.repeat_last_n if opts.repeat_last_n > 0
                       else -1 if opts.repeat_last_n < 0 else 64),
        presence_penalty=opts.presence_penalty,
        frequency_penalty=opts.frequency_penalty,
        mirostat=opts.mirostat,
        mirostat_tau=opts.mirostat_tau or 5.0,
        mirostat_eta=opts.mirostat_eta or 0.1,
        seed=opts.seed if opts.seed != 0 else -1,
        logit_bias={int(k): float(v) for k, v in opts.logit_bias.items()},
    )


class EngineServicer(BackendServicer):
    """LLM serving: LoadModel/Predict/PredictStream/Embedding/Tokenize/
    Status/GetMetrics on top of the continuous-batching engine."""

    def __init__(self):
        self.engine = None
        self.tokenizer = None
        self.model_cfg = None
        self.vision = None
        self.vision_cfg = None
        self.model_path = ""       # base dir for relative prompt-cache paths
        self._state = pb.StatusResponse.UNINITIALIZED
        self._load_lock = threading.Lock()
        self._embed = False
        self.kv_server = None      # ISSUE 17: KVWireServer when kv_serve=
        self.kv_fed = None         # ISSUE 17: FederatedKV when kv_peers=

    @staticmethod
    def _host_store_path(extra: dict, request) -> str:
        """kv_host_store=path option -> absolute persistence path for the
        offloaded-page store (engine/kv_offload.py); relative paths land
        next to the prompt caches under model_path."""
        p = str(extra.get("kv_host_store", "") or "")
        if not p:
            return ""
        if not os.path.isabs(p) and request.model_path:
            base = os.path.join(request.model_path, "prompt_cache")
            os.makedirs(base, exist_ok=True)
            p = os.path.join(base, p)
        return p

    @staticmethod
    def _sane_ga_w(extra: dict) -> int:
        n = max(1, int(extra.get("ga_n", 1) or 1))
        w = int(extra.get("ga_w", 512) or 512)
        w = max(w, n)
        return w - (w % n)   # divisible window: no shared block boundaries

    # ---- lifecycle ----

    def LoadModel(self, request: pb.ModelOptions, context) -> pb.Result:
        with self._load_lock:
            try:
                self._load(request)
                self._state = pb.StatusResponse.READY
                # clock handshake (ISSUE 12): Result.message carries this
                # process's wall/monotonic clocks and the tracer epoch so
                # the loader can measure the cross-process clock offset
                # that aligns merged /debug/trace timelines. The loader
                # tolerates a plain "loaded" from backends that don't
                # participate (fakes, external bridges).
                hs = {"status": "loaded",
                      "handshake": {
                          "wall": time.time(),
                          "mono": time.monotonic(),
                          "trace_epoch": self.engine.tracer.t0_epoch,
                          "pid": os.getpid()}}
                return pb.Result(success=True, message=json.dumps(hs))
            except Exception as e:  # surface the error to the core
                self._state = pb.StatusResponse.ERROR
                log.exception("LoadModel failed")
                return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _load(self, request: pb.ModelOptions):
        import jax
        import jax.numpy as jnp

        from localai_tpu.engine import engine as eng
        from localai_tpu.engine import weights
        from localai_tpu.models import llama
        from localai_tpu.parallel import mesh as meshlib
        from localai_tpu.parallel import sharding as shardlib

        model_dir = request.model
        if request.model_path and not os.path.isabs(model_dir):
            model_dir = os.path.join(request.model_path, model_dir)
        dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}.get(
            request.dtype or "bfloat16", jnp.bfloat16
        )
        gguf_path = weights.find_gguf(model_dir)
        family = None
        if gguf_path is not None:
            # GGUF checkpoint (ollama://, oci:// or gallery pull): config
            # and tokenizer come from the file's own metadata
            from localai_tpu.engine import gguf as gguflib

            cfg = dataclasses.replace(
                gguflib.config_from_gguf(gguflib.open_gguf(gguf_path)),
                dtype=dtype)
        else:
            cfg_path = os.path.join(model_dir, "config.json")
            with open(cfg_path) as f:
                cfg_dict = json.load(f)
            mtype = cfg_dict.get("model_type", "")
            if mtype in ("mamba", "rwkv"):
                # non-attention LLM families (reference: backend/python/
                # mamba selective-scan SSM; backend/go/llm/rwkv/rwkv.go
                # linear-attention RWKV): fixed-size recurrent state rides
                # the same engine slot lanes via the family adapter
                if mtype == "mamba":
                    from localai_tpu.models import mamba as family

                    cfg = family.MambaConfig.from_hf_config(cfg_dict,
                                                            dtype=dtype)
                else:
                    from localai_tpu.models import rwkv as family

                    cfg = family.RwkvConfig.from_hf_config(cfg_dict,
                                                           dtype=dtype)
                if request.lora_adapter:
                    raise ValueError("LoRA adapters are llama-family only")
                if request.draft_model:
                    raise ValueError(
                        "speculative draft models are llama-family only")
                if "ga_n" in (request.options or ""):
                    raise ValueError(
                        "self-extend (group_attn_n) is llama-family only")
                if request.quantization not in ("", "int8"):
                    # unknown schemes must fail loudly (and fast, before
                    # the weight load): silently serving full-precision
                    # weights would fake the memory savings
                    raise ValueError(
                        f"quantization={request.quantization!r} is not "
                        f"supported for {mtype} (only weight-only int8)")
            else:
                cfg = llama.LlamaConfig.from_hf_config(cfg_dict, dtype=dtype)

        # kv_cache_dtype (YAML -> capabilities.py:31 -> here): the memory
        # knob that buys batch — int8 KV halves the cache so slot count
        # can double on a bandwidth-bound chip (reference analogue:
        # llama.cpp cache-type-k q8_0 / vLLM kv_cache_dtype,
        # /root/reference/backend/python/vllm/backend.py:92-111).
        # Validated BEFORE the weight load so a bad knob fails fast.
        from localai_tpu.config.model_config import KV_CACHE_DTYPES

        kv_dt_name = (request.kv_cache_dtype or "bfloat16").lower()
        kv_dt_map = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                     "float16": jnp.float16, "f16": jnp.float16,
                     "float32": jnp.float32, "f32": jnp.float32,
                     "int8": jnp.int8, "q8_0": jnp.int8}
        assert set(kv_dt_map) == set(KV_CACHE_DTYPES)  # schema <-> runner sync
        if kv_dt_name not in kv_dt_map:
            raise ValueError(
                f"unknown kv_cache_dtype {kv_dt_name!r} "
                f"(one of {sorted(kv_dt_map)})")
        cache_dtype = kv_dt_map[kv_dt_name]
        if family is not None and cache_dtype == jnp.int8:
            # mamba/rwkv cache lanes hold recurrent STATE, not KV rows;
            # quantizing recurrent state accumulates error every step
            raise ValueError(
                f"kv_cache_dtype {kv_dt_name!r} is llama-family only "
                f"(mamba/rwkv cache lanes carry recurrent state, kept "
                f"fp32); float dtypes are accepted as no-ops for these "
                f"families")
        if family is not None:
            # float kv_cache_dtype values are NO-OPS for recurrent-state
            # families (their init_cache pins fp32 — SSM/wkv recurrences
            # are precision-sensitive) and the YAML validator accepts
            # them for any family: accept rather than fail a valid
            # config at load time (ADVICE r5, runner.py:201)
            cache_dtype = jnp.bfloat16

        n_dev = len(jax.devices())
        tp = request.mesh_tp or n_dev
        dp = request.mesh_dp or 1
        mesh = None
        if tp * dp > 1:
            mesh = meshlib.make_mesh(meshlib.MeshPlan(dp=dp, tp=tp),
                                     devices=jax.devices()[: tp * dp])
        lora_dir = request.lora_adapter
        if lora_dir and request.model_path and not os.path.isabs(lora_dir):
            lora_dir = os.path.join(request.model_path, lora_dir)
        # parsed BEFORE the weight load: weight_prefetch=1 swaps the
        # loader itself (ISSUE 19)
        extra = parse_options(request.options)
        stream_load = str(extra.get("weight_prefetch", "")
                          ).strip().lower() in ("1", "true", "on", "yes")
        stream_auto = str(extra.get("autoscale", "")
                          ).strip().lower() in ("1", "true", "on", "yes")
        self.weight_stream_stats = None
        if family is not None:
            params = family.load_hf_params(model_dir, cfg, dtype=dtype)
            # r5 (VERDICT r4 #7): mamba is no longer a single-chip
            # second-class citizen — weight-only int8 of the mixer
            # projections and Megatron-style tp over d_inner
            if request.quantization == "int8" or request.dtype == "int8":
                params = family.quantize_params(params)
            if mesh is not None and mtype == "mamba":
                from jax.sharding import PartitionSpec as P

                from localai_tpu.parallel import sharding as shardlib

                tp_size = mesh.shape.get("tp", 1)
                if tp_size > 1 and cfg.d_inner % tp_size == 0:
                    specs = shardlib.mamba_param_specs(
                        cfg.tie_word_embeddings)
                    if cfg.vocab_size % tp_size:
                        specs["embed"] = P(None, None)
                        if "lm_head" in specs:
                            specs["lm_head"] = P(None, None)
                    params = shardlib.shard_params(mesh, params, specs=specs)
        elif stream_load:
            # leaf-at-a-time streaming load (ISSUE 19): bounded host-RAM
            # chunks + per-leaf yields, so siblings serving in this
            # process keep their cadence while a swap/scale-out loads
            params, self.weight_stream_stats = weights.stream_llama_params(
                model_dir, cfg, mesh=mesh, dtype=dtype,
                quantize=request.quantization or
                ("int8" if request.dtype == "int8" else ""),
                lora_adapter=lora_dir, lora_scale=request.lora_scale or 1.0)
            log.info("streamed weight load: %d leaves, %.1f MB, %.0f ms",
                     self.weight_stream_stats["leaves"],
                     self.weight_stream_stats["bytes"] / 1e6,
                     self.weight_stream_stats["ms"])
        else:
            params = weights.load_llama_params(
                model_dir, cfg, mesh=mesh, dtype=dtype,
                quantize=request.quantization or
                ("int8" if request.dtype == "int8" else ""),
                lora_adapter=lora_dir, lora_scale=request.lora_scale or 1.0)

        if gguf_path is not None and not request.tokenizer:
            from localai_tpu.engine import gguf_tokenizer

            self.tokenizer = gguf_tokenizer.from_gguf(gguf_path)
        else:
            from transformers import AutoTokenizer

            tok_dir = request.tokenizer or model_dir
            self.tokenizer = AutoTokenizer.from_pretrained(tok_dir)

        ecfg = eng.EngineConfig(
            num_slots=request.num_slots or 8,
            max_context=request.context_size or min(cfg.max_position_embeddings, 4096),
            prefill_buckets=tuple(request.prefill_buckets) or (32, 128, 512, 2048),
            cache_dtype=cache_dtype,
            # self-extend (model YAML group_attn_n/group_attn_w via the
            # options k=v escape hatch, reference backend.proto Options).
            # Sanitized here too: external gRPC clients bypass the YAML
            # validator, and ga_w=0 or non-divisible windows would crash
            # or degrade the engine loop.
            ga_n=max(1, int(extra.get("ga_n", 1) or 1)),
            ga_w=self._sane_ga_w(extra),
            # 0 (or absent) = engine default, matching the YAML contract
            **({"decode_burst": db} if (db := int(
                extra.get("decode_burst", 0) or 0)) > 0 else {}),
            # paged-KV knobs via the options escape hatch: the engine's
            # "auto" default picks the paged layout for llama-family
            # serving; kv_layout=contiguous opts out, kv_page_size /
            # kv_pool_pages tune the pool (EngineConfig docs)
            **({"kv_layout": kl} if (kl := str(
                extra.get("kv_layout", "") or "")) in
               ("paged", "contiguous") else {}),
            **({"kv_page_size": kp} if (kp := int(
                extra.get("kv_page_size", 0) or 0)) > 0 else {}),
            **({"kv_pool_pages": kpp} if (kpp := int(
                extra.get("kv_pool_pages", 0) or 0)) > 0 else {}),
            # cross-release prefix cache (PR 2): kv_prefix_cache=0 opts
            # out (restores PR-1 lifecycle exactly);
            # kv_prefix_cache_min_rows guards short accidental matches
            **({"kv_prefix_cache": False} if str(
                extra.get("kv_prefix_cache", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"kv_prefix_cache_min_rows": mr} if (mr := int(
                extra.get("kv_prefix_cache_min_rows", 0) or 0)) > 0
               else {}),
            # two-tier host offload (PR 3): kv_offload=0 opts out
            # (restores the PR-2 lifecycle exactly); kv_host_pool_mb
            # bounds the host tier; kv_host_store=path persists it
            # across restarts (relative paths resolve under model_path)
            **({"kv_offload": False} if str(
                extra.get("kv_offload", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"kv_host_pool_mb": hmb} if (hmb := int(
                extra.get("kv_host_pool_mb", 0) or 0)) > 0 else {}),
            **({"kv_host_store_path": hsp} if (hsp := self._host_store_path(
                extra, request)) else {}),
            # KV lifecycle auditor (ISSUE 15): off = zero-cost no-op,
            # on = report-only scans (default), strict = raise
            **({"kv_audit": ka} if (ka := str(
                extra.get("kv_audit", "") or "")) in
               ("off", "on", "strict") else {}),
            # long-context serving tier (ISSUE 16): kv_window_pages
            # bounds the on-device working set (0 = off, the default);
            # kv_sink_pages pins attention-sink head pages on device;
            # kv_window_policy picks what happens to cold middle pages
            # (demote to host / drop); kv_prefetch_ahead sets the
            # decode-time restore pipeline depth (explicit 0 disables
            # prefetch, so isdigit passes it through)
            **({"kv_window_pages": wp} if (wp := int(
                extra.get("kv_window_pages", 0) or 0)) > 0 else {}),
            **({"kv_sink_pages": int(v)} if (v := str(
                extra.get("kv_sink_pages", "")).strip()).isdigit()
               else {}),
            **({"kv_window_policy": wpol} if (wpol := str(
                extra.get("kv_window_policy", "") or "")) in
               ("demote", "drop") else {}),
            **({"kv_prefetch_ahead": int(v)} if (v := str(
                extra.get("kv_prefetch_ahead", "")).strip()).isdigit()
               else {}),
            # ragged packed prefill (this PR): prefill_packed=0 opts
            # back into the per-slot bucketed path bit-for-bit;
            # prefill_token_budget caps packed prompt tokens per
            # scheduler tick (0 = engine auto, 2x prefill_chunk)
            **({"prefill_packed": False} if str(
                extra.get("prefill_packed", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"prefill_token_budget": ptb} if (ptb := int(
                extra.get("prefill_token_budget", 0) or 0)) > 0 else {}),
            # prefill_packed_fuse=auto|0|1|split: fuse the packed step
            # with the decode burst (1 = monolithic program, split =
            # early-emit pair, auto = split everywhere)
            **({"prefill_packed_fuse": ppf} if (ppf := str(
                extra.get("prefill_packed_fuse", "") or "")) in
               ("auto", "0", "1", "split") else {}),
            # comm_overlap=auto|0|1 (ISSUE 11): TokenWeave-style halved-
            # pack overlap of per-layer collectives with compute
            # (auto = meshed backends only; bit-exact either way)
            **({"comm_overlap": cov} if (cov := str(
                extra.get("comm_overlap", "") or "")) in
               ("auto", "0", "1") else {}),
            # observability (this PR): trace=0 turns the span tracer into
            # a hot-path no-op; trace_ring_size bounds retained spans;
            # slow_request_ms logs a span decomposition when TTFT or e2e
            # exceeds the threshold
            **({"trace": False} if str(
                extra.get("trace", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"trace_ring_size": trs} if (trs := int(
                extra.get("trace_ring_size", 0) or 0)) > 0 else {}),
            **({"slow_request_ms": srm} if (srm := int(
                extra.get("slow_request_ms", 0) or 0)) > 0 else {}),
            # fault-tolerant lifecycle (ISSUE 7): admission control,
            # per-request deadlines, stall watchdog. Explicit 0 must pass
            # through (it DISABLES the bound), so these use isdigit
            # instead of the >0 idiom above.
            **({"max_queued_requests": int(v)} if (v := str(
                extra.get("max_queued_requests", "")).strip()).isdigit()
               else {}),
            **({"max_queue_wait_ms": int(v)} if (v := str(
                extra.get("max_queue_wait_ms", "")).strip()).isdigit()
               else {}),
            **({"request_timeout_ms": int(v)} if (v := str(
                extra.get("request_timeout_ms", "")).strip()).isdigit()
               else {}),
            **({"dispatch_stall_ms": int(v)} if (v := str(
                extra.get("dispatch_stall_ms", "")).strip()).isdigit()
               else {}),
            **({"stall_dump_dir": sdd} if (sdd := str(
                extra.get("stall_dump_dir", "") or "")) else {}),
            # system observability (ISSUE 8): structured event-log sink
            # (path|stderr|off) + peak device TFLOP/s for MFU accounting
            **({"event_log": evl} if (evl := str(
                extra.get("event_log", "") or "")) else {}),
            **({"peak_tflops": ptf} if (ptf := float(
                extra.get("peak_tflops", 0) or 0)) > 0 else {}),
            # event-driven hot path (ISSUE 9): emitter=0 restores in-loop
            # emission; event_log_max_mb bounds the file sink (0 disables
            # rotation, so isdigit passes the explicit 0 through)
            **({"emitter": False} if str(
                extra.get("emitter", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"event_log_max_mb": int(v)} if (v := str(
                extra.get("event_log_max_mb", "")).strip()).isdigit()
               else {}),
            # preemptive priority scheduler (ISSUE 10): preempt=0 restores
            # strict-FIFO admission bit-for-bit; priority_weights is
            # colon-separated (the options wire splits on commas);
            # priority sets the model-wide default class
            **({"preempt": False} if str(
                extra.get("preempt", "")).strip().lower() in
               ("0", "false", "off", "no") else {}),
            **({"priority_weights": pw} if (pw := str(
                extra.get("priority_weights", "") or "")) else {}),
            **({"priority": pc} if (pc := str(
                extra.get("priority", "") or "").strip().lower()) in
               ("high", "normal", "low") else {}),
            **({"max_preemptions": int(v)} if (v := str(
                extra.get("max_preemptions", "")).strip()).isdigit()
               else {}),
            **({"resume_reserve_pages": int(v)} if (v := str(
                extra.get("resume_reserve_pages", "")).strip()).isdigit()
               else {}),
            **({"priority_aging_ms": int(v)} if (v := str(
                extra.get("priority_aging_ms", "")).strip()).isdigit()
               else {}),
            # per-class SLO objectives (ISSUE 12): colon-separated
            # high:normal:low thresholds in ms (one value = all classes),
            # like priority_weights — the options wire splits on commas.
            # slo_error_budget tunes the burn-rate denominator.
            **({"slo_ttft_ms": st} if (st := str(
                extra.get("slo_ttft_ms", "") or "")) else {}),
            **({"slo_itl_ms": si} if (si := str(
                extra.get("slo_itl_ms", "") or "")) else {}),
            **({"slo_queue_wait_ms": sq} if (sq := str(
                extra.get("slo_queue_wait_ms", "") or "")) else {}),
            **({"slo_error_budget": seb} if (seb := float(
                extra.get("slo_error_budget", 0) or 0)) > 0 else {}),
            # speculative decoding (ISSUE 13): draft picks the drafter
            # (auto = model when a draft model is loaded, else n-gram
            # self-speculation; 0/off disables), n_draft sets the
            # proposal depth (explicit 0 disables, so isdigit passes it
            # through), spec_ngram the lookup n-gram length
            **({"draft": dr} if (dr := str(
                extra.get("draft", "") or "").strip().lower()) in
               ("auto", "model", "ngram", "0", "off", "none", "false")
               else {}),
            **({"n_draft": int(v)} if (v := str(
                extra.get("n_draft", "")).strip()).isdigit() else {}),
            **({"spec_ngram": sn} if (sn := int(
                extra.get("spec_ngram", 0) or 0)) > 0 else {}),
            # prefill/decode disaggregation role (ISSUE 17): "both"
            # (the default) is bit-for-bit the single-host path;
            # "prefill" retires finished prefills to the cluster
            # transport, "decode" is a routing hint
            **({"disagg": dg} if (dg := str(
                extra.get("disagg", "") or "").strip().lower()) in
               ("prefill", "decode", "both") else {}),
            # SLO-driven replica autoscaling (ISSUE 19): autoscale=0 (the
            # default) builds no policy object and no policy thread —
            # bit-for-bit the static pool path. autoscale_max=0 means
            # "twice the configured engines"; explicit 0 must pass, so
            # isdigit. Burn thresholds are floats (>0).
            **({"autoscale": True} if stream_auto else {}),
            **({"autoscale_min": amn} if (amn := int(
                extra.get("autoscale_min", 0) or 0)) > 0 else {}),
            **({"autoscale_max": int(v)} if (v := str(
                extra.get("autoscale_max", "")).strip()).isdigit()
               else {}),
            **({"autoscale_burn_out": abo} if (abo := float(
                extra.get("autoscale_burn_out", 0) or 0)) > 0 else {}),
            **({"autoscale_burn_in": abi} if (abi := float(
                extra.get("autoscale_burn_in", 0) or 0)) > 0 else {}),
            **({"autoscale_dwell_ms": adw} if (adw := int(
                extra.get("autoscale_dwell_ms", 0) or 0)) > 0 else {}),
            **({"autoscale_cooldown_ms": acd} if (acd := int(
                extra.get("autoscale_cooldown_ms", 0) or 0)) > 0 else {}),
            # predictive weight prefetch / streaming load (ISSUE 19)
            **({"weight_prefetch": True} if stream_load else {}),
            # federated KV stream timing (ISSUE 20, formerly hardcoded):
            # peer cooldown / negative-cache TTL / connect timeout.
            # Explicit 0 is meaningful (no cooldown / no negative
            # cache), so isdigit passes it through.
            **({"kv_stream_cooldown_ms": int(v)} if (v := str(
                extra.get("kv_stream_cooldown_ms", "")).strip()).isdigit()
               else {}),
            **({"kv_stream_negcache_ms": int(v)} if (v := str(
                extra.get("kv_stream_negcache_ms", "")).strip()).isdigit()
               else {}),
            **({"kv_stream_connect_timeout_ms": cto} if (cto := int(
                extra.get("kv_stream_connect_timeout_ms", 0) or 0)) > 0
               else {}),
            # cluster control plane (ISSUE 20): host placement + the
            # failure-detector / retry schedule knobs
            **({"cluster_mode": cm} if (cm := str(
                extra.get("cluster_mode", "") or "").strip().lower()) in
               ("inproc", "process") else {}),
            **({"cluster_heartbeat_ms": chb} if (chb := int(
                extra.get("cluster_heartbeat_ms", 0) or 0)) > 0 else {}),
            **({"cluster_suspect_ms": csu} if (csu := int(
                extra.get("cluster_suspect_ms", 0) or 0)) > 0 else {}),
            **({"cluster_dead_ms": cde} if (cde := int(
                extra.get("cluster_dead_ms", 0) or 0)) > 0 else {}),
            **({"cluster_rpc_timeout_ms": crt} if (crt := int(
                extra.get("cluster_rpc_timeout_ms", 0) or 0)) > 0 else {}),
            **({"cluster_rpc_retries": int(v)} if (v := str(
                extra.get("cluster_rpc_retries", "")).strip()).isdigit()
               else {}),
            **({"cluster_rpc_backoff_ms": crb} if (crb := int(
                extra.get("cluster_rpc_backoff_ms", 0) or 0)) > 0 else {}),
        )
        # chaos harness: a faults=... model option arms the in-process
        # fault table (same spec format as the LOCALAI_FAULTS env var,
        # ';'-separated because the options wire splits on commas)
        if extra.get("faults"):
            from localai_tpu.services.faults import FAULTS

            FAULTS.configure(str(extra["faults"]))
        draft = None
        if request.draft_model:
            ddir = request.draft_model
            if request.model_path and not os.path.isabs(ddir):
                ddir = os.path.join(request.model_path, ddir)
            dgguf = weights.find_gguf(ddir)
            if dgguf is not None:
                from localai_tpu.engine import gguf as gguflib

                dcfg = dataclasses.replace(gguflib.config_from_gguf(
                    gguflib.open_gguf(dgguf)), dtype=dtype)
            else:
                dcfg = llama.LlamaConfig.from_json(
                    os.path.join(ddir, "config.json"), dtype=dtype)
            dparams = weights.load_llama_params(
                ddir, dcfg, mesh=mesh, dtype=dtype,
                quantize=request.quantization or
                ("int8" if request.dtype == "int8" else ""))
            draft = (dcfg, dparams)

        self.model_cfg = cfg
        self.model_path = request.model_path or os.path.dirname(model_dir)
        # engine replica pool (ISSUE 14): engines=N>1 builds an EnginePool
        # (shared host KV tier + cross-replica prefix index, prefix-affinity
        # routing, live migration). engines=1 (the default) constructs a
        # plain Engine — no pool object anywhere on the path, so single-
        # engine behavior stays bit-for-bit.
        n_engines = max(1, int(extra.get("engines", 1) or 1))
        if n_engines > 1 or ecfg.autoscale:
            # autoscale=1 needs the pool even at engines=1: the pool IS
            # the actuator (resize), and its build-arg stash is what lets
            # the policy add replicas later (ISSUE 19)
            from localai_tpu.engine.pool import EnginePool

            self.engine = EnginePool.build(
                cfg, params, self.tokenizer, ecfg, engines=n_engines,
                mesh=mesh, draft=draft, family=family)
        else:
            self.engine = eng.Engine(cfg, params, self.tokenizer, ecfg,
                                     mesh=mesh, draft=draft, family=family)
        # compile the whole serving surface before accepting traffic (a cold
        # compile mid-request stalls every active slot for 20-40s); skippable
        # for tests that only care about wiring
        self.engine.start(
            precompile=os.environ.get("LOCALAI_PRECOMPILE", "1") != "0")
        # cross-host KV federation (ISSUE 17): kv_serve=1|host:port makes
        # this host's KV tier network-addressable (peers stream chain
        # entries out of it); kv_peers=host:port|host:port attaches the
        # federated tier so a local host-store miss consults peers before
        # falling back to re-prefill. Both absent (the default) leaves
        # the single-host path untouched.
        self.kv_server = None
        self.kv_fed = None
        kv_serve = str(extra.get("kv_serve", "") or "").strip()
        serve_on = kv_serve.lower() not in ("", "0", "false", "off", "no")
        kv_peers = [a.strip() for a in
                    str(extra.get("kv_peers", "") or "").split("|")
                    if a.strip()]
        if serve_on or kv_peers:
            if n_engines > 1:
                store, index = (self.engine._shared.store,
                                self.engine._shared.index)
            else:
                store, index = self.engine._hstore, None
            if store is None:
                log.warning("kv_serve/kv_peers ignored: no host KV "
                               "tier (kv_offload=0 or a non-paged layout)")
            else:
                if serve_on:
                    from localai_tpu.services.kv_wire import KVWireServer

                    bind, port = "127.0.0.1", 0
                    if ":" in kv_serve:
                        b, _, p = kv_serve.rpartition(":")
                        bind, port = b, int(p)
                    self.kv_server = KVWireServer(
                        store, index=index,
                        host_id=int(extra.get("kv_host_id", 0) or 0),
                        bind=bind, port=port)
                    log.info("kv wire serving at %s",
                                self.kv_server.start())
                if kv_peers:
                    from localai_tpu.engine.kv_stream import (FederatedKV,
                                                              KVStreamClient)

                    self.kv_fed = FederatedKV(store, [
                        KVStreamClient(
                            a, store.scope, store.page_size,
                            timeout_s=ecfg.kv_stream_connect_timeout_ms
                            / 1e3,
                            cooldown_s=ecfg.kv_stream_cooldown_ms / 1e3)
                        for a in kv_peers],
                        neg_ttl_s=ecfg.kv_stream_negcache_ms / 1e3,
                    ).attach()
                    log.info("kv federated tier attached: %d peer(s)",
                                len(kv_peers))
        self._embed = request.embeddings

        # multimodal projector (LLaVA-style vision tower; reference injects
        # CLIP embeddings at [img-N] placeholders, grpc-server.cpp:1157-1180)
        self.vision = None
        self.vision_cfg = None
        if request.mmproj:
            from localai_tpu.models import vision

            vdir = request.mmproj
            if request.model_path and not os.path.isabs(vdir):
                vdir = os.path.join(request.model_path, vdir)
            self.vision_cfg = vision.VisionConfig.from_json(
                os.path.join(vdir, "config.json"), proj_dim=cfg.hidden_size)
            self.vision = vision.load_params(vdir, self.vision_cfg)

    # ---- inference ----

    def _expand_media(self, opts: pb.PredictOptions):
        """Tokenize the prompt around [img-N]/[vid-N] placeholders and
        compute injection positions + projected embeddings: images one
        CLIP pass each; videos as uniformly sampled frames through the
        same tower (reference vLLM video semantics,
        backend/python/vllm/backend.py:208-236)."""
        import base64
        import re

        from localai_tpu.models import vision

        n_frames = int(os.environ.get("LOCALAI_VIDEO_FRAMES", "4"))
        pieces = re.split(r"(\[img-\d+\]|\[vid-\d+\])", opts.prompt)
        ids: list = []
        positions: list = []
        vectors: list = []
        pad = getattr(self.tokenizer, "pad_token_id", None) or 0

        def inject(img_bytes: bytes):
            emb = vision.embed_image(self.vision, self.vision_cfg, img_bytes)
            for v in emb:
                positions.append(len(ids))
                vectors.append(v)
                ids.append(pad)

        for piece in pieces:
            mi = re.fullmatch(r"\[img-(\d+)\]", piece)
            mv = re.fullmatch(r"\[vid-(\d+)\]", piece)
            if mi and int(mi.group(1)) < len(opts.images):
                inject(base64.b64decode(opts.images[int(mi.group(1))]))
            elif mv and int(mv.group(1)) < len(opts.videos):
                vid = base64.b64decode(opts.videos[int(mv.group(1))])
                for frame in vision.sample_video_frames(vid, n_frames):
                    inject(frame)
            elif piece:
                ids.extend(self.tokenizer.encode(
                    piece, add_special_tokens=not ids))
        import numpy as np

        return ids, positions, (np.stack(vectors) if vectors else None)

    def _build_request(self, opts: pb.PredictOptions, context=None):
        from localai_tpu.engine.engine import GenRequest

        # per-request hints ride invocation metadata (the compiled
        # descriptor cannot grow PredictOptions fields — same constraint
        # as the localai-retry-after trailing metadata): the priority
        # class (ISSUE 10) and the cross-process trace id (ISSUE 12).
        # Guarded with getattr: in-process callers pass bare context
        # fakes. An empty priority -> the engine applies the model
        # default; an empty trace id falls back to the correlation_id
        # proto field, keeping older cores traceable.
        priority = ""
        trace_id = ""
        meta_fn = getattr(context, "invocation_metadata", None)
        if meta_fn is not None:
            for key, value in meta_fn() or ():
                if key == "localai-priority":
                    priority = str(value)
                elif key == "localai-trace-id":
                    trace_id = str(value)

        # media parts the backend cannot consume are a loud error, never a
        # silent drop (VERDICT r4 #6): the HTTP layer 400s these first;
        # this is the backstop for direct gRPC clients
        if opts.audios:
            raise ValueError(
                "audio content parts are not consumable by the LLM "
                "backend; use the transcription endpoint for speech input")
        if (opts.images or opts.videos) and self.vision is None:
            raise ValueError(
                "image/video content parts require a vision-capable model "
                "(set mmproj in the model config)")
        mm_positions: list = []
        mm_vectors = None
        if (opts.images or opts.videos) and self.vision is not None \
                and not opts.prompt_ids:
            ids, mm_positions, mm_vectors = self._expand_media(opts)
        elif opts.prompt_ids:
            ids = list(opts.prompt_ids)
        else:
            ids = self.tokenizer.encode(opts.prompt)
        cache_path = opts.prompt_cache_path
        if cache_path and not os.path.isabs(cache_path):
            base = os.path.join(self.model_path or ".", "prompt_cache")
            os.makedirs(base, exist_ok=True)
            cache_path = os.path.join(base, cache_path)
        return GenRequest(
            prompt_ids=ids,
            params=_sampling_from_predict(opts),
            max_new_tokens=opts.max_tokens or 256,
            stop_sequences=list(opts.stop_sequences),
            ignore_eos=opts.ignore_eos,
            grammar=opts.grammar,
            mm_positions=mm_positions,
            mm_vectors=mm_vectors,
            request_id=trace_id or opts.correlation_id or "",
            prompt_cache_path=cache_path,
            prompt_cache_ro=opts.prompt_cache_ro,
            prompt_cache_all=opts.prompt_cache_all,
            priority=priority,
        )

    def Predict(self, request: pb.PredictOptions, context) -> pb.Reply:
        self._require_ready(context)
        req = self._build_request(request, context)
        text, events = self.engine.generate_text(req)
        last = events[-1] if events else None
        if last is not None and last.error:
            _abort_event(context, last)
        if request.echo:
            text = request.prompt + text
        return pb.Reply(
            message=text.encode("utf-8"),
            tokens=last.completion_tokens if last else 0,
            prompt_tokens=last.prompt_tokens if last else 0,
            finish_reason=(last.finish_reason or "") if last else "",
            timing_prompt_processing=(last.timings or {}).get("prefill_ms", 0.0) if last else 0.0,
            timing_token_generation=(last.timings or {}).get("decode_tokens_per_s", 0.0) if last else 0.0,
        )

    def PredictStream(self, request: pb.PredictOptions, context):
        self._require_ready(context)
        req = self._build_request(request, context)
        out = self.engine.submit(req)
        while True:
            ev = out.get()
            if ev is None:
                return
            if not context.is_active():
                # client cancelled: reference parity is TASK_TYPE_CANCEL
                # (utils.hpp:53-56); here -> cancel the slot
                self.engine.cancel(req.request_id)
                return
            if ev.error:
                _abort_event(context, ev)
            yield pb.Reply(
                message=ev.text.encode("utf-8"),
                token_id=ev.token_id,
                logprob=ev.logprob,
                # burst-coalesced chunks: every member token (engine emits
                # one event per slot per decode burst)
                token_ids=ev.token_ids or ([ev.token_id] if ev.token_id >= 0 else []),
                logprobs=ev.logprobs or ([ev.logprob] if ev.token_id >= 0 else []),
                tokens=ev.completion_tokens,
                prompt_tokens=ev.prompt_tokens,
                finish_reason=ev.finish_reason or "",
            )

    def Embedding(self, request: pb.PredictOptions, context) -> pb.EmbeddingResult:
        self._require_ready(context)
        if not hasattr(self.engine, "embed"):
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "model not loaded for embeddings")
        vec = self.engine.embed(request.prompt)
        return pb.EmbeddingResult(embeddings=[float(x) for x in vec])

    def TokenizeString(self, request: pb.PredictOptions, context) -> pb.TokenizationResponse:
        if self.tokenizer is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        ids = self.tokenizer.encode(request.prompt)
        return pb.TokenizationResponse(length=len(ids), tokens=ids)

    # ---- observability ----

    def Status(self, request, context) -> pb.StatusResponse:
        breakdown = {}
        total = 0
        try:
            import resource

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
            breakdown["rss"] = rss
            total = rss
        except Exception:
            pass
        state = self._state
        if state == pb.StatusResponse.READY and self.engine and self.engine.num_active > 0:
            state = pb.StatusResponse.BUSY
        return pb.StatusResponse(
            state=state, memory=pb.MemoryUsageData(total=total, breakdown=breakdown)
        )

    def GetMetrics(self, request, context) -> pb.MetricsResponse:
        if not self.engine:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        m = self.engine.metrics()
        if getattr(self, "weight_stream_stats", None):
            m["weight_stream"] = self.weight_stream_stats
        # the engine's FULL stats dict (kv pool occupancy, prefix-cache
        # counters, TTFT decomposition, ...) rides the proto's free
        # string field as JSON: the stubs are hand-rolled (no protoc in
        # the image), so the wire cannot grow typed fields per release —
        # the core's /metrics exporter and tokenMetrics endpoint parse
        # this instead (api/localai_routes.py)
        try:
            stats_json = json.dumps(m)
        except (TypeError, ValueError):
            stats_json = ""
        return pb.MetricsResponse(
            tokens_per_second=m["tokens_per_second_active"],
            tokens_generated=m["total_tokens_generated"],
            slots_active=m["slots_active"],
            slots_total=m["slots_total"],
            queued=m["queued"],
            uptime_s=m["uptime_s"],
            prompt_json_for_slot=stats_json,
        )

    # ---- observability side-channel (service.py METHODS additions) ----

    def GetTrace(self, request, context) -> pb.Reply:
        """Chrome trace-event JSON of the engine's span ring. The span
        data itself is process-local (the engine lives in this backend
        subprocess); the core's /debug/trace endpoint merges one of
        these per loaded model."""
        self._require_ready(context)
        try:
            payload = json.dumps(self.engine.trace_events())
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"trace export failed: {type(e).__name__}: {e}")
        return pb.Reply(message=payload.encode("utf-8"))

    def GetState(self, request, context) -> pb.Reply:
        """Live engine-state snapshot + this backend process's event-log
        ring as JSON (ISSUE 8). The core's /debug/state and /debug/events
        endpoints merge one of these per loaded model."""
        self._require_ready(context)
        from localai_tpu.services.eventlog import EVENTS

        try:
            payload = json.dumps({
                "state": self.engine.state_snapshot(),
                "events": EVENTS.events(),
                # KV lifecycle view (ISSUE 15): tier map + genealogy +
                # ledger tail for the core's /debug/kv endpoint
                "kv": self.engine.kv_debug(),
            }, default=str)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"state export failed: {type(e).__name__}: {e}")
        return pb.Reply(message=payload.encode("utf-8"))

    def Profile(self, request, context) -> pb.Result:
        """Capture a jax.profiler trace (TensorBoard/perfetto format) for
        the requested number of seconds while the engine keeps serving.
        Request rides PredictOptions.prompt as JSON {"seconds": N}."""
        self._require_ready(context)
        import tempfile
        import time as _time

        try:
            req = json.loads(request.prompt or "{}")
        except ValueError:
            req = {}
        seconds = min(60.0, max(0.1, float(req.get("seconds", 3) or 3)))
        out_dir = req.get("dir") or tempfile.mkdtemp(prefix="localai-prof-")
        try:
            import jax

            jax.profiler.start_trace(out_dir)
            _time.sleep(seconds)
            jax.profiler.stop_trace()
        except Exception as e:
            return pb.Result(
                success=False,
                message=f"profiler capture failed: {type(e).__name__}: {e}")
        return pb.Result(success=True, message=out_dir)

    def _require_ready(self, context):
        if self.engine is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")


def _apply_platform_env():
    """Honor LOCALAI_JAX_PLATFORM / LOCALAI_JAX_CPU_DEVICES before any jax use.

    The TPU plugin ignores the JAX_PLATFORMS env var, so spawned backends
    (e.g. hermetic tests forcing a CPU mesh) need an explicit config hook.
    """
    plat = os.environ.get("LOCALAI_JAX_PLATFORM")
    ndev = os.environ.get("LOCALAI_JAX_CPU_DEVICES")
    if plat or ndev:
        if ndev and not ndev.isdigit():
            raise SystemExit(
                f"LOCALAI_JAX_CPU_DEVICES must be an integer, got {ndev!r}")
        if ndev:
            # pre-jax_num_cpu_devices releases read the count from
            # XLA_FLAGS at backend init — set it before jax imports
            import re

            os.environ["XLA_FLAGS"] = (re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
                + f" --xla_force_host_platform_device_count={ndev}").strip()

        import jax

        if plat:
            jax.config.update("jax_platforms", plat)
        if ndev:
            try:
                jax.config.update("jax_num_cpu_devices", int(ndev))
            except AttributeError:
                pass  # covered by XLA_FLAGS above


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    _apply_platform_env()
    from localai_tpu.utils.jaxtools import enable_compilation_cache

    enable_compilation_cache()
    servicer = EngineServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)  # readiness marker
    server.wait_for_termination()


if __name__ == "__main__":
    main()
