"""STT backend: Whisper encoder-decoder on TPU behind AudioTranscription.

Capability parity with the reference's whisper backend (reference:
backend/go/transcribe/whisper/whisper.go:1-105 — whisper.cpp: load model,
decode audio to 16 kHz mono, emit TranscriptSegment{id, start, end, text,
tokens} plus concatenated text; language + translate knobs). Audio is
processed in whisper's native 30-second windows; each window yields one
segment with window-aligned timestamps (token-level timestamps are a
planned refinement).
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import wave

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.whisper_runner")


def read_audio(path: str, target_sr: int) -> np.ndarray:
    """Load a WAV file as float32 mono at target_sr.

    (The reference shells ffmpeg for arbitrary formats before the backend
    sees the file — core/http passes a WAV; we support PCM WAV directly.)
    """
    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        ch = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(w.getnframes())
    if width == 2:
        a = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        a = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
    elif width == 1:
        a = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width: {width}")
    if ch > 1:
        a = a.reshape(-1, ch).mean(axis=1)
    if sr != target_sr:
        from scipy.signal import resample_poly

        g = np.gcd(sr, target_sr)
        a = resample_poly(a, target_sr // g, sr // g).astype(np.float32)
    return a


class WhisperServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        self.tokenizer = None
        self.forced = None
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        try:
            from localai_tpu.models import whisper

            model_dir = request.model
            if request.model_path and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            self.cfg = whisper.WhisperConfig.from_json(
                os.path.join(model_dir, "config.json"))
            self.params = whisper.load_hf_params(model_dir, self.cfg)

            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(request.tokenizer or model_dir)
            # forced decoder prefix (sot, language, task) from generation
            # config when present — HF whisper keeps it there
            self.forced = [self.cfg.decoder_start_token_id]
            gen = os.path.join(model_dir, "generation_config.json")
            if os.path.exists(gen):
                import json

                with open(gen) as f:
                    g = json.load(f)
                ids = g.get("forced_decoder_ids") or []
                self.forced += [t for _, t in sorted(ids)]
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def AudioTranscription(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        from localai_tpu.models import whisper

        audio = read_audio(request.dst, whisper.SAMPLE_RATE)
        n = len(audio)
        segments = []
        texts = []
        with self._lock:
            for i, off in enumerate(range(0, max(n, 1), whisper.CHUNK_SAMPLES)):
                window = audio[off: off + whisper.CHUNK_SAMPLES]
                mel = whisper.log_mel(window, self.cfg.n_mels)
                toks = whisper.transcribe_window(self.params, self.cfg, mel,
                                                 forced_tokens=self.forced)
                text = self.tokenizer.decode(toks, skip_special_tokens=True)
                start_ns = int(off / whisper.SAMPLE_RATE * 1e9)
                end_ns = int(min(off + len(window), n) / whisper.SAMPLE_RATE * 1e9)
                segments.append(pb.TranscriptSegment(
                    id=i, start=start_ns, end=end_ns, text=text, tokens=toks))
                texts.append(text)
        return pb.TranscriptResult(segments=segments, text=" ".join(t for t in texts if t))

    def Status(self, request, context):
        state = pb.StatusResponse.READY if self.params is not None else \
            pb.StatusResponse.UNINITIALIZED
        return pb.StatusResponse(state=state, memory=pb.MemoryUsageData(total=0))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level.upper())
    servicer = WhisperServicer()
    server = make_server(servicer, args.addr)
    server.start()
    log.info("whisper backend listening on %s", args.addr)
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
