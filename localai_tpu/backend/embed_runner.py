"""Embeddings backend: BERT-family (or llama mean-pool) over the contract.

Parity role: reference's bert-embeddings / sentencetransformers backends
(reference: backend/go/llm/bert/bert.go, backend/python/
sentencetransformers/backend.py). Batched requests hit one jit per padded
length bucket.

Run: python -m localai_tpu.backend.embed_runner --addr 127.0.0.1:PORT
"""

from __future__ import annotations

import argparse
import logging
import os
import threading

import grpc
import numpy as np

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendServicer, make_server

log = logging.getLogger("localai_tpu.backend.embed_runner")

_BUCKETS = (16, 32, 64, 128, 256, 512)


class EmbedServicer(BackendServicer):
    def __init__(self):
        self.params = None
        self.cfg = None
        self.tokenizer = None
        self._fns = {}
        self._lock = threading.Lock()

    def LoadModel(self, request, context):
        try:
            import jax

            from localai_tpu.models import bert

            model_dir = request.model
            if request.model_path and not os.path.isabs(model_dir):
                model_dir = os.path.join(request.model_path, model_dir)
            self.cfg = bert.BertConfig.from_json(os.path.join(model_dir, "config.json"))
            self.params = bert.load_hf_params(model_dir, self.cfg)
            self._fns.clear()  # bucket fns close over cfg; invalidate on reload

            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(request.tokenizer or model_dir)
            return pb.Result(success=True, message="loaded")
        except Exception as e:
            log.exception("LoadModel failed")
            return pb.Result(success=False, message=f"{type(e).__name__}: {e}")

    def _embed_fn(self, bucket: int):
        fn = self._fns.get(bucket)
        if fn is None:
            import jax

            from localai_tpu.models import bert

            fn = jax.jit(lambda p, t, m: bert.embed(p, self.cfg, t, m))
            self._fns[bucket] = fn
        return fn

    _BATCH = 16  # padded batch per jitted call for multi-input requests

    def Embedding(self, request, context):
        if self.params is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no model loaded")
        import jax.numpy as jnp

        texts = list(request.inputs) or [request.prompt]
        encoded = [self.tokenizer.encode(t, truncation=True,
                                         max_length=self.cfg.max_position_embeddings)
                   for t in texts]
        longest = max(len(e) for e in encoded)
        bucket = next((b for b in _BUCKETS if longest <= b), _BUCKETS[-1])
        vecs = []
        with self._lock:
            for off in range(0, len(encoded), self._BATCH):
                group = encoded[off: off + self._BATCH]
                B = 1 if len(group) == 1 else self._BATCH
                tokens = np.zeros((B, bucket), np.int32)
                mask = np.zeros((B, bucket), bool)
                for b, ids in enumerate(group):
                    ids = ids[:bucket]
                    tokens[b, : len(ids)] = ids
                    mask[b, : len(ids)] = True
                out = self._embed_fn((bucket, B))(
                    self.params, jnp.asarray(tokens), jnp.asarray(mask))
                vecs.extend(np.asarray(out)[: len(group)])
        if not request.inputs:
            return pb.EmbeddingResult(
                embeddings=[float(x) for x in vecs[0]],
                batch=[pb.FloatVector(values=[float(x) for x in vecs[0]])])
        return pb.EmbeddingResult(
            embeddings=[float(x) for x in vecs[0]],
            batch=[pb.FloatVector(values=[float(x) for x in v]) for v in vecs])


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = make_server(EmbedServicer(), args.addr)
    server.start()
    print(f"gRPC Server listening at {args.addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
