"""JAX runtime helpers shared by the serving runners and benches."""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a stable directory so
    process restarts (backend respawn, bench runs, tests) deserialize
    executables instead of recompiling — a cold XLA compile costs 20-40s
    on the serving chip, and the reference's llama.cpp backend has no such
    cost to hide (model load there IS the warmup).

    Env override: LOCALAI_JAX_CACHE (empty string disables).
    """
    env = os.environ.get("LOCALAI_JAX_CACHE")
    if env == "":
        return None
    path = env or path or os.path.join(
        os.path.expanduser("~"), ".cache", "localai_tpu", "jax")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, even fast compiles — dispatch count matters more
        # than disk on the serving path
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:  # pragma: no cover - cache is best-effort
        log.exception("persistent compilation cache unavailable")
        return None
