"""Video-container decoding — the decoder-support contract in ONE place.

The HTTP layer's decodability probe (api/openai_routes.py, fail-fast 400)
and the vision backend's frame sampler (models/vision.py) must agree on
what is decodable, down to the error message. This module is jax-free so
the API process can probe without importing the compute stack.

Video chat parts follow the reference vLLM semantics — sample frames, run
each through the vision tower (/root/reference/backend/python/vllm/
backend.py:208-236). This environment has no ffmpeg-class decoder, so
coverage is the animated containers PIL decodes natively (GIF/WebP/APNG);
anything else raises ValueError, which callers MUST surface as a request
error — silently dropping a video the user asked about is the one
forbidden outcome (VERDICT r4 #6).
"""

from __future__ import annotations

import io


def _undecodable(e: Exception) -> ValueError:
    return ValueError(
        f"undecodable video container ({type(e).__name__}: {e}); "
        "supported: GIF/WebP/APNG (no ffmpeg in this build)")


def decode_video_frames(video_bytes: bytes) -> list:
    """Decode an animated-image container into RGB PIL frames, or raise
    ValueError describing why it cannot be consumed."""
    from PIL import Image, ImageSequence

    try:
        im = Image.open(io.BytesIO(video_bytes))
        frames = [f.convert("RGB").copy() for f in ImageSequence.Iterator(im)]
    except Exception as e:
        raise _undecodable(e) from None
    if not frames:
        raise ValueError("video container held no frames")
    return frames


def probe_video_b64(video_b64: str) -> None:
    """Route-level fail-fast: raise ValueError if decode_video_frames
    would reject this payload. Deliberately CHEAP — header + first frame
    only, not a full all-frames decode (the backend decodes for real and
    still errors loudly on deeper corruption). Takes base64 so the
    decode also runs off the event loop."""
    import base64

    from PIL import Image

    try:
        im = Image.open(io.BytesIO(base64.b64decode(video_b64)))
        im.load()
    except Exception as e:
        raise _undecodable(e) from None
