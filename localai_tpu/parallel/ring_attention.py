"""Ring attention: causal attention with the sequence dim sharded over the
"sp" mesh axis, K/V blocks rotating around the ring via ppermute.

The reference has NO sequence/context parallelism (SURVEY §5.7 — long
context is handled inside one llama.cpp process via self-extend and
context-shift). On TPU, long-context parity is a mesh axis: each sp rank
holds one sequence block of Q/K/V; K/V blocks hop neighbor-to-neighbor
over ICI (jax.lax.ppermute) while each rank folds every visiting block
into a numerically-stable online softmax (flash-attention style m/l/o
accumulators). Compute and memory per chip stay O(T/sp * T) and O(T/sp),
and the collectives are nearest-neighbor — the layout the ICI torus is
built for.

Causality across blocks uses absolute positions derived from the visiting
block's ring index, so the result is bit-for-bit the same math as
ops.attention.causal_attention on a single device (up to fp reduction
order).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, q_pos, k_pos, q_per_kv: int):
    """Unnormalized block attention with running-softmax stats.

    q [B, Tq, H, hd]; k/v [B, Tk, KV, hd]; q_pos [Tq], k_pos [Tk] absolute.
    Returns (scores_exp_sum l [B,KV,G,Tq], row max m [B,KV,G,Tq],
             weighted values o [B,Tq,KV,G,hd]).
    """
    B, Tq, H, hd = q.shape
    KV = k.shape[2]
    qg = q.reshape(B, Tq, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    s = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scale
    mask = k_pos[None, :] <= q_pos[:, None]                      # [Tq, Tk]
    s = jnp.where(mask[None, None, None, :, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                       # [B,KV,G,Tq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == -inf -> p would be exp(0)=1 garbage; zero them
    live = m > _NEG_INF / 2
    p = jnp.where(live[..., None], p, 0.0)
    m = jnp.where(live, m, _NEG_INF)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bkgts,bskd->btkgd", p.astype(v.dtype), v).astype(jnp.float32)
    return l, m, o


def _ring_body(q, k, v, *, axis: str, n: int, q_per_kv: int):
    """shard_map body: one sequence block per sp rank."""
    idx = jax.lax.axis_index(axis)
    B, Tb, H, hd = q.shape
    KV = k.shape[2]
    G = q_per_kv
    q_pos = idx * Tb + jnp.arange(Tb, dtype=jnp.int32)

    o = jnp.zeros((B, Tb, KV, G, hd), jnp.float32)
    m = jnp.full((B, KV, G, Tb), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, KV, G, Tb), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v
    for step in range(n):  # static ring walk, unrolled at trace time
        k_idx = (idx - step) % n
        k_pos = k_idx * Tb + jnp.arange(Tb, dtype=jnp.int32)
        bl, bm, bo = _block_attn(q, k_cur, v_cur, q_pos, k_pos, G)
        new_m = jnp.maximum(m, bm)
        live = new_m > _NEG_INF / 2
        alpha = jnp.where(live, jnp.exp(m - new_m), 0.0)
        beta = jnp.where(live, jnp.exp(bm - new_m), 0.0)
        l = l * alpha + bl * beta
        o = (o * alpha.transpose(0, 3, 1, 2)[..., None]
             + bo * beta.transpose(0, 3, 1, 2)[..., None])
        m = new_m
        if step + 1 < n:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    denom = jnp.maximum(l.transpose(0, 3, 1, 2)[..., None], 1e-20)
    out = (o / denom).reshape(B, Tb, H, hd)
    return out.astype(q.dtype)


def ring_causal_attention(q, k, v, mesh: Mesh, q_per_kv: int = 1,
                          axis: str = "sp"):
    """Causal attention with sequence sharded on ``axis``.

    q [B, T, H, hd]; k/v [B, T, KV, hd] — T must divide by mesh.shape[axis].
    Returns [B, T, H, hd] with the same sharding.
    """
    n = mesh.shape[axis]
    if n == 1:
        from localai_tpu.ops.attention import causal_attention

        valid = jnp.ones(q.shape[:2], bool)
        return causal_attention(q, k, v, valid, q_per_kv)
    spec = P(None, axis, None, None)
    body = functools.partial(_ring_body, axis=axis, n=n, q_per_kv=q_per_kv)
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-0.6 release: only the experimental alias
        from jax.experimental.shard_map import shard_map
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def sp_sharding(mesh: Mesh, axis: str = "sp") -> NamedSharding:
    """Sharding for [B, T, heads, hd] activations split on sequence."""
    return NamedSharding(mesh, P(None, axis, None, None))
