"""Device mesh management — the TPU-native replacement for the reference's
distributed stack.

The reference distributes compute by proxying tensor ops to remote
llama.cpp rpc-servers discovered over a libp2p VPN (reference:
core/p2p/p2p.go, core/cli/api/p2p.go:61-76 rewriting LLAMACPP_GRPC_SERVERS).
On TPU none of that userspace machinery is needed: topology is static and
declarative — ``jax.devices()`` enumerates the slice, a ``Mesh`` names the
axes, shardings annotate the program, and XLA inserts all-gather/
all-reduce/reduce-scatter over ICI (intra-slice) or DCN (multi-slice).

Axes (any may be size 1):
  dp  - data parallel: slots/batch divided across replicas
  tp  - tensor parallel: attention heads + MLP intermediate divided
  sp  - sequence parallel: long-context ring attention (parallel/ring_attention.py)
  pp  - pipeline parallel: layer stages (scan-over-layers split)
  ep  - expert parallel: MoE experts
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "sp", "tp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Logical parallelism plan; axis sizes multiply to the device count."""
    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    def axis_sizes(self) -> tuple:
        return (self.dp, self.pp, self.sp, self.tp, self.ep)


def make_mesh(plan: MeshPlan, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh for the plan. ``tp`` is placed on the fastest-varying
    axis so tensor-parallel collectives ride nearest-neighbor ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if plan.num_devices != len(devices):
        raise ValueError(
            f"mesh plan wants {plan.num_devices} devices (dp{plan.dp}*pp{plan.pp}"
            f"*sp{plan.sp}*tp{plan.tp}*ep{plan.ep}), have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(plan.axis_sizes())
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshPlan(), devices=jax.devices()[:1])


def plan_for_devices(n: int, want_tp: Optional[int] = None) -> MeshPlan:
    """Default plan: as much tp as divides n (serving favors tp for latency),
    remainder to dp."""
    tp = want_tp or n
    while n % tp:
        tp -= 1
    return MeshPlan(dp=n // tp, tp=tp)


def local_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def paged_cache_shardings(mesh: Mesh) -> tuple:
    """(pages, scales, page_table) NamedShardings for the paged KV layout
    (ops/kvcache.py): pages split kv heads on tp, page axis replicated;
    the page table is replicated host-managed metadata. Convenience for
    callers outside the engine (the engine derives the same via
    kvcache.paged_sharding from its 5-dim contiguous spec)."""
    from localai_tpu.parallel.sharding import (page_table_spec,
                                               paged_cache_spec)

    pages = NamedSharding(mesh, paged_cache_spec())
    scales = NamedSharding(mesh, P(*paged_cache_spec()[:-1]))
    return pages, scales, NamedSharding(mesh, page_table_spec())
