"""Multi-process (multi-host) serving: leader/follower lockstep dispatch.

In JAX's multi-controller SPMD model every process must issue IDENTICAL
programs in IDENTICAL order or cross-process collectives deadlock. The
serving engine is host-driven and timing-dependent, so multi-host serving
needs an explicit dispatch plan: process 0 (the LEADER — it owns HTTP and
the engine loop) serializes every device dispatch as a small descriptor
(opcode + the host-side arrays that parameterize it) over TCP; follower
processes replay the descriptors 1:1 against their own shards. Device
state (params, KV cache, RNG keys, the burst chain) then evolves
identically everywhere because it is the same program.

This replaces the reference's distributed "worker mode" — llama.cpp RPC
servers receiving individual tensor ops over TCP
(reference: core/cli/worker/worker_p2p.go:31-109, grpc-server.cpp:2264)
— with XLA collectives over ICI/DCN: the bus carries only tiny dispatch
descriptors (~KBs per burst), never tensors; all tensor traffic rides the
mesh inside jit.

Feature coverage (r5): grammar-constrained decoding and logit-bias ride
"bias_*" descriptors (the leader's host-side grammar automaton computes
mask rows; followers replay the device writes bit-identically via the
packed encoding below), and prompt-cache persistence rides
"cache_save"/"cache_restore" descriptors — save runs a replicated
all-gather of the slot's rows on every process (the leader alone cannot
fetch remote shards) and the leader writes the file; restore has every
process read the SAME file (multi-host deployments need the prompt-cache
dir on a shared filesystem, like the model dir) and replay the same
restore body. Still restricted (enforced at admission): multimodal
injection; speculative draft + self-extend (asserted at engine init);
fork-dedup (leader-internal, disabled when a bus is present).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import queue
from typing import Optional

import numpy as np

_NEG = -1e9  # grammar mask value (functions/grammars/automaton.py:240)


def encode_bias_row(row: np.ndarray) -> dict:
    """Pack a [V] f32 bias row for the wire: a bitmask for entries that
    are EXACTLY the grammar mask value (-1e9 — the overwhelming majority
    of a constrained row) + sparse (idx, val) for everything else nonzero.
    Reconstruction is BIT-exact: follower device state must match the
    leader's bit-for-bit or the replayed sampling programs diverge."""
    row = np.asarray(row, np.float32)
    neg = row == np.float32(_NEG)
    sparse = np.nonzero(~neg & (row != 0.0))[0].astype(np.int32)
    return {
        "n": int(row.shape[0]),
        "mask": np.packbits(neg).tobytes(),
        "idx": sparse.tobytes(),
        "val": row[sparse].tobytes(),
    }


def decode_bias_row(enc: dict) -> np.ndarray:
    n = enc["n"]
    row = np.zeros((n,), np.float32)
    neg = np.unpackbits(np.frombuffer(enc["mask"], np.uint8),
                        count=n).astype(bool)
    row[neg] = np.float32(_NEG)
    idx = np.frombuffer(enc["idx"], np.int32)
    if idx.size:
        row[idx] = np.frombuffer(enc["val"], np.float32)
    return row


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        part = sock.recv(4 - len(hdr))
        if not part:
            raise ConnectionError("bus closed")
        hdr += part
    (n,) = struct.unpack("!I", hdr)
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(min(1 << 20, n - len(buf)))
        if not part:
            raise ConnectionError("bus closed mid-message")
        buf += part
    return pickle.loads(bytes(buf))


class LeaderBus:
    """Leader side: accepts follower connections ASYNCHRONOUSLY (leader
    and followers must construct their engines concurrently — building a
    multi-process-sharded array runs internal collectives, so a blocking
    accept here would deadlock against the follower's Engine.__init__)
    and streams descriptors in dispatch order; a sender thread keeps
    serialization off the engine loop, and the queue preserves order."""

    def __init__(self, bind: str, n_followers: int):
        host, port = bind.rsplit(":", 1)
        self._srv = socket.create_server((host, int(port)))
        self._n = n_followers
        self._socks = []
        self._ready = threading.Event()
        # A dropped descriptor permanently desyncs that follower's replayed
        # program order and the next cross-process collective deadlocks the
        # whole mesh — so a failed send is FATAL, not skippable: the pump
        # marks the bus broken and the next engine send() raises, which the
        # engine loop turns into fail-active-requests + shutdown.
        self.broken = threading.Event()
        self._q: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._accept, daemon=True,
                         name="lockstep-accept").start()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="lockstep-send")
        self._thread.start()

    def _accept(self):
        for _ in range(self._n):
            conn, _ = self._srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(conn)
        self._ready.set()

    def _pump(self):
        import logging
        self._ready.wait()
        while True:
            msg = self._q.get()
            for s in self._socks:
                try:
                    _send_msg(s, msg)
                except OSError:
                    logging.getLogger(__name__).error(
                        "lockstep: descriptor send to follower failed — "
                        "bus is broken, mesh cannot continue")
                    self.broken.set()
                    return
            if msg and msg.get("op") == "shutdown":
                return

    def send(self, op: str, **payload):
        if self.broken.is_set():
            raise ConnectionError(
                "lockstep bus broken: a follower stopped receiving "
                "descriptors; the mesh program order has diverged")
        payload["op"] = op
        self._q.put(payload)

    def close(self):
        if not self.broken.is_set():
            self.send("shutdown")
        self._thread.join(timeout=10)
        for s in self._socks:
            s.close()
        self._srv.close()


class FollowerBus:
    def __init__(self, addr: str, retries: int = 120, delay: float = 0.5):
        import time

        host, port = addr.rsplit(":", 1)
        last = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection((host, int(port)))
                break
            except OSError as e:
                last = e
                time.sleep(delay)
        else:
            raise ConnectionError(f"cannot reach leader bus {addr}: {last}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def recv(self):
        return _recv_msg(self._sock)

    def close(self):
        self._sock.close()


def follow(engine, bus: "FollowerBus") -> None:
    """Replay the leader's dispatch stream on a follower process.

    ``engine`` is a NEVER-STARTED Engine built with the same config,
    params (same checkpoint, same mesh) and EngineConfig as the leader's.
    Blocks until the leader shuts down."""
    from localai_tpu.engine import sampling

    e = engine
    e.precompile()   # the leader precompiles before serving; same order
    while True:
        m = bus.recv()
        op = m["op"]
        if op == "shutdown":
            return
        if op == "seed":
            e.rng_keys = sampling.seed_slot_key(
                e.rng_keys, m["slot"],
                sampling.SamplingParamsHost(seed=int(m["seed"])),
                fallback_seed=int(m["seed"]))
        elif op == "burst":
            fn = e._get_burst_fn(m["k"], tuple(m["flags"]))
            chain = tuple(m["chain"]) if m["chain"] is not None else e._chain
            _, e.ck, e.cv, e.rng_keys, e._chain = fn(
                e.params, chain[0], e.ck, e.cv, chain[1], chain[2], chain[3],
                e.bias, e.rng_keys, m["spp"], m["active"], chain[4], m["ovp"])
        elif op == "fused":
            fn = e._get_fused_fn(m["bucket"], m["B"])
            chain = tuple(m["chain"]) if m["chain"] is not None else e._chain
            _, e.ck, e.cv, e.rng_keys, e._chain = fn(
                e.params, chain[0], e.ck, e.cv, chain[1], chain[2], chain[3],
                e.bias, e.rng_keys, m["spp"], m["active"], chain[4], m["ovp"],
                m["p_tokens"], m["p_seq"], m["p_slots"], m["p_start"])
        elif op == "final":
            fn = e._get_final_fn(m["bucket"], m["B"], m["continued"])
            _, _, e.ck, e.cv, e.rng_keys, _ = fn(
                e.params, m["tokens"], m["seq_len"], e.ck, e.cv,
                m["slots_v"], m["start_v"], m["ring"], m["ring_pos"],
                e.bias, e.rng_keys, m["spp"], m["mu"])
        elif op == "chunk":
            fn = e._get_chunk_fn(m["bucket"])
            e.ck, e.cv = fn(e.params, m["tokens"], m["seq_len"], e.ck, e.cv,
                            m["slot"], m["start"])
        elif op == "bias_rows":
            # grammar mask / combined bias rows: same batched scatter as
            # the leader's _flush_grammar_bias
            import jax.numpy as jnp

            rows = np.stack([decode_bias_row(r) for r in m["rows"]])
            e.bias = e.bias.at[np.asarray(m["slots"], np.int32)].set(
                jnp.asarray(rows))
        elif op == "bias_sparse":
            # plain logit_bias admission write — replay the identical op
            # sequence (engine.py _start_request logit_bias branch)
            e.bias = sampling.set_slot_logit_bias(
                e.bias, m["slot"],
                sampling.SamplingParamsHost(logit_bias=dict(m["pairs"])))
        elif op == "bias_clear":
            e.bias = e.bias.at[m["slot"]].set(0.0)
        elif op == "cache_save":
            # replicated all-gather of the slot's rows: a COLLECTIVE, so
            # every process must issue it; only the leader writes the file
            e._get_cache_export_fn(m["n2"])(e.ck, e.cv, np.int32(m["slot"]))
        elif op == "cache_restore":
            # every process reads the SAME cache file (shared filesystem)
            # and replays the same restore body with identical inputs.
            # The leader has ALREADY issued its restore program, so the
            # follower MUST issue the same program no matter what — a
            # raise here kills follow() and deadlocks the mesh on the
            # next collective over what is only an optimization.
            import time as _time

            kfull = vfull = ctoks = None
            for attempt in range(3):
                kfull, vfull, ctoks = e._load_prompt_cache_rows(
                    m["path"], m["m"])
                if kfull is not None:
                    break
                _time.sleep(0.05 * (attempt + 1))  # transient FS read
            if ctoks is not None and ctoks[:m["m"]] != m["tokens"]:
                # a DIFFERENT file version than the leader validated:
                # not transient — a mis-deployed (non-shared) prompt
                # cache dir. Still mesh-fatal by design, but loudly.
                raise RuntimeError(
                    f"lockstep cache_restore: follower's view of "
                    f"{m['path']} diverges from the leader's — shared "
                    f"filesystem required for prompt-cache in multi-host")
            if kfull is None:
                # degrade to no-reuse for THIS request: replay the same
                # restore program with zero rows. This process's shard
                # of the reused prefix is zeros (degraded output for one
                # request), but the program sequence stays identical and
                # the mesh lives.
                import logging

                logging.getLogger(__name__).error(
                    "lockstep cache_restore: unreadable %s after retries; "
                    "replaying with zero rows (degraded prefix reuse for "
                    "one request)", m["path"])
                import numpy as _np

                from localai_tpu.ops import kvcache as _kv

                L, _, C, KV, hd = _kv.shape(e.ck)
                kfull = _np.zeros((L, C, KV, hd), _np.float16)
                vfull = _np.zeros((L, C, KV, hd), _np.float16)
            e.ck, e.cv = e._get_restore_fn()(
                e.ck, e.cv, kfull, vfull, m["slot"], m["m"])
        elif op == "reset":
            e._reset_device_state()
        else:
            raise ValueError(f"unknown lockstep op {op!r}")


class PrebuiltEngineServicer:
    """An EngineServicer over an engine that already exists in-process
    (the leader's distributed engine) — registered as an EMBEDDED backend
    so the real HTTP app serves it (the reference's in-process backend
    seam: pkg/grpc/embed.go Provide, used by local-store)."""

    def __new__(cls, engine, tokenizer, model_cfg):
        from localai_tpu.backend import contract_pb2 as pb
        from localai_tpu.backend.runner import EngineServicer

        class _Impl(EngineServicer):
            def __init__(self):
                super().__init__()
                self.engine = engine
                self.tokenizer = tokenizer
                self.model_cfg = model_cfg
                self._state = pb.StatusResponse.READY

            def LoadModel(self, request, context):
                return pb.Result(success=True, message="prebuilt (lockstep)")

        return _Impl()
